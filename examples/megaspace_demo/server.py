"""Megaspace demo — ONE logical space spanning the device mesh.

The flagship capability beyond the reference: where GoWorld caps a
space's population by pinning it to one process (the example policy is
100 avatars/space, ``SpaceService.go:14``), a megaspace tiles the XZ
plane over TPU cores — AOI sees across tile borders through halo
exchange, and entities that walk over a border migrate between cores
inside the step (no EnterSpace, no dispatcher hop). The ini sets
``megaspace = true`` with a ``4x2`` tile layout over 8 devices and the
fused behavior-tree NPC kernel (monsters chase players, avoid crowds,
wander — ``models/behavior_tree.py``).

Run on a CPU rig:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m goworld_tpu start examples/megaspace_demo
"""

import goworld_tpu as gw


@gw.register_space("World", megaspace=True)
class World(gw.Space):
    def OnGameReady(self):
        pass


@gw.register_entity("Monster")
class Monster(gw.Entity):
    ATTRS = {"hp": "allclients"}


@gw.register_entity("Avatar")
class Avatar(gw.Entity):
    ATTRS = {"name": "allclients"}

    def OnClientConnected(self):
        self.attrs["name"] = "hero"


@gw.register_entity("Account")
class Account(gw.Entity):
    ATTRS = {"status": "client"}

    def Login_Client(self, name):
        avatar = gw.create_entity(
            "Avatar", space=gw.world()._mega_space, pos=(400.0, 0.0, 200.0)
        )
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


@gw.on_deployment_ready
def setup():
    import numpy as np

    w = gw.world()
    sp = gw.create_space("World")
    w._mega_space = sp
    rng = np.random.default_rng(7)
    for _ in range(200):
        gw.create_entity(
            "Monster", space=sp, moving=True,
            pos=(rng.uniform(0, 800), 0.0, rng.uniform(0, 400)),
            attrs={"hp": 100},
        )


if __name__ == "__main__":
    gw.run()
