"""Multihost demo, CLI-managed: ``[game1] mesh_processes = 2`` makes the
ops CLI run THIS script as two SPMD controller processes over one
8-device mesh (4 local devices each) — one logical game spanning both.

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python -m goworld_tpu start examples/multihost_demo

World population happens in ``@gw.on_boot`` — the SPMD-safe hook that
runs before the network/ticks, so every controller creates the identical
world (``on_deployment_ready`` fires at different wall instants per
controller and must not mutate a multi-controller world). The login
Avatar is placed on the SECOND controller's half of the world: its
create/sync traffic reaches the client through the dispatcher wire
(cross-controller client visibility).

See ``run_cluster.py`` for the same topology driven programmatically.
"""

import numpy as np

import goworld_tpu as gw


@gw.register_space("World", megaspace=True)
class World(gw.Space):
    pass


@gw.register_entity("Monster")
class Monster(gw.Entity):
    ATTRS = {"hp": "allclients"}


@gw.register_entity("Avatar")
class Avatar(gw.Entity):
    ATTRS = {"name": "allclients"}


@gw.register_entity("Account")
class Account(gw.Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"

    def Login_Client(self, name):
        # look the megaspace up by type, never via an attribute only
        # on_boot sets: after a -restore boot (reload OR watchdog crash
        # recovery) on_boot is skipped and the space came from the
        # snapshot
        world = gw.world()
        sp = next(
            s for s in world.spaces.values() if s.type_name == "World"
        )
        # x=600 of the 800-wide world = the second controller's half
        avatar = gw.create_entity(
            "Avatar", space=sp, pos=(600.0, 0.0, 200.0),
        )
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


@gw.on_boot
def populate(world):
    sp = world.create_space("World")
    world._mega_space = sp
    rng = np.random.default_rng(7)   # same seed => identical world on
    for _ in range(400):             # every controller (SPMD contract)
        world.create_entity(
            "Monster", space=sp, moving=True,
            pos=(float(rng.uniform(0, 800)), 0.0,
                 float(rng.uniform(0, 400))),
            attrs={"hp": 100},
        )


if __name__ == "__main__":
    gw.run()
