"""Multihost demo — ONE world, TWO controller processes, real cluster.

The capability the reference scales to with its dispatcher TCP star
(``engine/dispatchercluster``): multiple machines serving one game
world. Here the ENTITY plane is a single SPMD megaspace over a global
``jax.distributed`` mesh (each controller owns half the tiles; AOI
halos / tile migration ride XLA collectives, over DCN between hosts),
while the HOST plane is the same dispatcher/gate wire protocol as the
reference — one dispatcher, one GameServer per controller, one gate
per controller. Dispatcher-originated world mutations (client logins,
client RPCs, position syncs) replicate to every controller through the
per-tick mutation log (``net/game.py``), so any client on any gate
sees entities on any controller's tiles.

Run (one machine, two OS processes, 4 virtual CPU devices each):

    python examples/multihost_demo/run_cluster.py

It forms the cluster, logs a bot in through controller 0's gate, walks
an NPC on controller 1's half of the world, prints what the bot's
mirror sees, and shuts down. On real multi-host TPU deployments, start
one controller per host with the same script arguments (coordinator
address, process id) and point gates at the shared dispatcher.
"""

import asyncio
import json
import os
import subprocess
import sys
import socket
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TICKS = 500
TICK_SLEEP = 0.02


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def controller_main(pid: int, coord_port: int, disp_port: int) -> int:
    """One controller: half the mesh + a GameServer + its own gate."""
    from goworld_tpu.parallel.multihost import global_mesh, init_distributed
    init_distributed(f"127.0.0.1:{coord_port}", num_processes=2,
                     process_id=pid)

    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.net.botclient import BotClient
    from goworld_tpu.net.dispatcher import DispatcherService
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.gate import GateService
    from goworld_tpu.ops.aoi import GridSpec

    n_dev, tile_w, radius = 8, 100.0, 12.0
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=16, cell_cap=32, row_block=64),
        npc_speed=0.0,
        enter_cap=512, leave_cap=512, sync_cap=512, input_cap=64,
    )
    world = World(cfg, n_spaces=n_dev, mesh=global_mesh(),
                  megaspace=True, halo_cap=16, migrate_cap=8)

    box = {}

    class Mega(Space):
        pass

    class Account(Entity):
        def Login_Client(self, name):
            # the avatar lands on tile 4+ — the OTHER controller's half
            avatar = self.world.create_entity(
                "Avatar", space=box["sp"], pos=(430.0, 0.0, 50.0),
            )
            avatar.attrs["name"] = name
            self.give_client_to(avatar)
            self.destroy()

    class Avatar(Entity):
        ATTRS = {"name": "allclients"}

    class Npc(Entity):
        pass

    world.registry.register("Mega", Mega, is_space=True, megaspace=True)
    world.register_entity("Account", Account)
    world.register_entity("Avatar", Avatar)
    world.register_entity("Npc", Npc)
    world.create_nil_space()
    box["sp"] = world.create_space("Mega")
    npc = world.create_entity("Npc", space=box["sp"],
                              pos=(433.0, 0.0, 50.0), eid="npc_demo_0000__x")

    ready = threading.Event()
    gate_port = {}
    loop_box = {}

    def services() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_box["loop"] = loop

        async def boot():
            if pid == 0:
                d = DispatcherService(1, "127.0.0.1", disp_port,
                                      desired_games=2, desired_gates=2)
                asyncio.ensure_future(d.serve())
                await d.started.wait()
            else:
                await asyncio.sleep(1.0)
            g = GateService(pid + 1, "127.0.0.1", 0,
                            [("127.0.0.1", disp_port)],
                            position_sync_interval_ms=20,
                            exit_on_dispatcher_loss=False)
            asyncio.ensure_future(g.serve())
            await g.started.wait()
            gate_port["p"] = g.bound_port

        loop.run_until_complete(boot())
        ready.set()
        loop.run_forever()

    threading.Thread(target=services, daemon=True).start()
    assert ready.wait(30)

    gs = GameServer(pid + 1, world, [("127.0.0.1", disp_port)],
                    boot_entity="Account")
    gs.start_network()

    bot = None
    if pid == 0:
        bot = BotClient("127.0.0.1", gate_port["p"], strict=True,
                        nosync=True)

        async def bot_script():
            while not gs.ready_event.is_set():
                await asyncio.sleep(0.1)
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 90)
                bot.call_server("Login_Client", "demo-hero")
                t0 = time.time()
                while time.time() - t0 < 90:
                    me = bot.entities.get("npc_demo_0000__x")
                    if me is not None and bot.sync_count >= 3:
                        break
                    await asyncio.sleep(0.05)
            finally:
                recv.cancel()
        fut = asyncio.run_coroutine_threadsafe(bot_script(),
                                               loop_box["loop"])

    x = 433.0
    for _ in range(TICKS):
        gs.pump()
        if any(e.type_name == "Avatar" and not e.destroyed
               for e in world.entities.values()) and x < 440.0:
            x += 0.25
            npc.set_position((x, 0.0, 50.0))
        gs.tick()
        time.sleep(TICK_SLEEP)

    if pid == 0:
        fut.result(timeout=30)
        me = bot.entities.get("npc_demo_0000__x")
        print(json.dumps({
            "bot_player": bot.player.type_name if bot.player else None,
            "npc_mirrored": me is not None,
            "npc_mirror_x": me.pos[0] if me else None,
            "syncs": bot.sync_count,
            "strict_errors": bot.errors,
        }))
    return 0


def main() -> int:
    if len(sys.argv) > 1:                 # child controller
        return controller_main(int(sys.argv[1]), int(sys.argv[2]),
                               int(sys.argv[3]))
    coord, disp = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             str(pid), str(coord), str(disp)],
            cwd=REPO, env=env,
        )
        for pid in (0, 1)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait(timeout=600)
    return rc


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
