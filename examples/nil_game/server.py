"""Minimal game — boot entities only (reference ``examples/nil_game``,
``nil_game.go:1-13``)."""

import goworld_tpu as gw


@gw.register_entity("Account")
class Account(gw.Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"


if __name__ == "__main__":
    gw.run()
