"""unity_demo — client-driven players + AI monsters (reference
``examples/unity_demo``): Player with client-synced position and shooting,
Monster with a 100 ms AI timer hunting players over ``interested_in``, and
a space that auto-summons monsters (``MySpace.go:24-47``)."""

import random

import goworld_tpu as gw


@gw.register_entity("Account")
class Account(gw.Entity):
    def Login_Client(self, name):
        player = self.world.create_entity("Player")
        player.attrs["name"] = name
        self.give_client_to(player)
        self.destroy()


@gw.register_entity("Player")
class Player(gw.Entity):
    ATTRS = {
        "name": "allclients",
        "hp": "allclients hot:0",
        "action": "allclients",
    }

    def OnAttrsReady(self):
        self.attrs["hp"] = 100
        self.attrs["action"] = "idle"

    def OnClientConnected(self):
        space = getattr(self.world, "_demo_space", None) \
            or self.world.nil_space
        self.enter_space(
            space.id, (random.uniform(30, 70), 0.0, random.uniform(30, 70))
        )

    def Shoot_Client(self, target_id):
        """Reference ``Player.go``: validate the target is visible, then
        damage it."""
        if target_id not in self.interested_in:
            return
        self.call(target_id, "TakeDamage", 10, self.id)

    def TakeDamage(self, amount, _attacker):
        hp = max(0, self.attrs.get("hp", 100) - amount)
        self.attrs["hp"] = hp
        if hp <= 0:
            self.attrs["action"] = "death"


@gw.register_entity("Monster")
class Monster(gw.Entity):
    ATTRS = {"hp": "allclients hot:0"}

    def OnEnterSpace(self):
        self.attrs["hp"] = 100
        self.set_moving(True)
        # reference Monster.go:32-100 — 100 ms AI tick
        self.add_timer(0.1, "AITick")

    def AITick(self):
        target = None
        for eid in self.interested_in:
            e = self.world.entities.get(eid)
            if e is not None and e.type_name == "Player" \
                    and e.attrs.get("hp", 0) > 0:
                target = e
                break
        if target is not None:
            self.call(target.id, "TakeDamage", 5, self.id)

    def TakeDamage(self, amount, attacker):
        hp = max(0, self.attrs.get("hp", 100) - amount)
        self.attrs["hp"] = hp
        if hp <= 0:
            self.set_moving(False)
            self.call_all_clients("OnDie", self.id)
            self.add_callback(2.0, "DoDestroy")

    def DoDestroy(self):
        self.destroy()


@gw.register_space("MySpace")
class MySpace(gw.Space):
    def OnSpaceCreated(self):
        # auto-summon monsters (reference MySpace.go:24-47)
        for _ in range(3):
            self.world.create_entity(
                "Monster", space=self,
                pos=(random.uniform(20, 80), 0.0, random.uniform(20, 80)),
            )


@gw.on_deployment_ready
def _create_demo_space():
    w = gw.world()
    if getattr(w, "_demo_space", None) is None:
        w._demo_space = gw.create_space("MySpace")


if __name__ == "__main__":
    gw.run()
