"""test_game — the integration workload (reference ``examples/test_game``).

Mirrors the reference's cast: Account (login flow, ``Account.go:37-70``),
Avatar (persistent player, ``Avatar.go:25-37``), Monster (AI npc), MySpace,
OnlineService, SpaceService (3 shards, fills spaces up to a cap,
``SpaceService.go:14,26-39``), MailService, and the pubsub ext service.
"""

import random

import goworld_tpu as gw
from goworld_tpu.ext.pubsub import PublishSubscribeService

_MAX_AVATARS_PER_SPACE = 100  # reference SpaceService.go:14


@gw.register_entity("Account")
class Account(gw.Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "ready"

    def Login_Client(self, name):
        """kvdb-mapped login: one Avatar per name (reference
        ``Account.go:37-70``)."""

        def got(avatar_id, _err=None):
            if self.destroyed or self.client is None:
                return
            if avatar_id:
                existing = self.world.entities.get(avatar_id)
                if existing is not None:
                    self._handoff(existing)
                    return
                avatar = self.world.create_entity("Avatar", eid=avatar_id)
                avatar.attrs["name"] = name
                self._handoff(avatar)
            else:
                avatar = self.world.create_entity("Avatar")
                avatar.attrs["name"] = name
                try:
                    gw.kvdb_put(f"avatarOf/{name}", avatar.id,
                                lambda *_: None)
                except RuntimeError:
                    pass
                self._handoff(avatar)

        try:
            gw.kvdb_get(f"avatarOf/{name}", got)
        except RuntimeError:  # standalone World without run(): no kvdb
            got(None)

    def _handoff(self, avatar):
        self.give_client_to(avatar)
        self.destroy()


@gw.register_entity("Avatar", persistent=True)
class Avatar(gw.Entity):
    ATTRS = {
        "name": "allclients persistent",
        "level": "client persistent",
        "exp": "client persistent",
        "hp": "allclients",
    }

    def OnAttrsReady(self):
        self.attrs.setdefault("level", 1)
        self.attrs.setdefault("exp", 0)
        self.attrs["hp"] = 100

    def OnClientConnected(self):
        self.call_service("OnlineService", "NotifyOnline", self.id,
                          shard_key=self.id)
        self.call_service("SpaceService", "EnterSpace", self.id,
                          shard_key=self.id)

    def OnClientDisconnected(self):
        self.call_service("OnlineService", "NotifyOffline", self.id,
                          shard_key=self.id)
        self.destroy()

    def DoEnterSpace(self, space_id):
        """Called back by SpaceService with the assigned space."""
        self.enter_space(
            space_id,
            (random.uniform(10, 90), 0.0, random.uniform(10, 90)),
        )

    def Say_Client(self, text):
        self.call_all_clients("OnSay", self.id, text)

    def SendMail_Client(self, to_name, text):
        self.call_service("MailService", "SendMail",
                          self.attrs.get("name"), to_name, text,
                          shard_key=to_name)

    def Subscribe_Client(self, subject):
        # shard by the subject's first segment so a wildcard subscription
        # ("news.*") and the publishes it matches ("news.tpu") always land
        # on the same Pubsub shard
        self.call_service("Pubsub", "Subscribe", self.id, subject,
                          shard_key=subject.split(".")[0])

    def Publish_Client(self, subject, *args):
        self.call_service("Pubsub", "Publish", subject, *args,
                          shard_key=subject.split(".")[0])

    def OnPublish(self, subject, *args):
        # relay pubsub deliveries to the owning client
        self.call_client("OnPublish", subject, *args)

    def OnGainExp(self, amount):
        self.attrs["exp"] = self.attrs.get("exp", 0) + amount
        if self.attrs["exp"] >= self.attrs.get("level", 1) * 10:
            self.attrs["exp"] = 0
            self.attrs["level"] = self.attrs.get("level", 1) + 1
        self.save()


@gw.register_entity("AOITester", aoi_distance=100.0)
class AOITester(gw.Entity):
    """Reference ``examples/test_game/AOITester.go``: an entity type with
    its OWN AOI distance (SetUseAOI(true, 100)) — exercises the per-type
    ``aoi_distance`` honored by the grid sweep's watch_radius path."""

    ATTRS = {"name": "allclients"}


@gw.register_entity("Monster")
class Monster(gw.Entity):
    ATTRS = {"hp": "allclients hot:0"}

    def OnEnterSpace(self):
        self.attrs["hp"] = 50
        self.set_moving(True)  # device-side random walk
        self.add_timer(0.1, "AITick")  # reference Monster 100ms AI timer

    def AITick(self):
        # attack a random nearby avatar (InterestedIn sweep like the
        # reference unity_demo Monster)
        for eid in self.interested_in:
            e = self.world.entities.get(eid)
            if e is not None and e.type_name == "Avatar":
                self.call(eid, "OnGainExp", 1)
                break


@gw.register_space("MySpace")
class MySpace(gw.Space):
    ATTRS = {"kind": "allclients"}

    def OnSpaceCreated(self):
        for _ in range(4):
            self.world.create_entity(
                "Monster", space=self,
                pos=(random.uniform(20, 80), 0.0, random.uniform(20, 80)),
            )


@gw.register_service("OnlineService", shard_count=3)
class OnlineService(gw.Entity):
    def OnInit(self):
        self.online: set[str] = set()

    def NotifyOnline(self, avatar_id):
        self.online.add(avatar_id)

    def NotifyOffline(self, avatar_id):
        self.online.discard(avatar_id)


@gw.register_service("SpaceService", shard_count=3)
class SpaceService(gw.Entity):
    """Assigns avatars to spaces, filling the fullest below the cap
    (reference ``SpaceService.go:26-39``)."""

    def OnInit(self):
        self.space_loads: dict[str, int] = {}

    def EnterSpace(self, avatar_id):
        best, best_n = None, -1
        for sid, n in self.space_loads.items():
            if n < _MAX_AVATARS_PER_SPACE and n > best_n \
                    and sid in self.world.spaces:
                best, best_n = sid, n
        if best is None:
            sp = self.world.create_space("MySpace", kind=1)
            best = sp.id
            self.space_loads[best] = 0
        self.space_loads[best] += 1
        self.call(avatar_id, "DoEnterSpace", best)


@gw.register_service("MailService", shard_count=1)
class MailService(gw.Entity):
    def OnInit(self):
        self.mails: dict[str, list] = {}

    def SendMail(self, from_name, to_name, text):
        self.mails.setdefault(to_name, []).append([from_name, text])


gw.register_service("Pubsub", PublishSubscribeService, shard_count=3)


if __name__ == "__main__":
    gw.run()
