"""chatroom_demo — filtered-clients broadcast (reference
``examples/chatroom_demo``): avatars join numbered rooms via a client
filter prop and chat via ``call_filtered_clients``."""

import goworld_tpu as gw


@gw.register_entity("Account")
class Account(gw.Entity):
    def Login_Client(self, name):
        avatar = self.world.create_entity("ChatAvatar")
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


@gw.register_entity("ChatAvatar")
class ChatAvatar(gw.Entity):
    ATTRS = {"name": "allclients", "room": "client"}

    def OnClientConnected(self):
        self.EnterRoom_Client(1)

    def EnterRoom_Client(self, room):
        """Reference ``Avatar.go:33-50``: SetClientFilterProp("chatroom", n)."""
        self.attrs["room"] = int(room)
        self.set_client_filter_prop("chatroom", str(int(room)))

    def Say_Client(self, text):
        self.call_filtered_clients(
            "chatroom", "=", str(self.attrs.get("room", 1)),
            "OnRoomSay", self.attrs.get("name"), text,
        )

    def Shout_Client(self, text):
        # all rooms >= 1, i.e. everyone
        self.call_filtered_clients(
            "chatroom", ">=", "0", "OnRoomSay",
            self.attrs.get("name"), f"(shout) {text}",
        )


if __name__ == "__main__":
    gw.run()
