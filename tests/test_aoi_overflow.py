"""AOI cap-overflow observability (VERDICT r3 #5).

The reference go-aoi sweep is exact at any density (``Space.go:244-252``);
the TPU grid sweep's ``k``/``cell_cap`` bounds degrade to nearest-k under
overflow — which must NEVER happen silently. These tests pin the device
gauges (``ops.aoi`` ``with_stats``), the World's opmon exposure + alarm,
and recovery: a mass teleport into one cell fires the alarm that tick and
interest is exact again the tick after the crowd disperses.

Gauge semantics under test: ``demand`` is measured within the candidate
pool, so when cells overflow it is a lower bound — but then
``over_cap_cells`` fires instead (occupancy comes from an unclipped
bincount). "Both gauges zero" <=> the sweep was exact; there is no silent
case.
"""

import logging

import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec, grid_neighbors_flags
from goworld_tpu.utils import opmon

# fused rows run the r6 Pallas kernel in interpret mode on CPU
FUSED = pytest.param("fused", marks=pytest.mark.pallas)


class Npc(Entity):
    pass


class Arena(Space):
    pass


def _stats(spec, pos, alive=None):
    import jax.numpy as jnp

    n = pos.shape[0]
    alive = np.ones(n, bool) if alive is None else alive
    _, cnt, _, stats = grid_neighbors_flags(
        spec, jnp.asarray(np.asarray(pos, np.float32)),
        jnp.asarray(alive),
        flag_bits=jnp.zeros(n, jnp.int32), with_stats=True,
    )
    return int(cnt.max()), tuple(map(int, stats))


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_k_overflow_gauges(sweep_impl):
    """Cells hold everyone (cell_cap=8 >= 6) but k=4 < demand 5: every
    clustered row reports truncation."""
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=8, row_block=64, sweep_impl=sweep_impl)
    pos = np.array(
        [[5.0 + 0.1 * i, 0.0, 5.0] for i in range(6)]
        + [[85.0, 0.0, 85.0], [55.0, 0.0, 15.0]],
        np.float32,
    )
    cnt_max, (demand_max, over_k, cell_max, over_cap) = _stats(spec, pos)
    assert demand_max == 5          # each clustered row sees 5 others
    assert over_k == 6              # all six truncated to nearest-4
    assert cell_max == 6
    assert over_cap == 0
    assert cnt_max == 4             # lists really were capped at k


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_cell_overflow_gauges(sweep_impl):
    """cell_cap=4 < occupancy 6: the cell gauge fires even where the
    pool-clipped demand cannot exceed k (the lower-bound case the
    module docstring documents)."""
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=4, row_block=64, sweep_impl=sweep_impl)
    pos = np.array(
        [[5.0 + 0.1 * i, 0.0, 5.0] for i in range(6)]
        + [[85.0, 0.0, 85.0], [55.0, 0.0, 15.0]],
        np.float32,
    )
    _, (_, _, cell_max, over_cap) = _stats(spec, pos)
    assert cell_max == 6            # occupancy bincount is UNclipped
    assert over_cap == 1


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_exact_tick_reports_all_zero(sweep_impl):
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=4, row_block=64, sweep_impl=sweep_impl)
    spread = np.array(
        [[5.0 + 11.0 * i, 0.0, 5.0 + 9.0 * (i % 7)] for i in range(8)],
        np.float32,
    )
    _, (_, over_k, _, over_cap) = _stats(spec, spread)
    assert over_k == 0 and over_cap == 0


def test_mass_teleport_alarms_and_recovers(caplog):
    """~10K entities teleported into ONE cell: the overflow alarm fires
    that same tick (cell gauge + log with re-provisioning guidance), and
    after dispersing the gauges are zero again with exact interest."""
    n = 10_000
    cap = 16384
    cfg = WorldConfig(
        capacity=cap,
        # k=16 / cell_cap=8: zero gauges at the spread density (~0.7
        # entities per 10x10 cell), unmistakable overflow when 10K land
        # in one cell
        grid=GridSpec(radius=10.0, extent_x=1200.0, extent_z=1200.0,
                      k=16, cell_cap=8, row_block=cap),
        npc_speed=0.0, turn_prob=0.0,
        enter_cap=131072, leave_cap=131072, sync_cap=4096,
        input_cap=cap,
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Npc", Npc)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    arena = w.create_space("Arena")
    rng = np.random.default_rng(11)
    home = rng.uniform(20, 1180, (n, 2)).astype(np.float32)
    ents = [
        w.create_entity("Npc", space=arena,
                        pos=(float(home[i, 0]), 0.0, float(home[i, 1])))
        for i in range(n)
    ]
    w.tick()
    assert w.op_stats["aoi_over_cap_cells"] == 0
    assert w.op_stats["aoi_over_k_rows"] == 0

    for e in ents:  # the mass teleport: everyone into one cell
        e.set_position((605.0, 0.0, 605.0))
    with caplog.at_level(logging.WARNING):
        w.tick()
    assert w.op_stats["aoi_over_cap_cells"] >= 1
    assert w.op_stats["aoi_cell_max"] == n  # occupancy gauge is exact
    assert opmon.vars()["aoi_over_cap_cells"] >= 1
    assert any("AOI cap overflow" in r.message for r in caplog.records)
    assert any("aoi_k" in r.message for r in caplog.records)  # guidance

    for i, e in enumerate(ents):  # disperse back home
        e.set_position((float(home[i, 0]), 0.0, float(home[i, 1])))
    w.tick()
    assert w.op_stats["aoi_over_cap_cells"] == 0
    assert w.op_stats["aoi_over_k_rows"] == 0
    # interest is exact again: a probe pair within radius sees each other
    a = w.create_entity("Npc", space=arena, pos=(300.0, 0.0, 300.0))
    b = w.create_entity("Npc", space=arena, pos=(303.0, 0.0, 303.0))
    w.tick()
    assert b.id in a.interested_in
    assert a.id in b.interested_in
