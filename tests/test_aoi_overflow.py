"""AOI cap-overflow observability (VERDICT r3 #5).

The reference go-aoi sweep is exact at any density (``Space.go:244-252``);
the TPU grid sweep's ``k``/``cell_cap`` bounds degrade to nearest-k under
overflow — which must NEVER happen silently. These tests pin the device
gauges (``ops.aoi`` ``with_stats``), the World's opmon exposure + alarm,
and recovery: a mass teleport into one cell fires the alarm that tick and
interest is exact again the tick after the crowd disperses.

Gauge semantics under test: ``demand`` is measured within the candidate
pool, so when cells overflow it is a lower bound — but then
``over_cap_cells`` fires instead (occupancy comes from an unclipped
bincount). "Both gauges zero" <=> the sweep was exact; there is no silent
case.
"""

import logging

import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec, grid_neighbors_flags
from goworld_tpu.utils import opmon

# fused rows run the r6 Pallas kernel in interpret mode on CPU
FUSED = pytest.param("fused", marks=pytest.mark.pallas)


class Npc(Entity):
    pass


class Arena(Space):
    pass


def _stats(spec, pos, alive=None):
    import jax.numpy as jnp

    n = pos.shape[0]
    alive = np.ones(n, bool) if alive is None else alive
    _, cnt, _, stats = grid_neighbors_flags(
        spec, jnp.asarray(np.asarray(pos, np.float32)),
        jnp.asarray(alive),
        flag_bits=jnp.zeros(n, jnp.int32), with_stats=True,
    )
    return int(cnt.max()), tuple(map(int, stats))


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_k_overflow_gauges(sweep_impl):
    """Cells hold everyone (cell_cap=8 >= 6) but k=4 < demand 5: every
    clustered row reports truncation."""
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=8, row_block=64, sweep_impl=sweep_impl)
    pos = np.array(
        [[5.0 + 0.1 * i, 0.0, 5.0] for i in range(6)]
        + [[85.0, 0.0, 85.0], [55.0, 0.0, 15.0]],
        np.float32,
    )
    cnt_max, (demand_max, over_k, cell_max, over_cap) = _stats(spec, pos)
    assert demand_max == 5          # each clustered row sees 5 others
    assert over_k == 6              # all six truncated to nearest-4
    assert cell_max == 6
    assert over_cap == 0
    assert cnt_max == 4             # lists really were capped at k


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_cell_overflow_gauges(sweep_impl):
    """cell_cap=4 < occupancy 6: the cell gauge fires even where the
    pool-clipped demand cannot exceed k (the lower-bound case the
    module docstring documents)."""
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=4, row_block=64, sweep_impl=sweep_impl)
    pos = np.array(
        [[5.0 + 0.1 * i, 0.0, 5.0] for i in range(6)]
        + [[85.0, 0.0, 85.0], [55.0, 0.0, 15.0]],
        np.float32,
    )
    _, (_, _, cell_max, over_cap) = _stats(spec, pos)
    assert cell_max == 6            # occupancy bincount is UNclipped
    assert over_cap == 1


@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "shift",
                                        FUSED])
def test_exact_tick_reports_all_zero(sweep_impl):
    spec = GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                    k=4, cell_cap=4, row_block=64, sweep_impl=sweep_impl)
    spread = np.array(
        [[5.0 + 11.0 * i, 0.0, 5.0 + 9.0 * (i % 7)] for i in range(8)],
        np.float32,
    )
    _, (_, over_k, _, over_cap) = _stats(spec, spread)
    assert over_k == 0 and over_cap == 0


@pytest.mark.scenarios
def test_hotspot_scenario_overflow_monotone_and_survivors_exact():
    """ISSUE 7 regression, scenario-driven: hotspot convergence (pure
    radial contraction: jitter 0, near-static attractor) must raise the
    ``aoi_over_k_rows``/``over_cap_cells`` gauges MONOTONICALLY as the
    crowd piles up, while interest stays oracle-exact for the
    survivors — rows the overflow cannot have touched (demand <= k and
    no overflowing cell anywhere in their 3x3 candidate window)."""
    import dataclasses

    from goworld_tpu.ops.aoi import neighbors_oracle
    from goworld_tpu.scenarios.spec import get_scenario

    n, ext = 60, 120.0
    spec = dataclasses.replace(
        get_scenario("hotspot"), hotspot_jitter=0.0,
        attractor_period=10**6,          # static target: no orbit drift
    )
    cfg = WorldConfig(
        capacity=n,
        # k=10 / cell_cap=10: exact at the spread density (demand max 9
        # at this seed), then over_k fires as rows crowd past 10 and
        # over_cap as cells pass 10
        grid=GridSpec(radius=12.0, extent_x=ext, extent_z=ext,
                      k=10, cell_cap=10, row_block=n),
        npc_speed=90.0,                  # 3 units/tick at 30 Hz
        scenario=spec,
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Npc", Npc)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    arena = w.create_space("Arena")
    rng = np.random.default_rng(17)
    for i in range(n):
        w.create_entity("Npc", space=arena,
                        pos=(float(rng.uniform(1, ext - 1)), 0.0,
                             float(rng.uniform(1, ext - 1))),
                        moving=True)

    over_k_series, over_cap_series = [], []
    survivors_checked = 0
    owner = w._slot_owner[0]
    cs = cfg.grid.cell_size
    for t in range(40):
        w.tick()
        over_k_series.append(int(w.op_stats["aoi_over_k_rows"]))
        over_cap_series.append(int(w.op_stats["aoi_over_cap_cells"]))

        pos = np.asarray(w.state.pos[0])
        alive = np.asarray(w.state.alive[0])
        oracle = neighbors_oracle(pos, alive, cfg.grid.radius)
        # overflowing cells, from the same geometry the sweep bins with
        cell = (np.floor(pos[:, 0] / cs).astype(int),
                np.floor(pos[:, 2] / cs).astype(int))
        occ: dict = {}
        for i in np.nonzero(alive)[0]:
            key = (int(cell[0][i]), int(cell[1][i]))
            occ[key] = occ.get(key, 0) + 1
        hot = {c for c, o in occ.items() if o > cfg.grid.cell_cap}
        for slot, eid in owner.items():
            if not alive[slot] or len(oracle[slot]) > cfg.grid.k:
                continue
            cx, cz = int(cell[0][slot]), int(cell[1][slot])
            if any((cx + dx, cz + dz) in hot
                   for dx in (-1, 0, 1) for dz in (-1, 0, 1)):
                continue                 # overflow may have eaten a
            e = w.entities[eid]          # candidate: not a survivor
            want = {owner[j] for j in oracle[slot] if j in owner}
            assert e.interested_in == want, (
                f"tick {t}: survivor {eid} diverged while over_k="
                f"{over_k_series[-1]} over_cap={over_cap_series[-1]}"
            )
            survivors_checked += 1

    # demand growth is monotone under pure radial contraction — so the
    # gauges are too (every wobble would mean a silent-degradation
    # window the bench blocks could miss)
    assert over_k_series == sorted(over_k_series), over_k_series
    assert over_cap_series == sorted(over_cap_series), over_cap_series
    assert over_k_series[0] == 0 and over_cap_series[0] == 0
    # converged: most rows over k — not n: once the blob's cells blow
    # cell_cap, demand is measured within the CLIPPED pool (the
    # lower-bound semantics the module docstring pins), and over_cap
    # is what fires for the rest
    assert over_k_series[-1] >= n // 2
    assert over_cap_series[-1] >= 1      # and the blob cell(s) over cap
    assert survivors_checked > 50        # the exactness claim had teeth


def test_mass_teleport_alarms_and_recovers(caplog):
    """~10K entities teleported into ONE cell: the overflow alarm fires
    that same tick (cell gauge + log with re-provisioning guidance), and
    after dispersing the gauges are zero again with exact interest."""
    n = 10_000
    cap = 16384
    cfg = WorldConfig(
        capacity=cap,
        # k=16 / cell_cap=8: zero gauges at the spread density (~0.7
        # entities per 10x10 cell), unmistakable overflow when 10K land
        # in one cell
        grid=GridSpec(radius=10.0, extent_x=1200.0, extent_z=1200.0,
                      k=16, cell_cap=8, row_block=cap),
        npc_speed=0.0, turn_prob=0.0,
        enter_cap=131072, leave_cap=131072, sync_cap=4096,
        input_cap=cap,
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Npc", Npc)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    arena = w.create_space("Arena")
    rng = np.random.default_rng(11)
    home = rng.uniform(20, 1180, (n, 2)).astype(np.float32)
    ents = [
        w.create_entity("Npc", space=arena,
                        pos=(float(home[i, 0]), 0.0, float(home[i, 1])))
        for i in range(n)
    ]
    w.tick()
    assert w.op_stats["aoi_over_cap_cells"] == 0
    assert w.op_stats["aoi_over_k_rows"] == 0

    for e in ents:  # the mass teleport: everyone into one cell
        e.set_position((605.0, 0.0, 605.0))
    with caplog.at_level(logging.WARNING):
        w.tick()
    assert w.op_stats["aoi_over_cap_cells"] >= 1
    assert w.op_stats["aoi_cell_max"] == n  # occupancy gauge is exact
    assert opmon.vars()["aoi_over_cap_cells"] >= 1
    assert any("AOI cap overflow" in r.message for r in caplog.records)
    assert any("aoi_k" in r.message for r in caplog.records)  # guidance

    for i, e in enumerate(ents):  # disperse back home
        e.set_position((float(home[i, 0]), 0.0, float(home[i, 1])))
    w.tick()
    assert w.op_stats["aoi_over_cap_cells"] == 0
    assert w.op_stats["aoi_over_k_rows"] == 0
    # interest is exact again: a probe pair within radius sees each other
    a = w.create_entity("Npc", space=arena, pos=(300.0, 0.0, 300.0))
    b = w.create_entity("Npc", space=arena, pos=(303.0, 0.0, 303.0))
    w.tick()
    assert b.id in a.interested_in
    assert a.id in b.interested_in
