"""Overload-protection plane (ISSUE 4): admission control, prioritized
backpressure, tick-deadline load shedding, circuit breakers.

Unit tier: traffic classification, governor ladder + hysteresis with
the seeded-replay determinism contract (equal signal streams ->
byte-identical transition logs), class-priority queues, token bucket,
circuit breaker (incl. the kvdb fail-fast integration), gate
downstream bounds + kick, game ingress shedding.

Live tier (``overload`` marker): a standalone cluster under a seeded
delay-fault schedule takes a bot flood of slow RPCs + position spam;
the ladder must engage (>= SHEDDING), only cheap classes may shed
(``shed_total`` for critical/rpc stays zero), the serve loop survives,
and the process returns to NORMAL after the flood stops.
"""

import asyncio
import json
import threading
import time
import types
import urllib.request
from random import Random

import pytest

from goworld_tpu.net import proto
from goworld_tpu.net.packet import Packet, new_packet
from goworld_tpu.utils import faults, metrics, overload


@pytest.fixture(autouse=True)
def _clean_planes():
    yield
    faults.uninstall()


# =======================================================================
# traffic classification
# =======================================================================
def test_classify_traffic_classes():
    # the PROCESS-level control plane is critical
    for mt in (proto.MT_SET_GAME_ID, proto.MT_NOTIFY_CLIENT_CONNECTED,
               proto.MT_KVREG_REGISTER, proto.MT_START_FREEZE_GAME,
               proto.MT_NOTIFY_DEPLOYMENT_READY):
        assert overload.classify(mt) == overload.CLASS_CRITICAL, mt
    # RPC (both directions), the client event bundle, AND the
    # entity-addressed order-sensitive control (migration legs,
    # disconnects) — never shed, and FIFO with each other so an ack /
    # disconnect can never overtake the same entity's queued calls
    for mt in (proto.MT_CALL_ENTITY_METHOD,
               proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT,
               proto.MT_CLIENT_EVENTS_BATCH,
               proto.MT_CREATE_ENTITY_ON_CLIENT,
               proto.MT_REAL_MIGRATE, proto.MT_MIGRATE_REQUEST_ACK,
               proto.MT_CANCEL_MIGRATE,
               proto.MT_NOTIFY_CLIENT_DISCONNECTED):
        assert overload.classify(mt) == overload.CLASS_RPC, mt
    # server->client sync fan-out above client-origin event streams
    assert overload.classify(proto.MT_SYNC_POSITION_YAW_ON_CLIENTS) \
        == overload.CLASS_SYNC
    assert overload.classify(proto.MT_SYNC_POSITION_YAW_FROM_CLIENT) \
        == overload.CLASS_EVENTS
    assert overload.classify(proto.MT_CLIENT_SYNC_POSITION_YAW) \
        == overload.CLASS_EVENTS
    assert overload.classify(proto.MT_HEARTBEAT) == overload.CLASS_NOISE
    # unknown msgtypes fail SAFE: never shed
    assert overload.classify(31337) == overload.CLASS_RPC


def test_shed_floor_never_reaches_critical_or_rpc():
    g = overload.OverloadGovernor("floor-test")
    for state in (overload.NORMAL, overload.DEGRADED,
                  overload.SHEDDING, overload.REJECTING):
        g.state = state
        assert not g.should_shed(overload.CLASS_CRITICAL)
        assert not g.should_shed(overload.CLASS_RPC)
    g.state = overload.NORMAL
    assert not g.should_shed(overload.CLASS_NOISE)
    g.state = overload.DEGRADED
    assert not g.should_shed(overload.CLASS_EVENTS)
    g.state = overload.SHEDDING
    assert g.should_shed(overload.CLASS_EVENTS)
    assert g.should_shed(overload.CLASS_NOISE)
    assert not g.should_shed(overload.CLASS_SYNC)
    g.state = overload.REJECTING
    assert g.should_shed(overload.CLASS_SYNC)


# =======================================================================
# governor: ladder + hysteresis + deterministic replay
# =======================================================================
def test_ladder_escalates_one_rung_per_threshold():
    g = overload.OverloadGovernor("ladder", up_ticks=3, down_ticks=4)
    # two pressured ticks are not enough
    g.observe(2.0)
    g.observe(2.0)
    assert g.state == overload.NORMAL
    g.observe(2.0)
    assert g.state == overload.DEGRADED
    # the score resets per rung: three more to climb again
    for _ in range(3):
        g.observe(2.0)
    assert g.state == overload.SHEDDING
    for _ in range(3):
        g.observe(2.0)
    assert g.state == overload.REJECTING
    # REJECTING is the top rung
    for _ in range(10):
        g.observe(10.0)
    assert g.state == overload.REJECTING
    # rungs never skip: transitions are adjacent pairs
    for _, frm, to, _r in g.transitions:
        assert abs(to - frm) == 1


def test_hysteresis_band_holds_the_rung():
    g = overload.OverloadGovernor("hyst", up_ticks=2, down_ticks=3,
                                  latency_ratio=1.5)
    g.observe(2.0)
    g.observe(2.0)
    assert g.state == overload.DEGRADED
    # in-band observations (between calm and pressured) hold the rung
    # forever — no flapping in the gray zone
    for _ in range(50):
        g.observe(1.2)
    assert g.state == overload.DEGRADED
    assert len(g.transitions) == 1
    # a calm run shorter than down_ticks is reset by one pressured tick
    g.observe(0.1)
    g.observe(0.1)
    g.observe(2.0)
    g.observe(2.0)
    assert g.state == overload.SHEDDING
    # sustained calm descends one rung per down_ticks run
    for _ in range(3):
        g.observe(0.1)
    assert g.state == overload.DEGRADED
    for _ in range(3):
        g.observe(0.1)
    assert g.state == overload.NORMAL


def test_severe_pressure_climbs_faster():
    slow = overload.OverloadGovernor("sev-a", up_ticks=8)
    fast = overload.OverloadGovernor("sev-b", up_ticks=8)
    for _ in range(2):
        slow.observe(1.6)   # plain pressure: 2/8 — still NORMAL
        fast.observe(20.0)  # severe: 2 * boost(4) = 8/8 — DEGRADED
    assert slow.state == overload.NORMAL
    assert fast.state == overload.DEGRADED


def _seeded_signals(seed: int, n: int = 2000):
    """A reproducible synthetic load trace: calm / pressured / severe
    stretches chosen by a seeded RNG (the same shape a seeded fault
    schedule produces in a live run)."""
    rng = Random(seed)
    out = []
    while len(out) < n:
        kind = rng.random()
        run = rng.randrange(1, 40)
        for _ in range(run):
            if kind < 0.4:
                out.append((rng.uniform(0.0, 0.5), 0.0, 0.0, 0.0))
            elif kind < 0.8:
                out.append((rng.uniform(1.6, 2.5),
                            rng.uniform(0.0, 3.0), 0.0, 0.0))
            else:
                out.append((rng.uniform(4.0, 30.0),
                            rng.uniform(8.0, 20.0),
                            rng.uniform(0.5, 1.0), 0.0))
    return out[:n]


def test_equal_seeds_produce_identical_transition_logs():
    """ISSUE 4 acceptance: the ladder is a pure function of the
    observation stream — equal seeds replay byte-identical transition
    logs; a different seed diverges."""
    a = overload.OverloadGovernor("replay-a", up_ticks=4, down_ticks=8)
    b = overload.OverloadGovernor("replay-b", up_ticks=4, down_ticks=8)
    c = overload.OverloadGovernor("replay-c", up_ticks=4, down_ticks=8)
    for sig in _seeded_signals(42):
        a.observe(*sig)
        b.observe(*sig)
    for sig in _seeded_signals(43):
        c.observe(*sig)
    assert a.log_lines() == b.log_lines()
    assert a.log_lines()          # the trace does transition
    assert a.log_lines() != c.log_lines()


# =======================================================================
# class-priority queues
# =======================================================================
def test_class_queues_priority_order_and_bounds():
    q = overload.ClassQueues(bounds={overload.CLASS_EVENTS: 2},
                             stage="t_q")
    assert q.offer(overload.CLASS_EVENTS, "e1")
    assert q.offer(overload.CLASS_SYNC, "s1")
    assert q.offer(overload.CLASS_CRITICAL, "c1")
    assert q.offer(overload.CLASS_RPC, "r1")
    assert q.offer(overload.CLASS_EVENTS, "e2")
    # events bound = 2: the third is dropped AND counted
    drop0 = overload.shed_counter(overload.CLASS_EVENTS, "t_q").value
    assert not q.offer(overload.CLASS_EVENTS, "e3")
    assert overload.shed_counter(
        overload.CLASS_EVENTS, "t_q").value == drop0 + 1
    assert q.qsize() == 5
    # drain: strict priority order, FIFO within a class
    assert q.drain() == ["c1", "r1", "s1", "e1", "e2"]
    assert q.qsize() == 0
    with pytest.raises(IndexError):
        q.pop()


# =======================================================================
# token bucket (deterministic under an injected clock)
# =======================================================================
def test_token_bucket_rate_and_burst():
    now = [0.0]
    b = overload.TokenBucket(10.0, burst=5.0, clock=lambda: now[0])
    assert all(b.allow() for _ in range(5))   # burst drains
    assert not b.allow()                      # empty
    now[0] += 0.1                             # refills 1 token
    assert b.allow()
    assert not b.allow()
    now[0] += 10.0                            # refill caps at burst
    assert all(b.allow() for _ in range(5))
    assert not b.allow()
    # disabled bucket always allows
    free = overload.TokenBucket(0.0, clock=lambda: now[0])
    assert all(free.allow() for _ in range(100))


# =======================================================================
# circuit breaker
# =======================================================================
def test_circuit_breaker_opens_half_opens_and_recovers():
    now = [0.0]
    br = overload.CircuitBreaker("t_br", failure_threshold=3,
                                 reset_timeout=5.0,
                                 clock=lambda: now[0])
    assert br.allow()
    for _ in range(3):
        br.record_failure()
    assert br.state == br.OPEN
    assert not br.allow()                 # fail fast while open
    now[0] += 5.0
    assert br.allow()                     # the half-open probe
    assert br.state == br.HALF_OPEN
    assert not br.allow()                 # only ONE probe per window
    br.record_failure()                   # probe failed -> re-open
    assert br.state == br.OPEN
    assert not br.allow()
    now[0] += 5.0
    assert br.allow()
    br.record_success()                   # probe succeeded -> closed
    assert br.state == br.CLOSED
    assert br.allow()


def test_circuit_breaker_unsettled_probe_cannot_wedge():
    """A probe whose caller died without record_success/record_failure
    (e.g. a non-transient exception path) must not pin the breaker
    HALF_OPEN forever: another probe is granted after a reset window."""
    now = [0.0]
    br = overload.CircuitBreaker("t_wedge", failure_threshold=1,
                                 reset_timeout=5.0,
                                 clock=lambda: now[0])
    br.record_failure()
    now[0] += 5.0
    assert br.allow()          # probe granted... and never settled
    assert not br.allow()
    now[0] += 5.0
    assert br.allow()          # the slot frees after another window
    br.record_success()
    assert br.state == br.CLOSED


def test_kvdb_circuit_open_fails_fast_without_retries():
    """A dead backend must stop costing 3 retry attempts per op: once
    the breaker opens, ops fail fast through the callback with
    CircuitOpenError and the backend is not touched."""
    import queue

    from goworld_tpu.kvdb import KVDB, MemoryKVDB
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    faults.plane = faults.FaultPlane(
        faults.parse_schedule("err:kvdb.get:1.0"), 7, process="t")
    faults.active = True
    posted = queue.Queue()
    kv = KVDB(MemoryKVDB(), AsyncWorkers(posted.put))
    kv.breaker = overload.CircuitBreaker(
        "t_kvdb", failure_threshold=2, reset_timeout=60.0)

    def run_get():
        out = []
        kv.get("k", lambda v, e: out.append((v, e)))
        deadline = time.time() + 10
        while not out and time.time() < deadline:
            try:
                posted.get(timeout=0.1)()
            except queue.Empty:
                pass
        assert out, "kvdb get callback never fired"
        return out[0]

    # first op: 3 failing attempts -> breaker (threshold 2) opens
    _, err = run_get()
    assert isinstance(err, faults.InjectedFaultError)
    assert kv.breaker.state == kv.breaker.OPEN
    # second op: rejected fast, no backend attempt (trials frozen)
    trials_before = faults.plane.rules[0].trials
    rejected0 = kv._m_circuit_rejected.value
    _, err = run_get()
    assert isinstance(err, overload.CircuitOpenError)
    assert faults.plane.rules[0].trials == trials_before
    assert kv._m_circuit_rejected.value == rejected0 + 1


# =======================================================================
# gate: downstream bounds + kick, admission refusal
# =======================================================================
class _FakeTransport:
    def __init__(self):
        self.buffered = 0

    def get_write_buffer_size(self):
        return self.buffered


class _FakeWriter:
    def __init__(self):
        self.transport = _FakeTransport()
        self.chunks = []

    def write(self, data):
        self.chunks.append(bytes(data))

    def close(self):
        pass

    async def wait_closed(self):
        pass

    def get_extra_info(self, _):
        return None


def _mk_gate(**kw):
    from goworld_tpu.net.gate import GateService

    return GateService(1, "127.0.0.1", 0, [("127.0.0.1", 1)],
                       exit_on_dispatcher_loss=False, **kw)


def test_gate_downstream_bound_drops_and_kicks():
    from goworld_tpu.net.gate import ClientProxy
    from goworld_tpu.net.packet import PacketConnection

    async def scenario():
        gate = _mk_gate(downstream_max_bytes=100,
                        downstream_kick_secs=0.05)
        w = _FakeWriter()
        cp = ClientProxy(PacketConnection(None, w))
        gate.clients[cp.client_id] = cp

        def pkt():
            p = new_packet(proto.MT_CLIENT_SYNC_POSITION_YAW)
            p.append_bytes(b"z" * 40)
            return p

        drop0 = gate._m_down_dropped.value
        kick0 = gate._m_kicked.value
        gate._send_to_client(cp, pkt())        # fits
        assert len(w.chunks) == 1
        w.transport.buffered = 90              # consumer stalled
        gate._send_to_client(cp, pkt())        # over budget: dropped
        assert len(w.chunks) == 1
        assert gate._m_down_dropped.value == drop0 + 1
        assert cp.down_full_since is not None
        assert cp.client_id in gate.clients    # not kicked yet
        await asyncio.sleep(0.08)              # past the kick window
        gate._send_to_client(cp, pkt())
        assert gate._m_kicked.value == kick0 + 1
        assert cp.client_id not in gate.clients  # kicked, never wedged
        # a draining buffer clears the strike (and the governor's
        # stalled-client set)
        cp2 = ClientProxy(PacketConnection(None, _FakeWriter()))
        gate.clients[cp2.client_id] = cp2
        cp2.conn.writer.transport.buffered = 90
        gate._send_to_client(cp2, pkt())
        assert cp2.down_full_since is not None
        assert cp2.client_id in gate._down_full
        cp2.conn.writer.transport.buffered = 0
        gate._send_to_client(cp2, pkt())
        assert cp2.down_full_since is None
        assert cp2.client_id not in gate._down_full
        # a correctness-critical message that cannot be buffered kicks
        # IMMEDIATELY — dropping a create_entity would silently desync
        # the client's world forever
        cp3 = ClientProxy(PacketConnection(None, _FakeWriter()))
        gate.clients[cp3.client_id] = cp3
        cp3.conn.writer.transport.buffered = 90
        crit = new_packet(proto.MT_CREATE_ENTITY_ON_CLIENT)
        crit.append_bytes(b"y" * 40)
        kick1 = gate._m_kicked.value
        gate._send_to_client(cp3, crit)
        assert gate._m_kicked.value == kick1 + 1
        assert cp3.client_id not in gate.clients

    asyncio.run(scenario())


def test_gate_refuses_handshakes_at_cap_and_in_rejecting():
    gate = _mk_gate(max_clients=1)
    assert gate._refuse_new_client() is None
    gate.clients["x" * 16] = object()
    assert "max_clients" in gate._refuse_new_client()
    gate.clients.clear()
    gate.overload.state = overload.REJECTING
    assert "REJECTING" in gate._refuse_new_client()
    gate.overload.state = overload.SHEDDING
    assert gate._refuse_new_client() is None


def test_gate_rate_limit_sheds_rpc_but_never_heartbeats():
    from goworld_tpu.net.gate import ClientProxy
    from goworld_tpu.net.packet import PacketConnection

    async def scenario():
        gate = _mk_gate(rate_limit_pps=2.0)
        w = _FakeWriter()
        cp = ClientProxy(PacketConnection(None, w))
        cp.bucket = overload.TokenBucket(2.0, burst=2.0)
        gate.clients[cp.client_id] = cp
        limited0 = overload.shed_counter(
            overload.CLASS_RPC, "gate_ratelimit").value

        def rpc():
            p = new_packet(proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT)
            p.append_entity_id("e" * 16)
            p.append_var_str("M")
            p.append_args(())
            q = Packet(bytes(p.buf))
            q.read_u16()
            return q

        for _ in range(5):
            gate._handle_client_packet(
                cp, proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT, rpc())
        assert overload.shed_counter(
            overload.CLASS_RPC, "gate_ratelimit").value >= limited0 + 3
        # heartbeats bypass the limiter entirely (liveness)
        hb0 = len(w.chunks)
        for _ in range(3):
            p = new_packet(proto.MT_HEARTBEAT)
            q = Packet(bytes(p.buf))
            q.read_u16()
            gate._handle_client_packet(cp, proto.MT_HEARTBEAT, q)
        assert len(w.chunks) == hb0 + 3

    asyncio.run(scenario())


# =======================================================================
# game: ingress shedding + priority pump
# =======================================================================
def _mk_gameserver(**kw):
    from goworld_tpu.net.game import GameServer

    world = types.SimpleNamespace(
        _multihost=False, mh_rank=0, sync_stride=1,
        entities={}, spaces={}, op_stats={},
    )
    return GameServer(99, world, [], gc_freeze_on_boot=False, **kw)


def test_game_ingress_sheds_cheap_classes_only():
    gs = _mk_gameserver()
    gs.overload.state = overload.SHEDDING
    shed0 = overload.shed_counter(
        overload.CLASS_EVENTS, "game_ingress").value

    gs._on_packet_netthread(
        0, proto.MT_SYNC_POSITION_YAW_FROM_CLIENT, Packet(b""))
    assert gs._packet_q.qsize() == 0          # shed at ingress
    assert overload.shed_counter(
        overload.CLASS_EVENTS, "game_ingress").value == shed0 + 1

    # rpc + critical always get through, even in REJECTING
    gs.overload.state = overload.REJECTING
    gs._on_packet_netthread(
        0, proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT, Packet(b""))
    gs._on_packet_netthread(0, proto.MT_NOTIFY_DEPLOYMENT_READY,
                            Packet(b""))
    gs._on_packet_netthread(0, proto.MT_REAL_MIGRATE, Packet(b""))
    assert gs._packet_q.qsize() == 3

    # the pump drains process-control first; entity-addressed traffic
    # (RPCs, migration legs) stays FIFO within the rpc class
    seen = []
    gs._handle_packet = lambda d, mt, p: seen.append(mt)
    gs.pump()
    assert seen == [proto.MT_NOTIFY_DEPLOYMENT_READY,
                    proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT,
                    proto.MT_REAL_MIGRATE]


def test_game_observe_pushes_sync_stride_into_world():
    gs = _mk_gameserver(degraded_sync_stride=4,
                        overload_up_ticks=1)
    gs._observe_overload(10.0 * gs.tick_interval, 8.0)  # severe
    assert gs.overload.state == overload.DEGRADED
    assert gs.world.sync_stride == 4
    gs.overload.state = overload.NORMAL
    gs._observe_overload(0.0, 0.0)
    assert gs.world.sync_stride == 1


def test_degraded_event_coalesce_flushes_every_nth_tick():
    gs = _mk_gameserver(degraded_event_coalesce=2)
    flushed = []
    gs._flush_events_out = lambda: flushed.append(True)
    gs.overload.state = overload.DEGRADED
    gs._flush_sync_out()           # odd phase: held
    gs._flush_sync_out()           # even phase: flushed
    assert len(flushed) == 1
    gs._flush_sync_out(force=True)  # freeze path always flushes
    assert len(flushed) == 2
    gs.overload.state = overload.NORMAL
    gs._flush_sync_out()
    assert len(flushed) == 3


# =======================================================================
# /overload endpoint
# =======================================================================
def test_debug_http_overload_endpoint():
    from goworld_tpu.utils import debug_http

    overload.register(overload.OverloadGovernor("ep-test"))
    srv = debug_http.start(0, process_name="overload-test")
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/overload", timeout=5) as r:
            snap = json.loads(r.read())
        assert snap["governors"]["ep-test"]["state"] == "NORMAL"
        assert "shed" in snap and "breakers" in snap
        assert snap["classes"]["critical"] == 0
    finally:
        srv.shutdown()
        srv.server_close()
        overload.unregister("ep-test")


# =======================================================================
# live overload smoke (the acceptance scenario; `overload` marker)
# =======================================================================
OVERLOAD_SEED = 4242


@pytest.mark.overload
def test_overload_smoke_ladder_engages_sheds_cheap_and_recovers():
    """ISSUE 4 acceptance: under a bot flood (slow RPCs + position
    spam) with seeded delay faults active, the game's ladder engages
    (>= SHEDDING), every shed packet is counted, the
    migration/persistence/RPC classes shed NOTHING, the serve loop
    never dies, and the process returns to NORMAL within a bounded
    interval after the flood stops."""
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.net.botclient import BotClient
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.standalone import ClusterHarness
    from goworld_tpu.ops.aoi import GridSpec

    class OvAccount(Entity):
        ATTRS = {"status": "client"}

        def OnClientConnected(self):
            self.attrs["status"] = "online"

        def Stress_Client(self, ms):
            # simulated expensive handler: the flood's tick-budget hog
            time.sleep(ms / 1000.0)

        def Ping_Client(self):
            self.call_client("OnPong")

    # the PR-3 fault grammar supplies the wire chaos (delay faults on
    # the client-facing edge), seeded for reproducibility
    faults.plane = faults.FaultPlane(
        faults.parse_schedule("delay:gate->dispatcher:0.5:5ms"),
        OVERLOAD_SEED, process="overload-smoke",
    )
    faults.active = True

    harness = ClusterHarness(n_dispatchers=1, n_gates=1,
                             desired_games=1)
    harness.start()
    world = World(
        WorldConfig(capacity=64, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0)),
        n_spaces=1,
    )
    world.register_entity("OvAccount", OvAccount)
    world.create_nil_space()
    gs = GameServer(
        1, world, list(harness.dispatcher_addrs),
        boot_entity="OvAccount", gc_freeze_on_boot=False,
        overload_up_ticks=3, overload_down_ticks=3,
        degraded_sync_stride=2, degraded_event_coalesce=2,
    )
    gs.start_network()
    # per-class shed baselines (the registry is process-global)
    base = {
        (cls, stage): overload.shed_counter(cls, stage).value
        for cls in range(overload.N_CLASSES)
        for stage in ("game_ingress", "game_queue", "gate_ingress",
                      "gate_ratelimit", "dispatcher_pend", "stride")
    }
    t = None
    try:
        # warm the boot compile + reach readiness on the test thread,
        # then SIZE the tick budget from the measured steady tick cost
        # — the smoke must engage the ladder on any machine speed, so
        # the "deadline" is defined relative to what this box can do
        deadline = time.monotonic() + 60
        while not gs.ready_event.is_set() \
                and time.monotonic() < deadline:
            gs.pump()
            gs.tick()
            time.sleep(0.01)
        assert gs.ready_event.is_set(), "deployment never became ready"
        costs = []
        for _ in range(8):
            t0 = time.perf_counter()
            gs.pump()
            gs.tick()
            costs.append(time.perf_counter() - t0)
        steady = sorted(costs)[len(costs) // 2]
        # idle ratio ~0.4 (calm, under the 0.9 hysteresis floor); one
        # stressed tick is ~3.9x (severe) — each climbs a full rung
        gs.tick_interval = max(0.05, 2.5 * steady)
        stress_ms = int(gs.tick_interval * 3500)

        t = threading.Thread(target=gs.serve_forever, daemon=True)
        t.start()
        assert gs.overload.state == overload.NORMAL

        peak = [overload.NORMAL]

        async def flood():
            bot = BotClient(*harness.gate_addrs[0])
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 60)
                # phase 1: slow RPCs until the ladder engages (each
                # stressed tick is 'severe': one rung per up_ticks
                # run). Paced AT the stress duration so arrival ~=
                # service — the ticks run hot (~3.5x budget) but stay
                # bounded, and the governor gets an observation per
                # tick instead of one mega-tick swallowing the clock.
                sent = 0
                deadline = time.monotonic() + 90
                while peak[0] < overload.SHEDDING \
                        and time.monotonic() < deadline:
                    bot.call_server("Stress_Client", stress_ms)
                    bot.send_position(float(sent % 7), 0.0,
                                      float(sent % 5), 0.0)
                    sent += 1
                    await asyncio.sleep(stress_ms / 1000.0 * 1.1)
                    peak[0] = max(peak[0], gs.overload.state)
                # phase 2: keep events-class traffic flowing while the
                # ladder is engaged so shedding demonstrably happens
                deadline = time.monotonic() + 30
                while gs.overload.state >= overload.SHEDDING \
                        and time.monotonic() < deadline:
                    bot.send_position(1.0, 0.0, 1.0, 0.0)
                    await asyncio.sleep(0.02)
                return sent
            finally:
                recv.cancel()
                await bot.conn.close()

        sent = harness.submit(flood()).result(timeout=240)
        assert sent >= 3, "flood never ran"
        assert peak[0] >= overload.SHEDDING, (
            f"ladder never engaged (peak {overload.STATE_NAMES[peak[0]]};"
            f" transitions {gs.overload.log_lines()})"
        )
        assert t.is_alive(), "serve loop died under the flood"

        # every shed is counted, and ONLY cheap classes shed: the
        # critical + rpc rows stay exactly at their baselines while
        # the cheap classes demonstrably dropped something
        cheap_shed = 0.0
        for (cls, stage), v0 in base.items():
            v = overload.shed_counter(cls, stage).value
            if cls in (overload.CLASS_CRITICAL, overload.CLASS_RPC):
                assert v == v0, (
                    f"{overload.CLASS_NAMES[cls]} shed at {stage}: "
                    f"{v - v0} packets"
                )
            else:
                cheap_shed += v - v0
        assert cheap_shed > 0, "ladder engaged but nothing was shed"

        # recovery: flood stopped -> NORMAL within a bounded interval
        deadline = time.monotonic() + 120
        while gs.overload.state != overload.NORMAL \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert gs.overload.state == overload.NORMAL, (
            f"never recovered: {gs.overload.log_lines()}"
        )
        assert t.is_alive()

        # the transition log walked the ladder one rung at a time
        for _, frm, to, _r in gs.overload.transitions:
            assert abs(to - frm) == 1

        # post-recovery liveness: a fresh RPC round trip completes
        async def ping():
            bot = BotClient(*harness.gate_addrs[0])
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 30)
                bot.call_server("Ping_Client")
                for _ in range(200):
                    if any(m == "OnPong" for _, m, _a in bot.rpc_log):
                        return True
                    await asyncio.sleep(0.05)
                return False
            finally:
                recv.cancel()
                await bot.conn.close()

        assert harness.submit(ping()).result(timeout=60), \
            "post-recovery RPC round trip failed"
    finally:
        gs._stop.set()
        if t is not None:
            t.join(timeout=30)
        gs.stop()
        harness.stop()


# =======================================================================
# slow tier: chaos_soak overload scenario (double-run JSON report)
# =======================================================================
@pytest.mark.overload
@pytest.mark.slow
def test_chaos_soak_overload_scenario_report(tmp_path):
    """tools/chaos_soak.py --scenario overload drives a bot flood at a
    configured msg/s against a real CLI cluster while delay faults are
    active, and must report an engaged + recovered ladder with zero
    critical/rpc sheds, in the same JSON report shape as the kill
    scenario."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    out = str(tmp_path / "overload_report.json")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_soak.py"),
         "--scenario", "overload",
         "--dir", str(tmp_path / "cluster"),
         "--seed", "77", "--flood-secs", "6", "--msg-rate", "120",
         "--out", out],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    with open(out) as f:
        report = json.load(f)
    assert report["scenario"] == "overload"
    assert report["converged"]
    assert report["engaged"] and report["returned_normal"]
    assert report["critical_shed"] == 0
