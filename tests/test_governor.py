"""Online kernel governor (goworld_tpu/autotune) — ISSUE 13.

Covers the full stack: the jax-free policy (table mapping, hysteresis,
hold bands, cooldown, regret pin, byte-identical replay — the
determinism acceptance criterion), the recommendation-key contract
(every knob name the workload-signature reducer can emit must resolve
through the accepted ``[gameN]`` set), the warm-set AOT executables
(bit-parity vs the jit path, no retrace on re-commit), the LIVE swap
(mid-churn oracle exactness on the very next tick, zero entity loss,
telemetry lane-set follow), the KernelGovernor runtime (warm-gated
commits, the regret guard, metrics counters, /governor), and the
flight-recorder ``governor_swap`` trigger.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from goworld_tpu.autotune import (
    DEFAULT_CANDIDATES,
    GovernorPolicy,
    KernelGovernor,
    WarmSet,
    candidate_overrides,
    classify_signature,
    parse_table,
    seed_table,
)
from goworld_tpu.autotune import governor as gov_mod
from goworld_tpu.autotune.policy import (
    CANDIDATE_GRID_KEYS,
    DEFAULT_TABLE,
    SCENARIO_CLASS_MAP,
)
from goworld_tpu.autotune.warmset import candidate_config, carry_state

pytestmark = pytest.mark.governor


# ----------------------------------------------------------------------
# synthetic signatures (the reducer's output grammar)
# ----------------------------------------------------------------------
def sig(churn="flock_like", rebuild_rate=0.1, density="exact",
        events="quiet", **extra):
    s = {"churn": churn, "rebuild_rate": rebuild_rate,
         "density": density, "events": events, "sig": f"churn={churn}"}
    s.update(extra)
    return s


TELE = sig(churn="teleport_like", rebuild_rate=0.95)
FLOCK = sig(churn="flock_like", rebuild_rate=0.05)
DENSE = sig(churn="flock_like", rebuild_rate=0.05, density="over_cap",
            over_k_frac=1.0)


# ----------------------------------------------------------------------
# policy: mapping, hysteresis, determinism
# ----------------------------------------------------------------------
class TestPolicy:
    def test_classification_grammar(self):
        assert classify_signature(TELE) == "teleport_like"
        assert classify_signature(FLOCK) == "flock_like"
        assert classify_signature(DENSE) == "density"
        # density outranks churn — but only at a real over_k duty
        # cycle (rows actually truncated)
        assert classify_signature(
            sig(churn="teleport_like", rebuild_rate=0.95,
                density="over_k", over_k_frac=0.8)) == "density"
        # bare over_cap ticks with no row truncation are the uniform
        # Poisson cell tail, not density pressure — churn wins
        assert classify_signature(
            sig(churn="teleport_like", rebuild_rate=0.95,
                density="over_cap", over_k_frac=0.0)) \
            == "teleport_like"
        assert classify_signature(
            sig(churn="flock_like", rebuild_rate=0.05,
                density="over_k", over_k_frac=0.02)) == "flock_like"
        # hold band on the churn boundary
        assert classify_signature(
            sig(churn="teleport_like", rebuild_rate=0.55)) is None
        # skinless: the event-volume proxy
        assert classify_signature(
            sig(churn="skinless", events="heavy")) == "teleport_like"
        assert classify_signature(
            sig(churn="skinless", events="quiet")) == "flock_like"
        assert classify_signature(
            sig(churn="skinless", events="low")) is None
        # honest absences never decide
        assert classify_signature({"error": "no samples"}) is None
        assert classify_signature(None) is None

    def test_hysteresis_up_windows(self):
        p = GovernorPolicy(up_windows=3, cooldown_windows=0)
        assert p.observe(TELE) is None
        assert p.observe(TELE) is None
        assert p.observe(TELE) == "skin=0"
        assert p.current == "skin=0"

    def test_changed_want_resets_the_run(self):
        p = GovernorPolicy(up_windows=2, cooldown_windows=0,
                           table={**DEFAULT_TABLE,
                                  "density": "sort=counting,skin=0"})
        assert p.observe(TELE) is None
        assert p.observe(DENSE) is None   # different target: run=1
        assert p.observe(TELE) is None    # back: run=1 again
        assert p.observe(TELE) == "skin=0"

    def test_hold_band_holds_and_resets(self):
        p = GovernorPolicy(up_windows=2, cooldown_windows=0)
        assert p.observe(TELE) is None
        assert p.observe(sig(churn="teleport_like",
                             rebuild_rate=0.52)) is None  # band
        assert p.observe(TELE) is None    # run restarted
        assert p.observe(TELE) == "skin=0"

    def test_cooldown_blocks_the_next_swap(self):
        p = GovernorPolicy(up_windows=1, down_windows=1,
                           cooldown_windows=3)
        assert p.observe(TELE) == "skin=0"
        # wants default immediately, but the cooldown holds for 3
        # refractory windows after the deciding one
        assert p.observe(FLOCK) is None
        assert p.observe(FLOCK) is None
        assert p.observe(FLOCK) is None
        assert p.observe(FLOCK) == "default"

    def test_pin_suppresses_decisions(self):
        p = GovernorPolicy(up_windows=1, cooldown_windows=0)
        assert p.observe(TELE) == "skin=0"
        p.pin("default", windows=3, reason="regret(test)")
        assert p.current == "default"
        assert p.observe(TELE) is None
        assert p.observe(TELE) is None
        assert p.observe(TELE) is None
        assert p.observe(TELE) == "skin=0"  # pin expired
        assert any("revert regret(test)" in ln for ln in p.log_lines())

    def test_replay_is_byte_identical(self):
        """The determinism acceptance criterion: replaying a recorded
        signature stream yields a byte-identical transition log."""
        rng = np.random.default_rng(3)
        stream = []
        for _ in range(200):
            stream.append(sig(
                churn=rng.choice(["flock_like", "teleport_like",
                                  "skinless"]),
                rebuild_rate=float(rng.uniform()),
                density=rng.choice(["exact", "over_k", "over_cap"]),
                events=rng.choice(["quiet", "low", "moderate",
                                   "heavy"]),
            ))
        mk = lambda: GovernorPolicy(up_windows=2, down_windows=2,  # noqa: E731
                                    cooldown_windows=3)
        a, b = mk(), mk()
        for s in stream:
            a.observe(s)
        for s in stream:
            b.observe(s)
        assert a.log_lines() == b.log_lines()
        assert a.log_lines()  # the stream must actually transition

    def test_table_override_parsing(self):
        t = parse_table("teleport_like:sort=counting,skin=0")
        assert t == {"teleport_like": "sort=counting,skin=0"}
        with pytest.raises(ValueError, match="unknown"):
            parse_table("nonsense_class:skin=0")
        with pytest.raises(KeyError):
            parse_table("teleport_like:not_a_candidate")
        with pytest.raises(ValueError, match="class:label"):
            parse_table("justaword")

    def test_seed_table_reads_checked_in_best_kernels(self):
        """The mapping seeds from the repo's own measured per-scenario
        stamps: BENCH_r12's teleport best_kernel is skin=0 (the CPU
        skin inversion) and every seeded label is in the pool."""
        t = seed_table()
        assert set(t) == set(DEFAULT_TABLE)
        labels = {lbl for lbl, _ in DEFAULT_CANDIDATES}
        assert set(t.values()) <= labels
        assert t["teleport_like"] == "skin=0"


# ----------------------------------------------------------------------
# contracts: recommendation keys + candidate pool
# ----------------------------------------------------------------------
class TestContracts:
    def test_recommendation_keys_resolve_through_gameconfig(self):
        """ISSUE-13 satellite: every knob name a workload_signature
        recommendation can emit must be a GameConfig field (the set
        api._build_world consumes) — a rename breaks HERE, not the
        governor's input grammar in production."""
        from goworld_tpu.config import GameConfig
        from goworld_tpu.ops.telemetry import RECOMMENDATION_KEYS

        fields = {f.name for f in dataclasses.fields(GameConfig)}
        missing = set(RECOMMENDATION_KEYS) - fields
        assert not missing, (
            f"recommendation keys {missing} are not [gameN] knobs — "
            "update RECOMMENDATION_KEYS and the reducer together")

    def test_reducer_only_emits_contract_keys(self):
        """Probe the reducer across every class combination and assert
        the emitted recommendation keys stay inside the contract."""
        from goworld_tpu.ops import telemetry as telem

        def lanes(rebuild_frac, over_k, over_cap, ev, sync_p50):
            n = 100

            def lane(edges, counts):
                return {"edges": list(edges), "counts": counts}

            rb = [n - int(n * rebuild_frac), int(n * rebuild_frac)]
            return {
                "rebuilt": lane(telem.REBUILD_EDGES, rb + [0]),
                "skin_slack": lane(telem.SLACK_EDGES,
                                   [0] * 4 + [n] + [0] * 5),
                "over_k_rows": lane(
                    telem.COUNT_EDGES,
                    [n - over_k, over_k] + [0] * 11),
                "over_cap_cells": lane(
                    telem.COUNT_EDGES,
                    [n - over_cap, over_cap] + [0] * 11),
                "enter_n": lane(telem.COUNT_EDGES,
                                [0] * ev + [n] + [0] * (12 - ev)),
                "leave_n": lane(telem.COUNT_EDGES,
                                [0] * ev + [n] + [0] * (12 - ev)),
                "sync_n": lane(telem.COUNT_EDGES,
                               [0] * sync_p50 + [n]
                               + [0] * (12 - sync_p50)),
            }

        from goworld_tpu.ops.telemetry import RECOMMENDATION_KEYS

        seen = set()
        for rf in (0.0, 0.2, 1.0):
            for ok in (0, 50):
                for oc in (0, 50):
                    for ev in (0, 3, 6, 9):
                        for sp in (1, 8):
                            s = telem.workload_signature(
                                lanes(rf, ok, oc, ev, sp))
                            rec = s.get("recommendation") or {}
                            seen |= set(rec)
        assert seen <= set(RECOMMENDATION_KEYS), (
            f"reducer emitted {seen - set(RECOMMENDATION_KEYS)} "
            "outside RECOMMENDATION_KEYS")

    def test_candidate_pool_contract(self):
        """Candidate override keys are GridSpec fields (the warm set
        builds configs from them), the bench pool IS the policy pool,
        and every table label resolves."""
        from goworld_tpu.ops.aoi import GridSpec

        grid_fields = {f.name for f in dataclasses.fields(GridSpec)}
        for lbl, ov in DEFAULT_CANDIDATES:
            assert set(ov) <= set(CANDIDATE_GRID_KEYS)
            assert set(ov) <= grid_fields
        import bench

        assert [(lbl, ov) for lbl, ov in
                bench.SCENARIO_KERNEL_CANDIDATES] \
            == [(lbl, dict(ov)) for lbl, ov in DEFAULT_CANDIDATES]
        for cls in DEFAULT_TABLE:
            candidate_overrides(DEFAULT_TABLE[cls])
        assert set(SCENARIO_CLASS_MAP.values()) <= set(DEFAULT_TABLE)

    def test_candidate_config_respects_packed_id_bound(self):
        from goworld_tpu.core.state import WorldConfig
        from goworld_tpu.ops.aoi import GridSpec
        from goworld_tpu.utils import consts

        cfg = WorldConfig(capacity=1 << consts.AOI_ID_BITS,
                          grid=GridSpec(radius=50.0))
        c2 = candidate_config(cfg, {"skin": 4.0})
        assert c2.grid.skin == 0.0  # the api._build_world gate


# ----------------------------------------------------------------------
# live world fixtures (shared across the jax-heavy classes)
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def flock_world():
    from goworld_tpu.scenarios.runner import build_world
    from goworld_tpu.scenarios.spec import get_scenario

    w, ents, clients = build_world(
        get_scenario("flock"), n=40, skin=4.0, client_frac=0.15,
        seed=11)
    w.tick()
    return w, ents, clients


@pytest.fixture(scope="module")
def warmset(flock_world):
    w, _ents, _clients = flock_world
    ws = WarmSet(w.cfg, 1, w.policy, telemetry=True)
    ws.ensure("skin=0", block=True)
    ws.ensure("sort=counting,skin=0", block=True)
    return ws


def _commit(w, entry):
    w.apply_tick_config(
        entry.cfg, entry.exe, telem_fold=entry.fold_exe,
        telem_acc0=entry.acc0, telem_skin_on=entry.skin_on,
        telem_half_skin=entry.half_skin)


# ----------------------------------------------------------------------
# warm set
# ----------------------------------------------------------------------
class TestWarmSet:
    def test_entries_warm_with_matching_structure(self, warmset):
        e = warmset.entry("skin=0")
        assert e.warm and e.error is None
        assert e.cfg.grid.skin == 0.0
        assert not e.skin_on
        e2 = warmset.entry("sort=counting,skin=0")
        assert e2.warm and e2.cfg.grid.sort_impl == "counting"

    def test_re_ensure_never_recompiles(self, warmset):
        n = warmset.compile_count
        assert warmset.ensure("skin=0") is True
        assert warmset.ensure("sort=counting,skin=0", block=True)
        assert warmset.compile_count == n

    def test_exe_bit_parity_with_jit_path(self, flock_world, warmset):
        """The AOT executable must produce the SAME state/outputs as a
        fresh jit of the same candidate config — the swap changes the
        dispatch mechanism, never the math."""
        import jax

        from goworld_tpu.entity.manager import _make_local_tick

        w, _ents, _clients = flock_world
        e = warmset.entry("skin=0")
        state = carry_state(w.state, w.cfg, e.cfg, stacked=True)
        inputs = jax.tree.map(
            lambda x: np.broadcast_to(np.asarray(x),
                                      (1,) + np.asarray(x).shape),
            __import__("goworld_tpu.core.step",
                       fromlist=["TickInputs"]).TickInputs.empty(e.cfg))
        s_aot, o_aot = e.exe(state, inputs, w.policy)
        s_jit, o_jit = _make_local_tick(e.cfg, 1)(state, inputs,
                                                  w.policy)
        np.testing.assert_array_equal(np.asarray(s_aot.pos),
                                      np.asarray(s_jit.pos))
        np.testing.assert_array_equal(np.asarray(o_aot.sync_n),
                                      np.asarray(o_jit.sync_n))
        np.testing.assert_array_equal(np.asarray(o_aot.enter_n),
                                      np.asarray(o_jit.enter_n))

    def test_unknown_label_rejected_loudly(self, warmset):
        with pytest.raises(KeyError):
            warmset.ensure("not_a_candidate")

    def test_blocking_ensure_waits_out_inflight_compile(self,
                                                       flock_world):
        """ensure(label) async followed by ensure(label, block=True)
        must yield exactly ONE compile — the blocking call waits for
        the worker instead of duplicating the XLA work (review
        finding)."""
        w, _e, _c = flock_world
        ws = WarmSet(w.cfg, 1, w.policy, telemetry=False)
        assert ws.ensure("skin=0") is False      # queued on the worker
        assert ws.ensure("skin=0", block=True)   # waits, never doubles
        assert ws.compile_count == 1

    def test_hist_quantile_interp_resolution(self):
        """The regret guard's estimator: continuous inside a bucket
        (2x-spaced upper edges alone cannot express a 25% threshold),
        inf when the quantile sits past the top bucket."""
        from goworld_tpu.utils.devprof import hist_quantile_interp

        edges = [1.0, 2.0, 4.0, 8.0]
        lo = hist_quantile_interp(edges, [0, 0, 10, 0, 0], 0.5)
        assert 2.0 < lo < 4.0
        # mass shifting toward the bucket top moves the estimate up
        hi = hist_quantile_interp(edges, [0, 0, 10, 2, 0], 0.9)
        assert hi > lo
        assert hist_quantile_interp(edges, [0, 0, 0, 0, 5], 0.9) \
            == float("inf")
        assert hist_quantile_interp(edges, [0] * 5, 0.9) \
            != hist_quantile_interp(edges, [0] * 5, 0.9)  # NaN

    def test_multi_shard_worlds_rejected(self, flock_world):
        w, _e, _c = flock_world
        with pytest.raises(ValueError, match="single-shard"):
            WarmSet(w.cfg, 2, None)


# ----------------------------------------------------------------------
# the live swap (oracle exactness, entity retention, no retraces)
# ----------------------------------------------------------------------
class TestLiveSwap:
    def test_swap_mid_churn_stays_oracle_exact(self, flock_world,
                                               warmset):
        """The acceptance criterion: a live swap mid-churn keeps
        check_oracle exact (both overflow gauges zero) on the VERY
        NEXT tick — both directions, with host churn riding through
        the production create/destroy API across the swaps."""
        from goworld_tpu.scenarios.runner import check_oracle

        w, ents, clients = flock_world
        space = next(iter(w.spaces.values()))
        rng = np.random.default_rng(5)
        live = [e for e in w.entities.values()
                if not e.destroyed and not e.is_space]
        n0 = len(live)

        def churn():
            victim = live.pop(int(rng.integers(len(live))))
            tname = victim.type_name
            victim.destroy()
            live.append(w.create_entity(
                tname, space=space,
                pos=(float(rng.uniform(1, 199)), 0.0,
                     float(rng.uniform(1, 199))),
                moving=True))

        for label in ("skin=0", "sort=counting,skin=0", "skin=0"):
            churn()
            _commit(w, warmset.entry(label))
            w.tick()  # the very next tick after the swap
            bad = check_oracle(w, clients)
            assert bad == [], f"swap to {label}: {bad[:3]}"
            assert w.op_stats["aoi_over_k_rows"] == 0
            assert w.op_stats["aoi_over_cap_cells"] == 0
            churn()
            w.tick()
            assert check_oracle(w, clients) == []
        assert len([e for e in w.entities.values()
                    if not e.destroyed and not e.is_space]) == n0

    def test_swap_between_warm_configs_never_retraces(self, flock_world,
                                                      warmset):
        """Trace-count assertion: once the candidates are warm,
        swapping back and forth (and ticking) adds ZERO traces — the
        AOT executables and pre-warmed folds serve every tick."""
        from goworld_tpu.ops import telemetry as telem

        w, _ents, _clients = flock_world
        for label in ("skin=0", "sort=counting,skin=0"):
            _commit(w, warmset.entry(label))
            w.tick()
        before = dict(telem.TRACE_COUNTS)
        for _ in range(3):
            for label in ("sort=counting,skin=0", "skin=0"):
                _commit(w, warmset.entry(label))
                w.tick()
                w.tick()
        assert dict(telem.TRACE_COUNTS) == before
        assert warmset.compile_count == 2  # still just the prewarm

    def test_telemetry_lane_set_follows_the_swap(self, flock_world,
                                                 warmset):
        w, _e, _c = flock_world
        _commit(w, warmset.entry("skin=0"))
        for _ in range(3):
            w.tick()
        s = w.workload_signature()
        assert s is not None and s["churn"] == "skinless"
        assert s["config"]["skin"] == 0.0

    def test_mesh_and_multi_shard_swaps_rejected(self):
        from goworld_tpu.core.state import WorldConfig
        from goworld_tpu.entity.manager import World
        from goworld_tpu.ops.aoi import GridSpec

        w = World(WorldConfig(capacity=32, grid=GridSpec(radius=25.0)),
                  n_spaces=2)
        with pytest.raises(ValueError, match="single-shard"):
            w.apply_tick_config(w.cfg, w._step)


# ----------------------------------------------------------------------
# the governor runtime
# ----------------------------------------------------------------------
class TestKernelGovernor:
    @pytest.fixture()
    def gov(self, flock_world, warmset):
        w, _e, _c = flock_world
        # restore the boot-ish default config before each test (the
        # module-scoped world is shared)
        g = KernelGovernor(w, name="gtest", up_windows=1,
                           cooldown_windows=0, regret_pct=0.25,
                           regret_pin_windows=4)
        # share the module warm set (already compiled) so tests never
        # pay a second compile
        g.warmset = warmset
        return g

    def test_decide_warm_commit_and_counter(self, gov, flock_world):
        from goworld_tpu.utils import metrics

        w, _e, _c = flock_world
        ev = gov.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None and ev["to"] == "skin=0"
        assert gov.current == "skin=0"
        assert w.cfg.grid.skin == 0.0
        c = metrics.counter("governor_swaps_total",
                            **{"from": ev["from"], "to": "skin=0",
                               "reason": "policy"})
        assert c.value >= 1
        assert gov.log_lines()

    def test_pending_until_warm_then_commit(self, flock_world):
        """A cold candidate never commits mid-window: the world keeps
        its config until the off-thread compile lands."""
        w, _e, _c = flock_world
        g = KernelGovernor(w, name="gcold", up_windows=1,
                           cooldown_windows=0)
        ev = g.on_window(TELE, tick_ms_p90=5.0)
        # either the async compile already finished (slow box margin)
        # or the decision is pending — never a half-committed state
        if ev is None:
            assert g.pending == "skin=0"
            deadline = time.monotonic() + 120
            while not g.warmset.is_warm("skin=0") \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
            ev = g.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None and ev["to"] == "skin=0"
        assert g.current == "skin=0"

    def test_policy_walkback_clears_stale_pending(self, flock_world):
        """A pending target whose compile is still in flight must be
        DROPPED when the policy re-decides back to the serving config
        — otherwise the stale target commits (unwanted) the moment it
        warms, and the policy (whose decision state already walked
        back) never issues a corrective decision (review finding)."""
        w, _e, _c = flock_world

        class _ColdSet:
            """Never warms: models a long in-flight compile."""

            def __init__(self):
                self.ensured = []

            def ensure(self, label, block=False):
                self.ensured.append(label)
                return False

            def entry(self, label):
                return None

        g = KernelGovernor(w, name="gstale", up_windows=1,
                           down_windows=1, cooldown_windows=0)
        g.warmset = _ColdSet()
        # teleport burst: decided, but the target is cold -> pending
        assert g.on_window(TELE, tick_ms_p90=5.0) is None
        assert g.pending == "skin=0"
        # workload reverts before the compile lands: the policy walks
        # back to the serving config -> the stale pending must clear
        assert g.on_window(FLOCK, tick_ms_p90=5.0) is None
        assert g.pending is None
        # later windows (compile could land any time) commit nothing:
        # the world keeps serving its config
        assert g.on_window(FLOCK, tick_ms_p90=5.0) is None
        assert g.current == "default"
        assert g.swaps == []

    def test_regret_guard_reverts_and_pins(self, gov):
        ev = gov.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None and ev["to"] == "skin=0"
        ev2 = gov.on_window(TELE, tick_ms_p90=50.0)  # 10x worse p90
        assert ev2 is not None and ev2["reason"] == "regret"
        assert ev2["to"] == ev["from"]
        assert "regret" in ev2 and ev2["regret"]["pre_p90_ms"] == 5.0
        assert gov.current == ev["from"]
        # pinned: the same teleport signature cannot re-swap yet
        assert gov.on_window(TELE, tick_ms_p90=5.0) is None

    def test_revert_installs_zeroed_boot_accumulator(self, gov,
                                                     flock_world):
        """The boot 'default' WarmEntry must carry a ZEROED telemetry
        accumulator: capturing the live cumulative one would re-feed
        every boot-era sample into the metrics registry (and classify
        the first post-revert window on process-lifetime averages)
        when a swap commits back to the boot config (review
        finding)."""
        import jax

        w, _e, _c = flock_world
        assert w._telem_fn is not None  # telemetry-live world
        for _ in range(3):
            w.tick()
        w.flush_pending_outputs()
        # the live accumulator has real boot-era mass
        assert any(float(np.asarray(x).sum()) > 0
                   for x in jax.tree.leaves(w._telem_acc))
        ev = gov.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None and ev["to"] == "skin=0"
        ev2 = gov.on_window(TELE, tick_ms_p90=50.0)  # regret revert
        assert ev2 is not None and ev2["reason"] == "regret"
        leaves = jax.tree.leaves(w._telem_acc)
        assert leaves and all(float(np.asarray(x).sum()) == 0
                              for x in leaves)

    def test_regret_fires_on_inf_p90(self, gov):
        """An inf p90 (latency mass beyond the top histogram bucket)
        is the STRONGEST regression signal — it must revert, never
        disarm as 'unmeasurable' (review finding)."""
        ev = gov.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None and ev["to"] == "skin=0"
        ev2 = gov.on_window(TELE, tick_ms_p90=float("inf"))
        assert ev2 is not None and ev2["reason"] == "regret"
        assert gov.current == ev["from"]

    def test_regret_without_baseline_disarms(self, gov):
        """A swap committed with no measured pre-swap p90 must not
        leave the guard armed (and displayed) forever."""
        ev = gov.on_window(TELE, tick_ms_p90=None)
        if ev is None:  # warm race margin: commit on the next window
            ev = gov.on_window(TELE, tick_ms_p90=None)
        assert ev is not None
        gov.on_window(TELE, tick_ms_p90=8.0)
        assert gov._regret is None  # disarmed, not stuck

    def test_swap_vindicated_when_p90_holds(self, gov):
        ev = gov.on_window(TELE, tick_ms_p90=5.0)
        assert ev is not None
        assert gov.on_window(TELE, tick_ms_p90=5.2) is None
        assert gov.on_window(TELE, tick_ms_p90=5.1) is None
        assert gov._regret is None  # disarmed after the judge windows
        assert gov.current == "skin=0"

    def test_snapshot_and_registry(self, gov):
        gov_mod.register("gtest", gov)
        try:
            gov.on_window(TELE, tick_ms_p90=5.0)
            snap = gov_mod.snapshot()
            assert "gtest" in snap
            g = snap["gtest"]
            assert {"current", "pending", "swaps", "policy",
                    "warmset", "regret_guard"} <= set(g)
            json.dumps(snap)  # endpoint-serializable
        finally:
            gov_mod.unregister("gtest")

    def test_governor_endpoint(self, gov):
        from goworld_tpu.utils import debug_http

        gov_mod.register("gep", gov)
        srv = debug_http.start(0)
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/governor",
                    timeout=5) as r:
                payload = json.loads(r.read())
            assert "gep" in payload
            assert "current" in payload["gep"]
        finally:
            gov_mod.unregister("gep")
            srv.shutdown()

    def test_empty_registry_is_honest(self):
        gov_mod.reset()
        assert "error" in gov_mod.snapshot()


# ----------------------------------------------------------------------
# flight recorder trigger
# ----------------------------------------------------------------------
class TestFlightRecTrigger:
    def test_governor_swap_trigger_freezes_context(self):
        from goworld_tpu.utils import flightrec

        ctx = {"governor": {"current": "skin=0", "swaps": ["#1 ..."]}}
        rec = flightrec.FlightRecorder(
            ring=16, cooldown_secs=0.0, context_fn=lambda: dict(ctx))
        for t in range(4):
            assert rec.record({"tick": t, "tick_ms": 1.0,
                               "budget_ms": 10.0}) == []
        out = rec.record({"tick": 4, "tick_ms": 1.0, "budget_ms": 10.0,
                          "governor": "default->skin=0 (policy)"})
        assert len(out) == 1
        b = out[0]
        assert b["trigger"] == "governor_swap"
        assert "default->skin=0" in b["detail"]
        assert b["context"]["governor"]["current"] == "skin=0"
        assert len(b["frames"]) == 5

    def test_no_governor_mark_no_trigger(self):
        from goworld_tpu.utils import flightrec

        rec = flightrec.FlightRecorder(ring=16, cooldown_secs=0.0)
        for t in range(8):
            assert rec.record({"tick": t, "tick_ms": 1.0,
                               "budget_ms": 10.0}) == []


# ----------------------------------------------------------------------
# config / api plumbing
# ----------------------------------------------------------------------
class TestConfigPlumbing:
    def test_governor_knobs_parse(self, tmp_path):
        from goworld_tpu import config as config_mod

        ini = tmp_path / "goworld_tpu.ini"
        ini.write_text(
            "[game1]\ngovernor = true\ngovernor_window_ticks = 32\n"
            "governor_regret_pct = 0.5\n"
            "governor_table = teleport_like:skin=0\n"
        )
        cfg = config_mod.load(str(ini))
        gc = cfg.games[1]
        assert gc.governor is True
        assert gc.governor_window_ticks == 32
        assert gc.governor_regret_pct == 0.5
        assert gc.governor_table == "teleport_like:skin=0"

    def test_eligibility_gate(self):
        from goworld_tpu import config as config_mod
        from goworld_tpu.api import _governor_eligible

        gc = config_mod.GameConfig(governor=True)
        assert _governor_eligible(gc, 1) is True
        assert _governor_eligible(
            config_mod.GameConfig(governor=False), 1) is False
        for bad in (dict(n_spaces=2), dict(mesh_devices=4),
                    dict(megaspace=True, mesh_devices=4),
                    dict(telemetry_live=False)):
            gc = config_mod.GameConfig(governor=True, **bad)
            assert _governor_eligible(gc, 1) is False
        with pytest.raises(ValueError):
            _governor_eligible(
                config_mod.GameConfig(
                    governor=True, governor_table="bogus:skin=0"), 1)

    def test_scraper_governor_lines(self):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        spec = importlib.util.spec_from_file_location(
            "scrape_metrics_under_test",
            os.path.join(repo, "tools", "scrape_metrics.py"))
        scraper = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(scraper)
        lines = scraper.governor_lines({
            "game1": {"game1": {
                "current": "skin=0", "pending": "default",
                "windows": 9, "swaps": ["#3 default->skin=0 policy"],
                "regret_guard": None,
            }}})
        assert len(lines) == 1
        assert "governor skin=0" in lines[0]
        assert "-> default (warming)" in lines[0]
        assert "swaps 1 over 9 windows" in lines[0]


# ----------------------------------------------------------------------
# the chaos-soak governor scenario (slow: ~8 synchronous compiles)
# ----------------------------------------------------------------------
@pytest.mark.slow
def test_chaos_soak_governor_scenario_converges():
    """tools/chaos_soak.py --scenario governor end-to-end: >= 3 live
    swaps on one world, zero oracle divergence, zero entity loss, and
    the decision log replay-verified — the ISSUE-13 soak satellite."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "chaos_soak_under_test",
        os.path.join(repo, "tools", "chaos_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    report = soak.run_governor(seed=7)
    assert report.get("error") is None, report
    assert len(report["swaps"]) >= 3, report["swaps"]
    assert report["mismatches"] == []
    assert report["entity_ids_stable"]
    assert report["replay_matches"]
    assert report["converged"], report


# ----------------------------------------------------------------------
# GameServer window drive (stub-light: the real wiring, no cluster)
# ----------------------------------------------------------------------
class TestGameServerDrive:
    def test_drive_commits_on_rotated_windows(self, flock_world,
                                              warmset, monkeypatch):
        from goworld_tpu.net.game import GameServer

        w, _e, _c = flock_world
        gs = GameServer(97, w, [], governor_enabled=True,
                        governor_up_windows=1,
                        governor_cooldown_windows=0,
                        governor_window_ticks=8,
                        flightrec_ring=32,
                        overload_enabled=False)
        assert gs.governor is not None
        gs.governor.warmset = warmset  # pre-compiled candidates
        assert w.SIG_WINDOW_TICKS == 8
        # simulate a rotated window carrying a teleport-like signature
        monkeypatch.setattr(w, "window_signature", lambda: dict(TELE))
        w._telem_win_tick = 123  # "a rotation happened"
        ev = gs._drive_governor()
        assert ev is not None and ev["to"] == "skin=0"
        assert "skin=0.0" in gs._kernel_key
        # same window tick: no double drive
        assert gs._drive_governor() is None
        # the frame stamp fires the flight-recorder trigger
        gs._flightrec_frame(0.001, ev)
        incidents = gs.flightrec.incidents()
        assert any(i["trigger"] == "governor_swap" for i in incidents)
        ctx = [i for i in incidents
               if i["trigger"] == "governor_swap"][-1]["context"]
        assert ctx["governor"]["current"] == "skin=0"
