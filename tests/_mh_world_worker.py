"""Worker for tests/test_multihost.py::test_world_api_multihost — a full
World (entity API, megaspace space type, host bookkeeping) running SPMD on
two controllers over one global mesh.

Invoked as: python -m tests._mh_world_worker <process_id> <port>
(env: JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""

import json
import sys


def main() -> int:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from goworld_tpu.parallel.multihost import global_mesh, init_distributed
    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

    import numpy as np
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.ops.aoi import GridSpec

    n_dev, tile_w, radius = 8, 100.0, 10.0
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=8, cell_cap=16, row_block=16),
        npc_speed=0.0,   # nothing wanders: motion comes from pos staging
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mesh = global_mesh()
    w = World(cfg, n_spaces=n_dev, mesh=mesh, megaspace=True,
              halo_cap=8, migrate_cap=4)

    class Mega(Space):
        pass

    class Npc(Entity):
        pass

    w.registry.register("Mega", Mega, is_space=True, megaspace=True)
    w.registry.register("Npc", Npc)

    # IDENTICAL program on both controllers (the SPMD contract): the
    # walker starts on tile 3 (process 0) and is driven east across the
    # process boundary; a watcher sits on tile 4 (process 1).
    sp = w.create_space("Mega")
    walker = w.create_entity("Npc", space=sp, pos=(398.5, 0.0, 50.0),
                             eid="walker_walker_00")
    watcher = w.create_entity("Npc", space=sp, pos=(403.0, 0.0, 50.0),
                              eid="watcher_watcher0")

    events = []
    orig = walker.OnEnterAOI

    def on_enter(other):
        events.append(("walker_sees", other.id))
        return orig(other)
    walker.OnEnterAOI = on_enter
    worig = watcher.OnEnterAOI

    def won_enter(other):
        events.append(("watcher_sees", other.id))
        return worig(other)
    watcher.OnEnterAOI = won_enter

    x = 398.5
    for t in range(6):
        if t < 3:
            x += 1.0
            walker.set_position((x, 0.0, 50.0))  # staged scatter, SPMD
        w.tick()

    out = {
        "process": pid,
        "local_shards": w.local_shards,
        "walker_shard": walker.shard,
        "watcher_shard": watcher.shard,
        "walker_alive": not walker.destroyed and walker.slot is not None,
        "events": events,
        "watcher_interested_in": sorted(watcher.interested_in),
        "walker_pos_x": float(walker.position[0]),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
