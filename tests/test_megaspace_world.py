"""Megaspace through the World/entity API (VERDICT #3): one registered
Space type spanning the whole mesh as tiles, entities created/moved through
the normal entity API, interest sets checked against a NumPy oracle while
entities churn across tile borders.

Reference anchor: the per-space population cap this removes is user-code
policy in the reference (SpaceService.go:14, <=100 avatars/space); one
GoWorld space can never span processes (doc.go:12-14)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.parallel.mesh import make_mesh

N_DEV = 8
TILE_W = 100.0
RADIUS = 10.0


class Walker(Entity):
    pass


class Silent(Entity):  # AOI-less (service-like)
    pass


class MegaArena(Space):
    pass


def _mega_world(capacity=96):
    cfg = WorldConfig(
        capacity=capacity,
        grid=GridSpec(
            radius=RADIUS, extent_x=TILE_W + 2 * RADIUS, extent_z=100.0,
            k=32, cell_cap=64, row_block=capacity,
        ),
        npc_speed=40.0,  # fast movers: border crossings within a few ticks
        turn_prob=0.2,
        enter_cap=8192, leave_cap=8192, sync_cap=8192,
    )
    mesh = make_mesh(N_DEV)
    w = World(cfg, n_spaces=N_DEV, mesh=mesh, megaspace=True,
              halo_cap=64, migrate_cap=32)
    w.register_space("MegaArena", MegaArena, megaspace=True)
    w.register_entity("Walker", Walker)
    w.register_entity("Silent", Silent, use_aoi=False)
    w.create_nil_space()
    return w


def _oracle_check(w: World, arena, exclude=()):
    """Host interest sets must equal the Chebyshev-radius oracle over the
    device positions (post-tick positions ARE the sweep positions)."""
    ents = [
        w.entities[eid] for eid in arena.members
        if w.entities[eid].slot is not None
    ]
    pos = np.asarray(w.state.pos)
    coords = {}
    for e in ents:
        p = pos[e.shard, e.slot]
        coords[e.id] = (float(p[0]), float(p[2]))
    part = [e for e in ents if e.id not in exclude]
    for e in part:
        ex, ez = coords[e.id]
        want = {
            o.id for o in part
            if o.id != e.id
            and max(abs(coords[o.id][0] - ex), abs(coords[o.id][1] - ez))
            <= RADIUS
        }
        assert e.interested_in == want, (
            f"{e.id} on tile {e.shard}: got {sorted(e.interested_in)} "
            f"want {sorted(want)}"
        )


def test_mega_world_border_churn_matches_oracle():
    w = _mega_world()
    arena = w.create_space("MegaArena")
    assert arena.is_mega and arena.use_aoi
    rng = np.random.default_rng(42)
    ents = []
    spawn_tile = {}
    for _ in range(N_DEV * 40):
        x = float(rng.uniform(0, TILE_W * N_DEV))
        z = float(rng.uniform(0, 100.0))
        e = w.create_entity("Walker", space=arena, pos=(x, 0, z),
                            moving=True)
        ents.append(e)
        spawn_tile[e.id] = e.shard
    for tick in range(10):
        w.tick()
        outs = w.last_outputs
        assert int(np.asarray(outs.migrate_dropped).sum()) == 0
        assert (np.asarray(outs.halo_demand) <= 64).all(), \
            "halo overflow: test halo_cap undersized"
        assert (np.asarray(outs.migrate_demand) <= 32).all()
        _oracle_check(w, arena)
    # host tile bookkeeping tracks device positions exactly
    pos = np.asarray(w.state.pos)
    for e in ents:
        x = float(pos[e.shard, e.slot][0])
        assert e.shard == max(0, min(N_DEV - 1, int(x // TILE_W))), \
            f"{e.id}: host tile {e.shard} disagrees with x={x}"
    assert sum(len(o) for o in w._slot_owner) == len(ents)
    # with speed 40 over 10 ticks, SOME entities crossed borders — the
    # whole migration path was genuinely exercised
    crossings = sum(1 for e in ents if e.shard != spawn_tile[e.id])
    assert crossings > 0, "no entity ever crossed a tile border"


def test_mega_world_crossing_entity_keeps_identity():
    """Drive one entity across a border via teleports; its host object,
    attrs and interest survive the tile hop (the EnterSpace-free analog of
    Entity.go:956-1115's migration)."""
    w = _mega_world()
    arena = w.create_space("MegaArena")
    a = w.create_entity("Walker", space=arena, pos=(95.0, 0, 50.0))
    b = w.create_entity("Walker", space=arena, pos=(97.0, 0, 50.0))
    a.attrs["hp"] = 77
    w.tick()
    assert a.shard == 0 and b.shard == 0
    assert a.interested_in == {b.id}
    # teleport a across the border; b stays — both still within radius
    a.set_position((103.0, 0, 50.0))
    w.tick()
    assert a.shard == 1, f"a did not hop tiles (shard={a.shard})"
    assert a.slot is not None
    assert a.attrs["hp"] == 77
    assert a.interested_in == {b.id}, "interest lost across the border"
    assert b.interested_in == {a.id}
    p = a.position
    assert abs(p[0] - 103.0) < 1.0
    # move out of range: interest drops
    a.set_position((140.0, 0, 50.0))
    w.tick()
    assert a.interested_in == set()
    assert b.interested_in == set()
    assert a.shard == 1


def test_mega_world_aoi_less_entity_excluded():
    w = _mega_world()
    arena = w.create_space("MegaArena")
    svc = w.create_entity("Silent", space=arena, pos=(99.0, 0, 50.0))
    others = [
        w.create_entity("Walker", space=arena, pos=(95.0 + i, 0, 50.0))
        for i in range(4)
    ]
    for _ in range(3):
        w.tick()
    assert not svc.interested_in and not svc.interested_by
    for o in others:
        assert svc.id not in o.interested_in
    _oracle_check(w, arena, exclude={svc.id})


def test_mega_world_destroy_mid_churn():
    w = _mega_world()
    arena = w.create_space("MegaArena")
    ents = [
        w.create_entity("Walker", space=arena,
                        pos=(90.0 + i * 2.0, 0, 50.0), moving=True)
        for i in range(10)
    ]
    w.tick()
    victim = ents[3]
    watchers = set(victim.interested_by)
    assert watchers
    w.destroy_entity(victim)
    for _ in range(2):
        w.tick()
    for wid in watchers:
        we = w.entities.get(wid)
        if we is not None:
            assert victim.id not in we.interested_in
    _oracle_check(w, arena)


def test_mega_dropped_migrant_reconciled():
    """A border-crosser dropped at a full destination tile must not become
    a zombie addressing a dead row: the host detects the orphan and
    respawns it (or parks it in the nil space when the tile stays full)."""
    cfg = WorldConfig(
        capacity=6,
        grid=GridSpec(radius=RADIUS, extent_x=TILE_W + 2 * RADIUS,
                      extent_z=100.0, k=8, cell_cap=16, row_block=6),
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mesh = make_mesh(N_DEV)
    w = World(cfg, n_spaces=N_DEV, mesh=mesh, megaspace=True,
              halo_cap=8, migrate_cap=4)
    w.register_space("MegaArena", MegaArena, megaspace=True)
    w.register_entity("Walker", Walker)
    w.create_nil_space()
    arena = w.create_space("MegaArena")
    # fill tile 1 completely
    parked = [
        w.create_entity("Walker", space=arena,
                        pos=(150.0 + i, 0, 10.0 + i * 10))
        for i in range(6)
    ]
    mover = w.create_entity("Walker", space=arena, pos=(95.0, 0, 50.0))
    w.tick()
    assert mover.shard == 0
    # teleport into the full tile: the device row departs but the arrival
    # is dropped (no free slot on tile 1)
    mover.set_position((150.0, 0, 80.0))
    w.tick()
    # not a zombie: either parked in nil space or re-placed somewhere live
    assert not mover.destroyed
    if mover.space is arena:
        assert mover.slot is not None
        assert bool(np.asarray(w.state.alive)[mover.shard, mover.slot])
        assert w._slot_owner[mover.shard][mover.slot] == mover.id
    else:
        assert mover.space is w.nil_space
    # the parked population is intact
    for p in parked:
        assert w._slot_owner[p.shard][p.slot] == p.id
        assert bool(np.asarray(w.state.alive)[p.shard, p.slot])


def test_mega_world_rejects_normal_aoi_space():
    w = _mega_world()
    w.register_space("Plain", Space)
    with pytest.raises(RuntimeError):
        w.create_space("Plain")


def test_mega_space_type_requires_mega_world():
    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=32),
    )
    w = World(cfg, n_spaces=1)
    w.register_space("MegaArena", MegaArena, megaspace=True)
    with pytest.raises(RuntimeError):
        w.create_space("MegaArena")
