"""Correctness audit plane (ISSUE 17): the entity-ownership ledger
(census digests, ownership seq, migration rings), deployment
conservation verdicts that NAME the lost EntityID, the sampled live
AOI oracle on a real ticking World, mirror probes, the
``audit_violation`` flight-recorder trigger, the ``/audit`` endpoint,
the aggregator / scrape / incident-bundle tooling, and the TRACE+AGE
trailer coexistence wire contract."""

import importlib.util
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from goworld_tpu.net import proto
from goworld_tpu.net.packet import (
    AGE_FLAG,
    TRACE_FLAG,
    Packet,
    decode_wire,
    new_packet,
    wire_payload,
)
from goworld_tpu.utils import audit, debug_http, flightrec, metrics

pytestmark = pytest.mark.audit


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_registries():
    metrics.REGISTRY.reset()
    yield
    metrics.REGISTRY.reset()


# =======================================================================
# census digest
# =======================================================================
def test_crc_fold_is_canonical_over_sets():
    a = audit.crc_fold(["E2", "E1", "E3"])
    assert a == audit.crc_fold(["E1", "E3", "E2"])  # order-free
    assert a != audit.crc_fold(["E1", "E2"])        # set-sensitive
    assert a != audit.crc_fold(["E1", "E3", "E4"])
    assert audit.crc_fold([]) == 0


def test_ledger_create_destroy_census():
    led = audit.EntityLedger("g1")
    led.on_create("E1", "Mob", 1)
    led.on_create("E2", "Mob", 1)
    led.on_create("E3", "Npc", 2)
    led.on_destroy("E2", 3)
    assert (led.created, led.destroyed) == (3, 1)
    census = led.census()
    assert census["Mob"]["count"] == 1
    assert census["Npc"]["count"] == 1
    assert census["Mob"]["crc"] == audit.crc_fold(["E1"])
    snap = led.snapshot(tick=3, eids=True)
    assert snap["kind"] == "game"
    assert snap["entities"] == 2
    assert snap["eids"] == ["E1", "E3"]
    assert snap["crc"] == audit.crc_fold(["E1", "E3"])
    assert snap["violations_total"] == {}


def test_ledger_duplicate_create_and_destroy_unknown():
    led = audit.EntityLedger("g1")
    led.on_create("E1", "Mob", 1)
    led.on_create("E1", "Mob", 2)
    led.on_destroy("E9", 3)
    assert led.violations_total == {"duplicate_create": 1,
                                    "destroy_unknown": 1}
    kinds = [v["kind"] for v in led.violations]
    assert kinds == ["duplicate_create", "destroy_unknown"]
    # the named-EntityID contract
    assert "E1" in led.violations[0]["detail"]
    assert "E9" in led.violations[1]["detail"]
    # counters moved (one per kind)
    assert metrics.counter("audit_violations_total",
                           kind="duplicate_create", game="g1").value == 1


# =======================================================================
# migration ownership seq
# =======================================================================
def test_cross_ledger_migration_roundtrip_clean():
    a, b = audit.EntityLedger("g1"), audit.EntityLedger("g2")
    a.on_create("E1", "Avatar", 1)
    assert a.next_seq("E1") == 2
    seq = a.stamp_migrate_out("E1", 5, target=2)
    assert seq == 2
    assert "E1" not in a.live_eids()
    b.on_migrate_in("E1", "Avatar", seq, 6)
    assert "E1" in b.live_eids()
    assert not a.violations and not b.violations
    assert (a.migrated_out, b.migrated_in) == (1, 1)
    # B now owns the seq: its next migrate-out carries seq+1
    assert b.next_seq("E1") == seq + 1


def test_self_roundtrip_accepted_then_ghost_rejected():
    """A->B->A through ONE ledger (single-game worlds, tests): the
    in-record matches the ledger's own open out-record and must be
    accepted; a RE-delivery of the same (eid, seq) after the record
    retired is a ghost and must name itself."""
    led = audit.EntityLedger("g1")
    led.on_create("E1", "Avatar", 1)
    seq = led.stamp_migrate_out("E1", 2)
    led.on_migrate_in("E1", "Avatar", seq, 3)
    assert not led.violations
    assert "E1" in led.live_eids()
    # the out-record was retired by the accepted round trip
    assert led.snapshot(tick=3)["in_flight"] == []
    # E1 migrates out AGAIN (seq bumps, old record long retired); a
    # re-delivery of the OLD hop's packet is now a ghost: stale seq
    # and no matching open out-record
    seq2 = led.stamp_migrate_out("E1", 4)
    assert seq2 == seq + 1
    led.on_migrate_in("E1", "Avatar", seq, 5)
    assert led.violations_total == {"stale_migrate": 1}
    assert "E1" in led.violations[-1]["detail"]


def test_duplicate_entity_and_stale_seq_rejected():
    led = audit.EntityLedger("g2")
    led.on_migrate_in("E1", "Avatar", 5, 1)
    # migrate-in of a LIVE entity = duplicated owner
    led.on_migrate_in("E1", "Avatar", 6, 2)
    assert led.violations_total == {"duplicate_entity": 1}
    # E1 hops onward (seq 7 stamped and remembered); a replay of the
    # seq-5 delivery is stale
    led.stamp_migrate_out("E1", 3)
    led.on_migrate_in("E1", "Avatar", 5, 4)
    assert led.violations_total == {"duplicate_entity": 1,
                                    "stale_migrate": 1}


def test_seq_zero_pre_stamp_peer_accepted():
    led = audit.EntityLedger("g2")
    led.on_migrate_in("E1", "Avatar", 0, 1)
    assert not led.violations and "E1" in led.live_eids()
    # accepted and re-anchored: the next out-stamp is monotone
    assert led.next_seq("E1") >= 2


def test_resync_restores_conservation_identity():
    led = audit.EntityLedger("g1")
    led.on_create("E1", "Mob", 1)
    led.on_create("E2", "Mob", 1)
    led.on_destroy("E1", 2)
    led.resync({"E7": "Mob", "E8": "Npc"}, 10)
    s = led.snapshot(tick=10)
    assert s["entities"] == 2
    # live == created - destroyed - out + in must hold post-restore
    assert s["entities"] == (s["created"] - s["destroyed"]
                             - s["migrated_out"] + s["migrated_in"])


# =======================================================================
# deployment conservation verdict
# =======================================================================
def _game_snap(led, tick):
    return led.snapshot(tick=tick)


def test_conservation_clean_and_in_flight_window():
    a, b = audit.EntityLedger("g1"), audit.EntityLedger("g2")
    for i in range(4):
        a.on_create(f"E{i}", "Mob", 1)
    seq = a.stamp_migrate_out("E0", 10, target=2)
    # mid-flight, inside grace: in_flight bridges the census gap
    v = audit.conservation_verdict([_game_snap(a, 12),
                                    _game_snap(b, 12)])
    assert v["ok"], v["problems"]
    assert v["in_flight"] == 1 and v["live"] == 3
    # delivered: the in-record retires the window
    b.on_migrate_in("E0", "Mob", seq, 13)
    v = audit.conservation_verdict([_game_snap(a, 14),
                                    _game_snap(b, 14)])
    assert v["ok"] and v["in_flight"] == 0 and v["live"] == 4


def test_conservation_names_lost_entity_after_grace():
    a = audit.EntityLedger("g1")
    a.on_create("Elost", "Avatar", 1)
    a.stamp_migrate_out("Elost", 10, target=2)
    v = audit.conservation_verdict([_game_snap(a, 30)], grace_ticks=8)
    assert not v["ok"]
    assert any("lost EntityID Elost" in p for p in v["problems"])
    assert v["lost"][0]["eid"] == "Elost"
    # the balance problem is ALSO reported (live 0 + in-flight 1 ok —
    # the lost record is still outstanding, so balance holds; only
    # the age names it)
    assert v["in_flight"] == 1


def test_conservation_balance_breach_and_violation_rollup():
    a = audit.EntityLedger("g1")
    a.on_create("E1", "Mob", 1)
    a.created = 3  # simulate a bookkeeping hole
    v = audit.conservation_verdict([_game_snap(a, 2)])
    assert not v["ok"]
    assert any("conservation broken" in p for p in v["problems"])
    b = audit.EntityLedger("g2")
    b.on_destroy("E9", 1)  # records destroy_unknown
    v = audit.conservation_verdict([_game_snap(b, 2)])
    assert any("destroy_unknown" in p for p in v["problems"])


def test_conservation_dispatcher_drift_cross_check():
    a = audit.EntityLedger("g1")
    for i in range(3):
        a.on_create(f"E{i}", "Mob", 1)
    disp_ok = {"kind": "dispatcher", "entities": 3, "games": {}}
    v = audit.conservation_verdict([_game_snap(a, 2)],
                                   dispatcher=disp_ok)
    assert v["ok"] and v["dispatcher_entities"] == 3
    disp_bad = {"kind": "dispatcher", "entities": 9, "games": {}}
    v = audit.conservation_verdict([_game_snap(a, 2)],
                                   dispatcher=disp_bad)
    assert not v["ok"]
    assert any("dispatcher routes 9" in p for p in v["problems"])


def test_first_divergent_eid():
    assert audit.first_divergent_eid(["E1", "E2"], ["E1", "E3"]) == "E2"
    assert audit.first_divergent_eid(["E1"], ["E1"]) is None
    assert audit.first_divergent_eid({"truncated": 99}, ["E1"]) is None


# =======================================================================
# AuditPlane: knobs, cohort rotation, oracle math
# =======================================================================
def test_audit_plane_knob_validation_is_loud():
    with pytest.raises(ValueError, match="audit_sample_every"):
        audit.AuditPlane("bad", sample_every=0)
    with pytest.raises(ValueError, match="audit_cohort"):
        audit.AuditPlane("bad", cohort=0)


def test_next_cohort_rotates_and_covers_every_slot():
    ap = audit.AuditPlane("rot", sample_every=1, cohort=3)
    try:
        slots = [5, 1, 9, 3, 7]
        seen = set()
        picks = [ap.next_cohort(slots) for _ in range(4)]
        for p in picks:
            assert len(p) == 3 == len(set(p))  # no wrap duplication
            seen.update(p)
        assert seen == set(slots)  # full coverage within one lap+
        assert ap.next_cohort([]) == []
    finally:
        ap.close()


def test_cohort_oracle_matches_full_bruteforce():
    rng = np.random.default_rng(7)
    n = 40
    pos = np.zeros((n, 3), np.float64)
    pos[:, 0] = rng.uniform(0, 100, n)
    pos[:, 2] = rng.uniform(0, 100, n)
    alive = rng.uniform(size=n) > 0.2
    wr = np.where(rng.uniform(size=n) > 0.3, 25.0, 0.0)
    rows = audit.cohort_oracle(pos, alive, 25.0, range(n),
                               watch_radius=wr)
    for i in range(n):
        want = set()
        if alive[i] and wr[i] > 0:
            for j in range(n):
                if j == i or not (alive[j] and wr[j] > 0):
                    continue
                d = max(abs(pos[j, 0] - pos[i, 0]),
                        abs(pos[j, 2] - pos[i, 2]))
                if d <= min(wr[i], 25.0):
                    want.add(j)
        assert rows[i] == want, f"slot {i}"


def test_judge_sample_flags_divergent_interest_set():
    ap = audit.AuditPlane("jud", sample_every=1, cohort=8)
    try:
        pos = np.zeros((3, 3), np.float32)
        pos[1, 0] = 5.0   # within radius of slot 0
        pos[2, 0] = 90.0  # far away
        alive = np.ones(3, bool)
        owner = {0: "E0", 1: "E1", 2: "E2"}
        good = {"E0": {"E1"}, "E1": {"E0"}, "E2": set()}
        ap.judge_sample(tick=1, pos=pos, alive=alive,
                        watch_radius=None, radius=10.0,
                        cohort_slots=[0, 1, 2], owner=owner,
                        interest=good)
        assert ap.oracle_stats["mismatches"] == 0
        assert not ap.ledger.violations
        bad = {"E0": {"E1", "E2"}, "E1": set(), "E2": set()}
        ap.judge_sample(tick=2, pos=pos, alive=alive,
                        watch_radius=None, radius=10.0,
                        cohort_slots=[0, 1, 2], owner=owner,
                        interest=bad)
        assert ap.oracle_stats["mismatches"] == 2
        kinds = {v["kind"] for v in ap.ledger.violations}
        assert kinds == {"aoi_oracle"}
        details = " ".join(v["detail"] for v in ap.ledger.violations)
        assert "E0" in details and "extra ['E2']" in details
        assert "E1" in details and "missing ['E0']" in details
        snap = ap.snapshot(tick=2)
        assert snap["oracle"]["samples"] == 2
        assert snap["oracle"]["entities_checked"] == 6
    finally:
        ap.close()


def test_skip_sample_records_honest_reasons():
    ap = audit.AuditPlane("skp", sample_every=4, cohort=8)
    try:
        assert ap.want_sample(8) and not ap.want_sample(9)
        ap.skip_sample("overflow", 8)
        ap.skip_sample("overflow", 12)
        ap.skip_sample("pipeline_decode", 16)
        snap = ap.snapshot(tick=16)
        assert snap["oracle"]["skipped"] == {"overflow": 2,
                                             "pipeline_decode": 1}
        assert snap["oracle"]["samples"] == 0
    finally:
        ap.close()


def test_take_violation_fires_once_per_note():
    ap = audit.AuditPlane("tv", sample_every=1, cohort=1)
    try:
        assert ap.take_violation() is None
        ap.ledger.note_violation("aoi_oracle", "EntityID EX diverged", 3)
        v = ap.take_violation()
        assert v is not None and v.startswith("aoi_oracle:")
        assert ap.take_violation() is None  # consumed
    finally:
        ap.close()


def test_registry_weakref_and_census_probe():
    ap = audit.AuditPlane("wk", sample_every=1, cohort=1)
    audit.register("wk", ap)
    probe = audit.CensusProbe(
        lambda eids: {"kind": "dispatcher", "entities": 2, "games": {}})
    audit.register("disp", probe)
    snap = audit.snapshot_all()
    assert snap["wk"]["kind"] == "game"
    assert snap["disp"]["entities"] == 2
    # a failing provider serves an honest error, never raises
    bad = audit.CensusProbe(lambda eids: 1 / 0)
    assert "error" in bad.snapshot()
    audit.unregister("disp")
    ap.close()
    del ap
    import gc

    gc.collect()
    # the registry holds weak references: dropping the plane removes
    # its entry with no unregister call (other suites' still-alive
    # planes may remain registered — only OUR names must be gone)
    after = audit.snapshot_all()
    assert "wk" not in after and "disp" not in after


# =======================================================================
# flight-recorder trigger
# =======================================================================
def test_audit_violation_trigger_freezes_with_context():
    led = audit.EntityLedger("trg")
    clock = [0.0]
    rec = flightrec.FlightRecorder(
        ring=16, cooldown_secs=30.0, clock=lambda: clock[0],
        context_fn=lambda: {"audit": led.incident_context()})
    led.on_create("E1", "Mob", 1)
    led.on_destroy("E9", 2)  # destroy_unknown
    frame = {"tick": 2, "audit_violation": led.take_violation()}
    out = rec.record(frame)
    assert len(out) == 1 and out[0]["trigger"] == "audit_violation"
    assert "E9" in out[0]["detail"]
    ctx = out[0]["context"]["audit"]
    assert any(ev[2] == "destroy_unknown" for ev in ctx["tail"]
               if ev[1] == "VIOLATION")
    # no pending violation -> no trigger
    assert rec.record({"tick": 3}) == []
    # cooldown dedups a repeat inside the window
    led.on_destroy("E9", 4)
    clock[0] = 5.0
    assert rec.record({"tick": 4,
                       "audit_violation": led.take_violation()}) == []


# =======================================================================
# live world: oracle exactness + migration round trip, zero device
# syncs beyond the tick's own fetch
# =======================================================================
@pytest.fixture(scope="module")
def audited_world():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.ops.aoi import GridSpec

    class Mob(Entity):
        ATTRS = {"hp": "allclients hot:0"}

    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=30.0, extent_x=200.0, extent_z=200.0),
        input_cap=64,
    )
    w = World(cfg, n_spaces=1, game_id=931, audit=True,
              audit_sample_every=1, audit_cohort=64)
    w.register_entity("Mob", Mob)
    w.register_space("Arena", Space)
    w.create_nil_space()
    sp = w.create_space("Arena")
    rng = np.random.default_rng(31)
    ents = []
    for _ in range(12):
        x, z = rng.uniform(20.0, 180.0, 2)
        ents.append(sp.create_entity("Mob",
                                     pos=(float(x), 0.0, float(z))))
    for _ in range(6):
        w.tick()
    yield w, ents
    audit.unregister("game931")
    w.audit.close()


def test_live_world_oracle_is_clean(audited_world):
    w, _ = audited_world
    ap = w.audit
    ap.drain()
    snap = ap.snapshot(tick=w.tick_count)
    assert snap["oracle"]["samples"] > 0
    assert snap["oracle"]["entities_checked"] > 0
    assert snap["oracle"]["mismatches"] == 0
    assert snap["probes"]["mismatches"] == 0
    assert snap["violations_total"] == {}
    v = audit.conservation_verdict([snap])
    assert v["ok"], v["problems"]


def test_live_world_migration_roundtrip_stamps_seq(audited_world):
    w, ents = audited_world
    ap = w.audit
    e = next(x for x in ents
             if not x.destroyed and x._migrating is None)
    data = w.get_migrate_data(e)
    assert data["own_seq"] >= 2  # created at 1, bumped for the hop
    before_out = ap.ledger.migrated_out
    w.remove_for_migration(e)
    assert ap.ledger.migrated_out == before_out + 1
    moved = w.restore_from_migration(data)
    assert moved.id == e.id
    w.tick()
    ap.drain()
    snap = ap.snapshot(tick=w.tick_count)
    assert snap["violations_total"] == {}
    assert snap["in_flight"] == []  # round trip retired the record
    v = audit.conservation_verdict([snap])
    assert v["ok"], v["problems"]


# =======================================================================
# /audit endpoint
# =======================================================================
def test_audit_endpoint_serves_registered_planes():
    ap = audit.AuditPlane("game44", sample_every=4, cohort=8)
    audit.register("game44", ap)
    ap.ledger.on_create("E1", "Mob", 1)
    srv = debug_http.start(0, process_name="game44")
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/audit", timeout=5) as r:
            payload = json.loads(r.read())
        snap = payload["game44"]
        for key in ("kind", "entities", "crc", "census", "in_flight",
                    "oracle", "probes", "scrub", "violations_total"):
            assert key in snap
        assert snap["entities"] == 1
        # ?eids=1 ships the bounded list
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/audit?eids=1",
                timeout=5) as r:
            assert json.loads(r.read())["game44"]["eids"] == ["E1"]
        audit.unregister("game44")
        ap.close()
        del ap
        import gc

        gc.collect()
        # weakref registry: the dropped plane is gone (other tests'
        # module-scoped worlds may still be registered, so check the
        # name, not emptiness)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/audit", timeout=5) as r:
            after = json.loads(r.read())
        assert "game44" not in after
    finally:
        srv.shutdown()


# =======================================================================
# tooling: aggregator line, strict scraping, incident bundles
# =======================================================================
def test_obs_aggregate_audit_line_formats_verdict():
    agg_tool = _load_tool("obs_aggregate")
    a = audit.EntityLedger("g1")
    for i in range(3):
        a.on_create(f"E{i}", "Mob", 1)
    v = audit.conservation_verdict([a.snapshot(tick=2)])
    v["oracle_samples"] = 7
    line = agg_tool.audit_line({"audit": v})
    assert line.startswith("deployment conservation PASS live=3")
    assert "7 oracle samples" in line
    a.stamp_migrate_out("E0", 2)
    bad = audit.conservation_verdict([a.snapshot(tick=60)])
    line = agg_tool.audit_line({"audit": bad})
    assert line.startswith("deployment conservation FAIL")
    assert "\n  audit: lost EntityID E0" in line
    assert agg_tool.audit_line({"audit": {"games": 0}}) == ""


def test_scrape_audit_strict_collects_unreachable():
    scrape = _load_tool("scrape_metrics")
    targets = [("game1", "http://127.0.0.1:9/metrics")]  # dead port
    assert scrape.scrape_audit(targets) == {}  # silent default
    errors: list = []
    assert scrape.scrape_audit(targets, errors=errors) == {}
    assert len(errors) == 1 and errors[0].startswith(
        "game1: http://127.0.0.1:9/audit failed")


def test_scrape_audit_lines_format():
    scrape = _load_tool("scrape_metrics")
    led = audit.EntityLedger("game2")
    led.on_create("E1", "Mob", 1)
    snap = led.snapshot(tick=1)
    snap["oracle"] = {"samples": 4, "mismatches": 0}
    scraped = {
        "game1": {"game2": snap},
        "dispatcher1": {"dispatcher1": {
            "kind": "dispatcher", "entities": 1,
            "games": {"2": {"count": 1}}}},
    }
    lines = scrape.audit_lines(scraped)
    assert any("game1: audit game2 live=1" in ln
               and "oracle 4 samples" in ln and ln.endswith("OK")
               for ln in lines)
    assert any("dispatcher1: audit routes 1 entities over 1 games"
               in ln for ln in lines)


def test_cmd_incidents_writes_postmortem_bundle(tmp_path):
    from goworld_tpu import cli

    led = audit.EntityLedger("game1")
    rec = flightrec.FlightRecorder(
        ring=16, context_fn=lambda: {"audit": led.incident_context()})
    flightrec.register("game1", rec)
    led.on_destroy("Egone", 2)
    frozen = rec.record({"tick": 2,
                         "audit_violation": led.take_violation()})
    assert frozen  # the incident the bundle must capture
    srv = debug_http.start(0, process_name="game1")
    try:
        port = srv.server_address[1]
        ini = tmp_path / "goworld.ini"
        ini.write_text(
            "[dispatcher1]\nport = 14391\n"
            f"[game1]\nhttp_port = {port}\n"
            "[gate1]\nport = 15391\n")
        out = tmp_path / "bundles"
        assert cli.cmd_incidents(str(tmp_path), out=str(out)) == 0
        bundle = next(p for p in out.iterdir()
                      if p.name.startswith("incidents_"))
        manifest = json.loads((bundle / "manifest.json").read_text())
        (label, entry), = manifest["processes"].items()
        assert sum(entry["incidents"].values()) >= 1
        payload = json.loads((bundle / entry["file"]).read_text())
        inc = payload["game1"]["incidents"][-1]
        assert inc["trigger"] == "audit_violation"
        assert "Egone" in inc["detail"]
    finally:
        srv.shutdown()
        flightrec.unregister("game1")


def test_cmd_incidents_unreachable_cluster_fails(tmp_path):
    from goworld_tpu import cli

    (tmp_path / "goworld.ini").write_text(
        "[dispatcher1]\nport = 14392\n"
        "[game1]\nhttp_port = 9\n"        # dead port
        "[gate1]\nport = 15392\n")
    assert cli.cmd_incidents(str(tmp_path)) == 1


# =======================================================================
# trailer coexistence: TRACE (bit 15) + AGE (bit 14) on one packet
# =======================================================================
def _sync_packet() -> Packet:
    p = new_packet(proto.MT_SYNC_POSITION_YAW_ON_CLIENTS)
    p.append_u16(1)
    p.append_bytes(b"y" * 64)
    return p


def test_both_trailers_ride_one_packet_any_attach_order():
    from goworld_tpu.utils import syncage, tracing

    legacy = wire_payload(_sync_packet())

    def build(order):
        p = _sync_packet()
        for attr in order:
            if attr == "age":
                p.age = syncage.SyncAgeStamp(3, 10, 20, 30, 40, 0)
            else:
                p.trace = tracing.TraceContext(b"\x11" * 16,
                                               b"\x22" * 8, 1)
        return wire_payload(p)

    w1 = build(("age", "trace"))
    w2 = build(("trace", "age"))
    # attach order is irrelevant: the wire layout is fixed (age inner,
    # trace outermost) so both orders serialize byte-identically
    assert w1 == w2
    head = int.from_bytes(w1[:2], "little")
    assert head & AGE_FLAG and head & TRACE_FLAG
    mt, back = decode_wire(w1)
    assert mt == proto.MT_SYNC_POSITION_YAW_ON_CLIENTS
    assert back.age is not None and back.age.seq == 3
    assert back.trace is not None and back.trace.trace_id == b"\x11" * 16
    # handlers see the exact unstamped payload
    assert bytes(back.buf) == legacy
    # and with both planes off the wire is byte-identical legacy
    assert wire_payload(_sync_packet()) == legacy
    assert not int.from_bytes(legacy[:2], "little") & (AGE_FLAG
                                                       | TRACE_FLAG)


def test_live_flush_carries_both_trailers_under_audit(audited_world):
    """The audited world's GameServer flush emits an AGE-stamped sync
    packet; adding a trace context on top must coexist and strip back
    to the identical payload — the satellite's live loopback."""
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.utils import tracing

    w, _ = audited_world

    class _Cap:
        def __init__(self):
            self.wires = []

        def send(self, p):
            p.trace = tracing.TraceContext(b"\x07" * 16, b"\x08" * 8, 1)
            self.wires.append(wire_payload(p))
            p.release()

    gs = GameServer(95, w, [], gc_freeze_on_boot=False)
    conn = _Cap()
    gs.cluster.select_by_gate_id = lambda gid: conn
    cids = np.asarray([b"C%015d" % i for i in range(3)], "S16")
    eids = np.asarray([b"E%015d" % i for i in range(3)], "S16")
    gs._sync_sink(1, cids, eids, np.ones((3, 4), np.float32))
    gs._flush_sync_out()
    assert len(conn.wires) == 1
    head = int.from_bytes(conn.wires[0][:2], "little")
    assert head & AGE_FLAG and head & TRACE_FLAG
    mt, back = decode_wire(conn.wires[0])
    assert mt == proto.MT_SYNC_POSITION_YAW_ON_CLIENTS
    assert back.age is not None and back.trace is not None
    assert back.age.seq == w.sync_age_anchor[0]
