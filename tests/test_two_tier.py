"""Churn-adaptive two-tier extraction (ops/extract.two_tier).

Contract under test: identical outputs to the single-graph version on
every path, a REAL lax.cond branch for unbatched callers (ordinary
ticks skip the full-cap extraction work), and NO cond under vmap —
batching would lower cond to select_n and execute both tiers, so the
batched trace must contain the single full-tier graph only.
"""

import numpy as np

import jax
import jax.numpy as jnp

from goworld_tpu.ops.delta import interest_pairs
from goworld_tpu.ops.extract import SMALL_TIER_ROWS, bounded_extract_rows


def _mask(n, k, hot_rows, seed):
    rng = np.random.default_rng(seed)
    m = np.zeros((n, k), bool)
    rows = rng.choice(n, hot_rows, replace=False)
    for r in rows:
        m[r, rng.choice(k, rng.integers(1, 4), replace=False)] = True
    return m


def test_rows_small_tier_matches_full_tier_output():
    n, k = SMALL_TIER_ROWS * 2, 8
    cap = n  # cap_rows = n > SMALL_TIER_ROWS: tiering active
    for hot, seed in ((50, 0), (SMALL_TIER_ROWS + 7, 1)):
        m = jnp.asarray(_mask(n, k, hot, seed))
        flat, valid, count = bounded_extract_rows(m, cap)
        # oracle: plain flat nonzero semantics
        want = np.flatnonzero(np.asarray(m).ravel())
        got = np.asarray(flat)[np.asarray(valid)]
        assert int(count) == want.size
        assert np.array_equal(got, want[:got.size])


def test_unbatched_trace_has_cond_batched_has_none():
    n, k = SMALL_TIER_ROWS * 2, 4
    m = jnp.zeros((n, k), bool)

    unbatched = str(jax.make_jaxpr(
        lambda x: bounded_extract_rows(x, n)
    )(m))
    assert "cond" in unbatched

    batched = str(jax.make_jaxpr(
        jax.vmap(lambda x: bounded_extract_rows(x, n))
    )(m[None]))
    assert "cond" not in batched


def test_vmapped_interest_pairs_matches_unbatched():
    n, k = SMALL_TIER_ROWS + 32, 8
    rng = np.random.default_rng(3)
    old = np.sort(rng.integers(0, n + 1, (n, k)).astype(np.int32), axis=1)
    new = old.copy()
    rows = rng.choice(n, 40, replace=False)
    new[rows] = np.sort(
        rng.integers(0, n + 1, (40, k)).astype(np.int32), axis=1
    )
    old_j, new_j = jnp.asarray(old), jnp.asarray(new)
    flat = interest_pairs(old_j, new_j, n, 256, 256, n)
    vm = jax.vmap(
        lambda a, b: interest_pairs(a, b, n, 256, 256, n)
    )(old_j[None], new_j[None])
    for a, b in zip(flat, vm):
        assert np.array_equal(np.asarray(a), np.asarray(b)[0])
