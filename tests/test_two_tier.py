"""Churn-adaptive two-tier extraction (ops/extract.two_tier).

Contract under test: identical outputs to the single-graph version on
every path, a REAL lax.cond branch for unbatched callers (ordinary
ticks skip the full-cap extraction work), and NO cond under vmap —
batching would lower cond to select_n and execute both tiers, so the
batched trace must contain the single full-tier graph only.
"""

import numpy as np

import jax
import jax.numpy as jnp

from goworld_tpu.ops.delta import interest_pairs
from goworld_tpu.ops.extract import SMALL_TIER_ROWS, bounded_extract_rows


def _mask(n, k, hot_rows, seed):
    rng = np.random.default_rng(seed)
    m = np.zeros((n, k), bool)
    rows = rng.choice(n, hot_rows, replace=False)
    for r in rows:
        m[r, rng.choice(k, rng.integers(1, 4), replace=False)] = True
    return m


def test_rows_small_tier_matches_full_tier_output():
    n, k = SMALL_TIER_ROWS * 2, 8
    cap = n  # cap_rows = n > SMALL_TIER_ROWS: tiering active
    for hot, seed in ((50, 0), (SMALL_TIER_ROWS + 7, 1)):
        m = jnp.asarray(_mask(n, k, hot, seed))
        flat, valid, count = bounded_extract_rows(m, cap)
        # oracle: plain flat nonzero semantics
        want = np.flatnonzero(np.asarray(m).ravel())
        got = np.asarray(flat)[np.asarray(valid)]
        assert int(count) == want.size
        assert np.array_equal(got, want[:got.size])


def test_adaptive_flag_controls_cond():
    n, k = SMALL_TIER_ROWS * 2, 4
    m = jnp.zeros((n, k), bool)

    adaptive = str(jax.make_jaxpr(
        lambda x: bounded_extract_rows(x, n)
    )(m))
    assert "cond" in adaptive

    fixed = str(jax.make_jaxpr(
        lambda x: bounded_extract_rows(x, n, adaptive=False)
    )(m))
    assert "cond" not in fixed


def test_vmapped_world_tick_has_no_cond():
    """The VMAPPED multi-space World path (n_spaces > 1,
    jit(vmap(tick_body))) must carry NO runtime cond: under vmap
    batching cond lowers to select_n and BOTH branches would execute
    every tick (the churn tiers AND the Verlet skin's rebuild/reuse
    dispatch). Tracer introspection cannot see this through the
    collectors' own jit boundary (pjit batches the traced jaxpr), so
    the manager threads adaptive_extract=False / skin=0 statically —
    this test pins that wiring end to end. The SINGLE-space local step
    (the common production shape) now calls tick_body directly instead
    of vmapping over one space, so there the real branches survive —
    pinned too."""
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.core.step import TickInputs, tick_body
    from goworld_tpu.entity.manager import _make_local_tick
    from goworld_tpu.ops.aoi import GridSpec

    cfg = WorldConfig(
        capacity=SMALL_TIER_ROWS * 2,
        grid=GridSpec(radius=20.0, extent_x=200.0, extent_z=200.0,
                      k=8, cell_cap=8, row_block=1024, skin=2.0),
    )
    from goworld_tpu.core.state import create_state

    st = create_state(cfg)
    st_b2 = jax.tree.map(lambda x: jnp.stack([x, x]), st)
    ins_b2 = jax.tree.map(lambda x: jnp.stack([x, x]),
                          TickInputs.empty(cfg))
    import dataclasses as _dc

    cfg_off = _dc.replace(cfg, adaptive_extract=False,
                          grid=_dc.replace(cfg.grid, skin=0.0))
    batched = str(jax.make_jaxpr(
        jax.vmap(lambda s, i: tick_body(cfg_off, s, i, None))
    )(st_b2, ins_b2))
    assert "cond" not in batched
    # the manager's multi-space step must be built with the flag off
    # and the skin cleared even though the caller's cfg has them on
    step = _make_local_tick(cfg, 2)
    mgr = str(jax.make_jaxpr(lambda s, i: step(s, i, None))(
        st_b2, ins_b2))
    assert "cond" not in mgr
    # while the unbatched tick keeps the real branches (churn tiers +
    # verlet rebuild dispatch) ...
    unbatched = str(jax.make_jaxpr(
        lambda s, i: tick_body(cfg, s, i, None)
    )(st, TickInputs.empty(cfg)))
    assert "cond" in unbatched
    # ... and so does the manager's SINGLE-space local step
    st_b1 = jax.tree.map(lambda x: x[None], st)
    ins_b1 = jax.tree.map(lambda x: x[None], TickInputs.empty(cfg))
    step1 = _make_local_tick(cfg, 1)
    mgr1 = str(jax.make_jaxpr(lambda s, i: step1(s, i, None))(
        st_b1, ins_b1))
    assert "cond" in mgr1


def test_vmapped_interest_pairs_matches_unbatched():
    n, k = SMALL_TIER_ROWS + 32, 8
    rng = np.random.default_rng(3)
    old = np.sort(rng.integers(0, n + 1, (n, k)).astype(np.int32), axis=1)
    new = old.copy()
    rows = rng.choice(n, 40, replace=False)
    new[rows] = np.sort(
        rng.integers(0, n + 1, (40, k)).astype(np.int32), axis=1
    )
    old_j, new_j = jnp.asarray(old), jnp.asarray(new)
    flat = interest_pairs(old_j, new_j, n, 256, 256, n)
    vm = jax.vmap(
        lambda a, b: interest_pairs(a, b, n, 256, 256, n)
    )(old_j[None], new_j[None])
    for a, b in zip(flat, vm):
        assert np.array_equal(np.asarray(a), np.asarray(b)[0])
