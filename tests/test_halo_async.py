"""halo_impl parity: the async (Pallas make_async_remote_copy, packed
dirty-only payload) halo exchange must be BIT-identical to the ppermute
impl — ghost blocks AND demand gauges — for 1D strips and 2D tiles,
across dirty/visible permutations and halo_cap overflow (ISSUE 10).

Off-TPU the async kernel runs in interpret mode behind
ops/pallas_compat.interpret_default (one-time warning, never a CPU
default) — exactly the configuration tier-1 exercises here.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from goworld_tpu.parallel.halo import (  # noqa: E402
    exchange_halo,
    exchange_halo_2d,
    meta_gid_bound,
)
from goworld_tpu.parallel.mesh import (  # noqa: E402
    SPACE_AXIS,
    make_mesh,
    shard_map_norep,
)

pytestmark = [pytest.mark.pallas, pytest.mark.multichip]

N_DEV = 8
N = 64
TILE_W = 100.0
TILE_D = 100.0
RADIUS = 25.0  # wide strips: plenty of rows to permute and overflow


def _world(seed: int, dirty_frac: float, alive_frac: float,
           two_d: bool):
    """Random per-shard world arrays in GLOBAL coordinates, leading
    [n_dev] axis."""
    rng = np.random.default_rng(seed)
    tx, tz = (4, 2) if two_d else (N_DEV, 1)
    pos = np.zeros((N_DEV, N, 3), np.float32)
    for d in range(N_DEV):
        ix, iz = d // tz, d % tz
        pos[d, :, 0] = ix * TILE_W + rng.uniform(0, TILE_W, N)
        pos[d, :, 2] = (iz * TILE_D + rng.uniform(0, TILE_D, N)
                        if two_d else rng.uniform(0, TILE_D, N))
    yaw = rng.uniform(-np.pi, np.pi, (N_DEV, N)).astype(np.float32)
    dirty = rng.uniform(size=(N_DEV, N)) < dirty_frac
    alive = rng.uniform(size=(N_DEV, N)) < alive_frac
    return (jnp.asarray(pos), jnp.asarray(yaw), jnp.asarray(dirty),
            jnp.asarray(alive))


def _exchange(impl: str, two_d: bool, halo_cap: int, world):
    mesh = make_mesh(N_DEV)

    def fn(pos, yaw, dirty, alive):
        pos, yaw, dirty, alive = pos[0], yaw[0], dirty[0], alive[0]
        if two_d:
            out = exchange_halo_2d(
                SPACE_AXIS, (4, 2), N, pos, yaw, dirty, alive,
                TILE_W, TILE_D, RADIUS, halo_cap, impl=impl,
            )
        else:
            out = exchange_halo(
                SPACE_AXIS, N_DEV, pos, yaw, dirty, alive,
                TILE_W, RADIUS, halo_cap, impl=impl,
            )
        return jax.tree.map(lambda x: x[None], out)

    mapped = shard_map_norep(
        fn, mesh=mesh, in_specs=(P(SPACE_AXIS),) * 4,
        out_specs=P(SPACE_AXIS),
    )
    return [np.asarray(x) for x in jax.jit(mapped)(*world)]


NAMES = ("gpos", "gyaw", "gdirty", "gvalid", "ggid", "strip_demand")


@pytest.mark.parametrize("two_d", [False, True], ids=["1d", "2d"])
@pytest.mark.parametrize("dirty_frac,alive_frac", [
    (0.0, 1.0),    # nobody dirty
    (1.0, 1.0),    # everybody dirty
    (0.4, 0.7),    # mixed dirty + dead rows (visibility filter)
], ids=["clean", "all-dirty", "mixed"])
def test_async_bit_identical(two_d, dirty_frac, alive_frac):
    world = _world(3, dirty_frac, alive_frac, two_d)
    ref = _exchange("ppermute", two_d, 32, world)
    got = _exchange("async", two_d, 32, world)
    for name, r, g in zip(NAMES, ref, got):
        assert r.dtype == g.dtype, name
        assert np.array_equal(r, g), (
            f"{name} diverges between impls "
            f"({(r != g).sum()} of {r.size} lanes)"
        )


@pytest.mark.parametrize("two_d", [False, True], ids=["1d", "2d"])
def test_async_bit_identical_under_overflow(two_d):
    """halo_cap far below the strip occupancy: the overflow rows must
    drop IDENTICALLY (bounded_extract slot order is shared) and the
    demand gauge must report the same true occupancy."""
    world = _world(7, 0.5, 1.0, two_d)
    cap = 4   # RADIUS/TILE_W = 25% of 64 rows per strip >> 4
    ref = _exchange("ppermute", two_d, cap, world)
    got = _exchange("async", two_d, cap, world)
    for name, r, g in zip(NAMES, ref, got):
        assert np.array_equal(r, g), f"{name} diverges under overflow"
    demand = ref[-1]
    assert (demand > cap).any(), (
        "overflow case never exceeded halo_cap — the test shape is "
        "not exercising the drop path"
    )


def test_async_ghosts_nonempty():
    """The parity cases must actually ship ghosts (an all-empty
    exchange would pass parity vacuously)."""
    world = _world(3, 0.4, 0.7, False)
    got = _exchange("async", False, 32, world)
    gvalid = got[3]
    assert gvalid.any(), "no ghosts shipped at 25% strip width"
    # interior shards receive from both sides
    assert gvalid[3].any() and gvalid[4].any()


def test_meta_gid_bound_guard():
    """MegaConfig refuses async when gids overflow the packed meta
    word (the 29-bit bound halo._pack_strip documents)."""
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.ops.aoi import GridSpec
    from goworld_tpu.parallel.megaspace import MegaConfig

    cap = (meta_gid_bound() // 2) + 1  # 2 devices -> gids past bound
    cfg = WorldConfig(
        capacity=cap,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=1024),
    )
    with pytest.raises(ValueError, match="29-bit"):
        MegaConfig(cfg=cfg, n_dev=2, tile_w=100.0, halo_impl="async")
    with pytest.raises(ValueError, match="halo_impl"):
        MegaConfig(cfg=WorldConfig(
            capacity=64,
            grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                          k=8, cell_cap=16, row_block=64),
        ), n_dev=2, tile_w=100.0, halo_impl="bogus")
