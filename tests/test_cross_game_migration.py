"""Cluster-level 3-phase entity migration across game processes.

The hardest distributed protocol in the system (reference
``Entity.go:956-1115`` EnterSpace -> OnMigrateOut -> real migrate, and
``DispatcherService.go:834-891`` query-space-gameid -> block+queue ->
real-migrate -> unblock): an avatar on game1 enters a space hosted by
game2 while client RPCs are in flight. The dispatcher must queue every
packet aimed at the migrating entity and flush it to the new game, so no
RPC is ever lost; attrs, timers and the client binding must survive the
hop. Also covers the cancel path (``Entity.go:1014-1023`` cancelEnterSpace
/ MT_CANCEL_MIGRATE): an entity destroyed mid-protocol must not migrate,
and the dispatcher's block must be lifted.
"""

import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net.botclient import BotClient
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec


class Account(Entity):
    ATTRS = {"status": "client"}

    def Login_Client(self, name):
        avatar = self.world.create_entity(
            "Avatar", space=self.world._test_space, pos=(50.0, 0.0, 50.0),
        )
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


class Avatar(Entity):
    ATTRS = {
        "name": "allclients",
        "pings": "client",
        "heartbeats": "client",
    }

    def OnClientConnected(self):
        if self.attrs.get("pings") is None:
            self.attrs["pings"] = 0
        if self.attrs.get("heartbeats") is None:
            self.attrs["heartbeats"] = 0
        self.add_timer(0.05, "Heartbeat")

    def Heartbeat(self):
        self.attrs["heartbeats"] = (self.attrs.get("heartbeats") or 0) + 1

    def Ping_Client(self):
        self.attrs["pings"] = (self.attrs.get("pings") or 0) + 1

    def JumpTo_Client(self, space_id):
        self.enter_space(space_id, (10.0, 0.0, 10.0))

    def JumpAndDie_Client(self, space_id):
        # destroy immediately after requesting the cross-game jump: the
        # protocol must cancel (reference destroyEntity during EnterSpace)
        self.enter_space(space_id, (10.0, 0.0, 10.0))
        self.destroy()

    def OnMigrateIn(self):
        self.call_client("OnArrived", self.world.game_id)


class Arena(Space):
    pass


def _make_world(game_id: int) -> World:
    cfg = WorldConfig(
        capacity=128,
        grid=GridSpec(radius=50.0, extent_x=200.0, extent_z=200.0),
        input_cap=128,
    )
    world = World(cfg, n_spaces=1, game_id=game_id)
    world.register_entity("Account", Account)
    world.register_entity("Avatar", Avatar)
    world.register_space("Arena", Arena)
    world.create_nil_space()
    return world


@pytest.fixture()
def two_game_cluster():
    harness = ClusterHarness(
        n_dispatchers=2, n_gates=1, desired_games=2,
        position_sync_interval_ms=20,
    )
    harness.start()

    worlds, servers, threads = [], [], []
    stop = threading.Event()
    for gid in (1, 2):
        world = _make_world(gid)
        gs = GameServer(
            gid, world, list(harness.dispatcher_addrs),
            boot_entity="Account",
            # all boot entities land on game1; game2 only receives migrants
            ban_boot=(gid == 2),
        )

        def _mk_space(w=world):
            w._test_space = w.create_space("Arena")

        gs.on_deployment_ready = _mk_space
        gs.start_network()

        def loop(gs=gs):
            while not stop.is_set():
                gs.pump()
                gs.tick()
                time.sleep(0.01)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        worlds.append(world)
        servers.append(gs)
        threads.append(t)

    for gs in servers:
        assert gs.ready_event.wait(60), "deployment never became ready"
    # spaces are created on the logic threads after deployment-ready
    deadline = time.time() + 10
    while time.time() < deadline and not all(
        hasattr(w, "_test_space") for w in worlds
    ):
        time.sleep(0.05)
    assert all(hasattr(w, "_test_space") for w in worlds)

    yield harness, worlds, servers
    stop.set()
    for t in threads:
        t.join(timeout=5)
    for gs in servers:
        gs.stop()
    harness.stop()


def _avatar_in(world):
    avs = [e for e in world.entities.values()
           if e.type_name == "Avatar" and not e.destroyed]
    return avs[0] if avs else None


async def _login(bot: BotClient, name: str):
    import asyncio

    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    await asyncio.wait_for(bot.player_ready.wait(), 10)
    bot.call_server("Login_Client", name)
    for _ in range(200):
        if bot.player is not None and bot.player.type_name == "Avatar":
            return recv
        await asyncio.sleep(0.05)
    raise AssertionError("avatar never arrived")


async def _migrate_script(bot: BotClient, space_id: str, n_pings: int):
    import asyncio

    recv = await _login(bot, "bob")
    try:
        # pings in flight BEFORE, DURING and AFTER the jump: the
        # dispatcher's block+queue must deliver every single one
        for _ in range(n_pings // 2):
            bot.call_server("Ping_Client")
        bot.call_server("JumpTo_Client", space_id)
        for _ in range(n_pings - n_pings // 2):
            bot.call_server("Ping_Client")
            await asyncio.sleep(0.002)
        # wait for the migrate-in client RPC
        for _ in range(200):
            if any(m == "OnArrived" for _, m, _ in bot.rpc_log):
                break
            await asyncio.sleep(0.05)
        assert any(m == "OnArrived" for _, m, _ in bot.rpc_log), \
            "client never told about migrate-in"
        await asyncio.sleep(0.5)
    finally:
        recv.cancel()
        await bot.conn.close()
    return True


def test_cross_game_enter_space_with_rpcs_in_flight(two_game_cluster):
    harness, (w1, w2), (gs1, gs2) = two_game_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)
    n_pings = 40

    fut = harness.submit(
        _migrate_script(bot, w2._test_space.id, n_pings)
    )
    fut.result(timeout=60)
    assert not bot.errors, bot.errors

    # the avatar left game1 entirely...
    assert _avatar_in(w1) is None
    # ...and lives on game2, in the target space
    deadline = time.time() + 10
    av = None
    while time.time() < deadline:
        av = _avatar_in(w2)
        if av is not None and (av.attrs.get("pings") or 0) >= n_pings:
            break
        time.sleep(0.05)
    assert av is not None, "avatar never arrived on game2"
    assert av.space is w2._test_space

    # attrs survived
    assert av.attrs.get("name") == "bob"
    # EVERY ping was delivered exactly once (block+queue, no loss): the
    # counter is an attr, so it also proves attr state moved intact
    assert av.attrs.get("pings") == n_pings
    # client binding survived (OnArrived already proves the downstream
    # path; this proves the server-side handle)
    assert av.client is not None
    # timers survived and keep firing on the new game
    assert av.timer_ids, "timers were not restored after migration"
    hb0 = av.attrs.get("heartbeats") or 0
    deadline = time.time() + 5
    while time.time() < deadline:
        if (av.attrs.get("heartbeats") or 0) > hb0:
            break
        time.sleep(0.05)
    assert (av.attrs.get("heartbeats") or 0) > hb0, \
        "migrated timer never fired on game2"


async def _cancel_script(bot: BotClient, space_id: str):
    import asyncio

    recv = await _login(bot, "bob")
    try:
        bot.call_server("JumpAndDie_Client", space_id)
        await asyncio.sleep(1.0)
    finally:
        recv.cancel()
        await bot.conn.close()
    return True


def test_migration_cancelled_when_entity_destroyed(two_game_cluster):
    """Entity destroyed right after requesting the jump: no copy may
    appear on game2, and the dispatcher's entity block must be lifted
    (MT_CANCEL_MIGRATE) so the route table doesn't wedge."""
    harness, (w1, w2), (gs1, gs2) = two_game_cluster

    # destroy() runs in the same handler as enter_space(), i.e. before the
    # query-space ack returns -> exercises the early-out. To exercise the
    # LATE cancel (destroyed between migrate-request and its ack, which
    # must emit MT_CANCEL_MIGRATE), flip a switch in the ack handler:
    orig = gs1._h_query_space_ack

    def late_destroy(pkt):
        orig(pkt)
        for pending in list(gs1._migrating_out.values()):
            pending[0].destroy()

    gs1._h_query_space_ack = late_destroy

    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)
    fut = harness.submit(_cancel_script_late(bot, w2._test_space.id))
    fut.result(timeout=60)
    assert not bot.errors, bot.errors

    time.sleep(1.0)
    assert _avatar_in(w1) is None
    assert _avatar_in(w2) is None, "cancelled migration still migrated"
    # the dispatcher shard must have dropped/unblocked the route: a fresh
    # login + jump must work end to end (would hang if the table wedged)
    gs1._h_query_space_ack = orig
    bot2 = BotClient(host, port, bot_id=2, strict=True)
    fut = harness.submit(_migrate_script(bot2, w2._test_space.id, 4))
    fut.result(timeout=60)
    assert not bot2.errors, bot2.errors


async def _cancel_script_late(bot: BotClient, space_id: str):
    import asyncio

    recv = await _login(bot, "bob")
    try:
        bot.call_server("JumpTo_Client", space_id)  # destroy injected at ack
        await asyncio.sleep(1.0)
    finally:
        recv.cancel()
        await bot.conn.close()
    return True


def test_early_cancel_before_query_ack(two_game_cluster):
    """destroy() in the same handler as enter_space(): the pending
    migration must be dropped at the query-space ack."""
    harness, (w1, w2), (gs1, gs2) = two_game_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)
    fut = harness.submit(_cancel_script(bot, w2._test_space.id))
    fut.result(timeout=60)
    assert not bot.errors, bot.errors
    time.sleep(0.5)
    assert _avatar_in(w1) is None
    assert _avatar_in(w2) is None
    assert not gs1._migrating_out, "pending migration leaked"


def test_enter_space_survives_target_game_death(two_game_cluster):
    """EnterSpace to a space whose hosting game DIED: the dispatcher's
    cleanup dropped the space route (DispatcherService.go:586-634), the
    query ack returns game 0, and the migrating entity must recover —
    alive, in its source space, timers firing, RPCs still served
    (reference semantics: nothing was packed yet, so nothing is lost)."""
    import asyncio

    harness, worlds, servers = two_game_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)

    target_space_id = worlds[1]._test_space.id

    async def script():
        recv = await _login(bot, "carol")
        try:
            # kill game2 and wait for the dispatchers to drop its routes
            servers[1].stop()
            await asyncio.sleep(1.0)
            bot.call_server("JumpTo_Client", target_space_id)
            await asyncio.sleep(1.5)
            # the avatar must still answer RPCs on game1
            before = bot.player.attrs.get("pings") or 0
            bot.call_server("Ping_Client")
            for _ in range(100):
                if (bot.player.attrs.get("pings") or 0) > before:
                    break
                await asyncio.sleep(0.05)
            assert (bot.player.attrs.get("pings") or 0) > before
        finally:
            recv.cancel()
            await bot.conn.close()

    harness.submit(script()).result(timeout=60)
    av = _avatar_in(worlds[0])
    assert av is not None and not av.destroyed
    assert av.space is worlds[0]._test_space        # stayed home
    assert av.slot is not None and av._migrating is None
    # timers kept firing through the failed attempt
    hb = av.attrs.get("heartbeats") or 0
    time.sleep(0.3)
    assert (av.attrs.get("heartbeats") or 0) > hb
    # and the failed migration left no leaked bookkeeping
    assert not servers[0]._migrating_out


def test_create_on_game_and_online_games(two_game_cluster):
    """CreateEntityOnGame pins placement to a specific game (reference
    goworld.go:83) and GetOnlineGames-style views are seeded by the
    handshake and maintained by connect/disconnect notifies."""
    harness, worlds, servers = two_game_cluster
    # both games see the full cluster (game1 joined first, learns of
    # game2 via NOTIFY_GAME_CONNECTED; game2 is seeded by its ack)
    deadline = time.time() + 10
    while time.time() < deadline and not all(
        gs.online_games == {1, 2} for gs in servers
    ):
        time.sleep(0.05)
    assert servers[0].online_games == {1, 2}
    assert servers[1].online_games == {1, 2}

    # pin an entity onto game2 explicitly (the load heap would otherwise
    # prefer either)
    servers[0].create_entity_anywhere("Avatar", {"name": "pinned"},
                                      gameid=2)
    deadline = time.time() + 10
    placed = None
    while time.time() < deadline:
        for e in worlds[1].entities.values():
            if e.type_name == "Avatar" and \
                    e.attrs.get("name") == "pinned":
                placed = e
                break
        if placed is not None:
            break
        time.sleep(0.05)
    assert placed is not None, "pinned entity never appeared on game2"
    assert all(
        e.attrs.get("name") != "pinned"
        for e in worlds[0].entities.values() if e.type_name == "Avatar"
    )
