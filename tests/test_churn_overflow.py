"""Mass-churn overflow behavior: when one tick changes more AOI rows than
delta_rows_cap, events degrade (bounded-queue contract) but the TRUE
demand surfaces and the host names the right knob — and the system
recovers to exact interest sets once churn stops (reference analog: the
pending-queue caps of consts.go:26-28; overflow there drops packets)."""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig, create_state, spawn
from goworld_tpu.core.step import TickInputs, make_tick
from goworld_tpu.ops.aoi import GridSpec, neighbors_oracle


def _world(n=96, delta_rows_cap=8):
    cfg = WorldConfig(
        capacity=n,
        grid=GridSpec(radius=12.0, extent_x=200.0, extent_z=200.0,
                      k=16, cell_cap=32, row_block=n),
        enter_cap=512, leave_cap=512, sync_cap=512,
        attr_sync_cap=64, input_cap=n,
        delta_rows_cap=delta_rows_cap,
    )
    st = create_state(cfg)
    rng = np.random.default_rng(3)
    pts = rng.uniform(10, 190, size=(n, 2))
    for s in range(n):
        st = spawn(st, s, pos=(pts[s, 0], 0.0, pts[s, 1]))
    return cfg, st


def test_mass_teleport_overflows_then_recovers():
    n = 96
    cfg, st = _world(n=n, delta_rows_cap=8)
    tick = make_tick(cfg)
    st, out = tick(st, TickInputs.empty(cfg), None)   # initial interest
    assert int(out.delta_rows_n) > 8                  # spawn wave churns

    # teleport EVERYONE at once: way more changed rows than the cap
    rng = np.random.default_rng(9)
    pts = rng.uniform(10, 190, size=(n, 2))
    ti = TickInputs(
        pos_sync_idx=jnp.arange(n, dtype=jnp.int32),
        pos_sync_vals=jnp.asarray(
            np.stack([pts[:, 0], np.zeros(n), pts[:, 1],
                      np.zeros(n)], axis=1), jnp.float32),
        pos_sync_n=jnp.asarray(n, jnp.int32),
    )
    st, out = tick(st, ti, None)
    drn = int(out.delta_rows_n)
    assert drn > cfg.delta_rows_cap        # true demand surfaces: the
    # row-cap overflow signal — pair counts stay TRUE demand within the
    # selected rows, never fabricated (hosts slice [:min(n, cap)])
    assert 0 < int(out.enter_n) <= cfg.enter_cap
    assert 0 < int(out.leave_n) <= cfg.leave_cap

    # churn stops: within one quiet tick the device's interest lists are
    # EXACT again (the sweep recomputes from scratch; only the emitted
    # event stream degraded during overflow)
    st, out = tick(st, TickInputs.empty(cfg), None)
    nbr = np.asarray(st.nbr)
    oracle = neighbors_oracle(np.asarray(st.pos), np.asarray(st.alive),
                              cfg.grid.radius)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == oracle[i], f"row {i} wrong after recovery"
    assert int(out.delta_rows_n) == 0      # steady state


def test_world_logs_the_right_knob(caplog):
    """The host's overflow warning must blame delta_rows_cap, not the
    enter/leave caps (review finding from this round: a saturated count
    would otherwise direct the operator to widen the wrong knob)."""
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space

    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=12.0, extent_x=200.0, extent_z=200.0,
                      k=16, cell_cap=32, row_block=64),
        enter_cap=512, leave_cap=512, sync_cap=512,
        attr_sync_cap=64, input_cap=64,
        delta_rows_cap=4,
    )
    w = World(cfg)

    class Arena(Space):
        pass

    class Npc(Entity):
        pass

    w.registry.register("Arena", Arena, is_space=True)
    w.registry.register("Npc", Npc)
    arena = w.create_space("Arena")
    rng = np.random.default_rng(1)
    for _ in range(40):
        w.create_entity("Npc", space=arena,
                        pos=(rng.uniform(10, 60), 0, rng.uniform(10, 60)))
    with caplog.at_level(logging.WARNING):
        w.tick()
    msgs = [r.message for r in caplog.records]
    assert any("delta_rows_cap" in m for m in msgs), msgs
