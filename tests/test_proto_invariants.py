"""Message-type space invariants (ISSUE 2 CI satellite).

The dispatcher/gate route packets by RANGE (net/proto.py: 1-999
dispatcher-routed, 1000-1499 gate redirect, 1500-1999 gate service,
2000+ client-direct), and the tracing layer claims bit 15 of the u16
msgtype field for the trace-context trailer (net/packet.py TRACE_FLAG).
A future MT_ constant outside its documented range — or colliding with
the trace bit — would mis-route silently; this guards both."""

from goworld_tpu.net import packet, proto

# the documented routing ranges (inclusive); 2000.. is the client-direct
# space, capped where the trace bit begins
RANGES = (
    (0, 999, "dispatcher-routed"),
    (1000, 1499, "gate redirect"),
    (1500, 1999, "gate service"),
    (2000, packet.MSGTYPE_MASK, "client-direct"),
)


def _mt_constants() -> dict[str, int]:
    return {
        name: val for name, val in vars(proto).items()
        if name.startswith("MT_") and isinstance(val, int)
    }


def test_every_msgtype_lives_in_a_documented_range():
    for name, val in _mt_constants().items():
        assert any(lo <= val <= hi for lo, hi, _ in RANGES), \
            f"{name}={val} is outside every documented routing range"


def test_msgtypes_never_collide_with_trace_flag():
    """Bit 15 is the trace-trailer marker: setting it on any real
    msgtype must be reversible (mask restores the original), which
    requires every constant to keep the bit clear."""
    for name, val in _mt_constants().items():
        assert val & packet.TRACE_FLAG == 0, \
            f"{name}={val} collides with TRACE_FLAG"
        assert (val | packet.TRACE_FLAG) & packet.MSGTYPE_MASK == val


def test_msgtypes_never_collide_with_age_flag():
    """Bit 14 is the sync-age-stamp trailer marker (net/packet.py
    AGE_FLAG, utils/syncage.py): every real msgtype must keep it clear
    so setting and masking the flag is reversible, exactly like the
    trace flag above."""
    for name, val in _mt_constants().items():
        assert val & packet.AGE_FLAG == 0, \
            f"{name}={val} collides with AGE_FLAG"
    # bit 14 sits INSIDE MSGTYPE_MASK: masking a raw wire msgtype with
    # MSGTYPE_MASK strips the trace flag but NOT the age flag, so
    # decode_wire's explicit AGE_FLAG strip is load-bearing — any
    # routing shortcut that only applies MSGTYPE_MASK would misroute
    # stamped packets (this pins the fact the strip code relies on)
    assert packet.AGE_FLAG & packet.MSGTYPE_MASK == packet.AGE_FLAG
    assert packet.TRACE_FLAG & packet.MSGTYPE_MASK == 0


def test_msgtypes_are_unique():
    consts = _mt_constants()
    by_val: dict[int, list[str]] = {}
    for name, val in consts.items():
        by_val.setdefault(val, []).append(name)
    dupes = {v: names for v, names in by_val.items() if len(names) > 1}
    assert not dupes, f"duplicate msgtype values: {dupes}"


def test_range_markers_bracket_their_constants():
    """Constants named into the redirect / gate-service ranges must sit
    strictly between their START/STOP markers."""
    consts = _mt_constants()
    redirect_lo = consts["MT_REDIRECT_TO_GATEPROXY_MSG_TYPE_START"]
    redirect_hi = consts["MT_REDIRECT_TO_GATEPROXY_MSG_TYPE_STOP"]
    service_lo = consts["MT_GATE_SERVICE_MSG_TYPE_START"]
    service_hi = consts["MT_GATE_SERVICE_MSG_TYPE_STOP"]
    assert (redirect_lo, redirect_hi) == (1000, 1499)
    assert (service_lo, service_hi) == (1500, 1999)
    for name, val in consts.items():
        if "START" in name or "STOP" in name:
            continue
        if redirect_lo < val < redirect_hi:
            # gate relays these verbatim to the owning client — they
            # must carry the [gate_id][client_id] routing prefix, which
            # only redirect-range pack helpers write
            assert name.endswith("_ON_CLIENT") or name in (
                "MT_CLEAR_CLIENT_FILTER_PROP",
            ), f"{name}={val} squats in the redirect range"
        if service_lo < val < service_hi:
            assert name in (
                "MT_SET_CLIENT_FILTER_PROP",
                "MT_CALL_FILTERED_CLIENTS",
                "MT_SYNC_POSITION_YAW_ON_CLIENTS",
                # the delta-compressed sync leg (ISSUE 12): handled by
                # the gate itself like its full-record sibling
                "MT_SYNC_POSITION_YAW_DELTA_ON_CLIENTS",
                "MT_CLIENT_EVENTS_BATCH",
            ), f"{name}={val} squats in the gate-service range"


def test_trace_trailer_roundtrips_on_every_range():
    """A traced packet built at any range decodes to the same msgtype
    and payload with the context recovered."""
    from goworld_tpu.utils import tracing

    for mt in (proto.MT_CALL_ENTITY_METHOD,
               proto.MT_CALL_ENTITY_METHOD_ON_CLIENT,
               proto.MT_CLIENT_EVENTS_BATCH,
               proto.MT_HEARTBEAT):
        p = packet.new_packet(mt)
        p.append_var_str("payload")
        p.trace = tracing.new_trace()
        wire = packet.wire_payload(p)
        mt2, q = packet.decode_wire(wire)
        assert mt2 == mt
        assert q.trace is not None
        assert q.trace.trace_id == p.trace.trace_id
        assert q.read_var_str() == "payload"
        assert q.remaining() == 0
