"""Resident-world runtime (ISSUE 20): carry donation end-to-end.

The contract under test: with ``resident=True`` (the default) the tick
is compiled with ``donate_argnums`` on the SpaceState carry, so (1) the
old carry is DELETED after every dispatch and any stale host read
raises instead of silently serving dead lanes, (2) every plane that
used to hold a state reference across ticks — async checkpoint, the
snapshot-chain capture, the residency census, the governor's
``carry_state`` — is fenced (pinned device copies / post-dispatch
handles), (3) tick results are BIT-IDENTICAL with donation off across
the parity matrix (skin on/off, precision q16/off, vmapped S>1) — the
knob is an aliasing hint, never a numerics change, and (4) the
residency census on the donated path reads 0 re-allocated carry lanes
in steady state (the worklist PR 16 measured, consumed)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import Entity, Space, World
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.utils import metrics, residency

pytestmark = pytest.mark.resident


@pytest.fixture(autouse=True)
def _fresh_registries():
    metrics.REGISTRY.reset()
    residency.reset()
    yield
    metrics.REGISTRY.reset()
    residency.reset()


class _Mob(Entity):
    ATTRS = {"hp": "allclients hot:100"}


def _world(n_spaces=1, n_ents=6, seed=0, skin=0.0, precision="off",
           **kw):
    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=32, row_block=32, skin=skin,
                      precision=precision),
        input_cap=32,
    )
    w = World(cfg, n_spaces=n_spaces, seed=seed, **kw)
    w.register_entity("Mob", _Mob)
    w.register_space("Arena", Space)
    w.create_nil_space()
    sp = w.create_space("Arena")
    rng = np.random.default_rng(seed + 1)
    for _ in range(n_ents):
        w.create_entity(
            "Mob", space=sp,
            pos=(float(rng.uniform(5, 95)), 0.0,
                 float(rng.uniform(5, 95))),
            moving=True)
    return w


def _state_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(bool(jnp.array_equal(x, y)) for x, y in zip(la, lb))


# =======================================================================
# deletion semantics: the old carry must RAISE, never read stale
# =======================================================================
def test_old_carry_deleted_and_raises_on_read():
    w = _world()
    w.tick()
    old = w.state
    w.tick()
    assert old.pos.is_deleted()
    with pytest.raises(RuntimeError):
        np.asarray(old.pos)
    with pytest.raises(RuntimeError):
        jax.device_get(old.nbr_cnt)
    # the NEW carry is live — the next dispatch's input
    assert not w.state.pos.is_deleted()


def test_non_resident_old_carry_stays_live():
    w = _world(resident=False)
    w.tick()
    old = w.state
    w.tick()
    assert not old.pos.is_deleted()
    np.asarray(old.pos)  # still readable — legacy behavior intact


# =======================================================================
# residency census on the donated path
# =======================================================================
def test_census_zero_realloc_steady_state():
    """The acceptance criterion: the donation-readiness census that
    measured 19/19 re-allocated carry lanes before donation reads 0 on
    a resident world — every fingerprinted lane aliases in place."""
    w = _world(residency_sample_every=1)
    for _ in range(6):
        w.tick()
    census = w.residency.census_snapshot()
    assert census["samples"] >= 4
    assert census["realloc"] == []
    assert len(census["aliased"]) >= 10
    assert census["skipped_deleted"] == 0  # fingerprints the NEW carry


def test_census_zero_realloc_vmapped_and_pipelined():
    wv = _world(n_spaces=2, residency_sample_every=1)
    for _ in range(6):
        wv.tick()
    assert wv.residency.census_snapshot()["realloc"] == []
    wp = _world(pipeline_decode=True, residency_sample_every=1)
    for _ in range(6):
        wp.tick()
    assert wp.residency.census_snapshot()["realloc"] == []


def test_census_counts_deleted_honestly_never_crashes():
    """Sampling an OLD carry (donation already consumed it) must not
    crash the plane that judges donation — the deleted lanes land in
    ``census_skipped_deleted``."""
    w = _world(residency_sample_every=1 << 20)
    w.tick()
    old = w.state
    w.tick()
    rt = w.residency
    rt.sample_census(old)          # every lane deleted: no crash
    snap = rt.census_snapshot()
    assert snap["skipped_deleted"] >= 10
    assert snap["realloc"] == []   # dead lanes never masquerade


# =======================================================================
# bit-parity: donation on vs off across the matrix
# =======================================================================
@pytest.mark.parametrize(
    "n_spaces,skin,precision",
    [(1, 0.0, "off"), (1, 4.0, "off"), (1, 0.0, "q16"),
     (2, 0.0, "off")],
    ids=["base", "skin", "q16", "vmapped_s2"])
def test_donation_parity_bit_identical(n_spaces, skin, precision):
    wa = _world(n_spaces=n_spaces, skin=skin, precision=precision,
                seed=9, resident=True)
    wb = _world(n_spaces=n_spaces, skin=skin, precision=precision,
                seed=9, resident=False)
    for _ in range(6):
        wa.tick()
        wb.tick()
    assert _state_equal(wa.state, wb.state)
    # the fetched outputs match too (the host decode sees one stream)
    oa = jax.tree.leaves(wa.last_outputs)
    ob = jax.tree.leaves(wb.last_outputs)
    assert all(bool(np.array_equal(np.asarray(x), np.asarray(y)))
               for x, y in zip(oa, ob))


def test_pipeline_overlap_parity_with_serial_drain():
    """resident + pipeline_decode (the double-buffered drain arm) must
    carry the same device state as the plain serial loop."""
    wa = _world(seed=4, resident=True, pipeline_decode=True)
    wb = _world(seed=4, resident=False)
    for _ in range(6):
        wa.tick()
        wb.tick()
    wa.flush_pending_outputs()
    assert _state_equal(wa.state, wb.state)


# =======================================================================
# freeze / snapshot capture fencing
# =======================================================================
def test_checkpoint_async_survives_donation():
    """The background checkpoint worker fetches planes captured on the
    tick thread; under donation those are PINNED device copies — ticks
    continuing while the worker writes must not kill the fetch."""
    import glob
    import os
    import tempfile

    from goworld_tpu import freeze as fz

    w = _world(seed=2)
    w.tick()
    want = {e.id: tuple(e.position) for e in w.entities.values()
            if not e.is_space and not e.destroyed}
    with tempfile.TemporaryDirectory() as d:
        h = fz.checkpoint_async(w, d)
        for _ in range(3):
            w.tick()            # donation deletes the captured tick's
        h.join(timeout=30)      # carry while the worker still reads
        assert h.path is not None
        files = glob.glob(os.path.join(d, "*"))
        assert files
        data = fz.read_freeze_file(h.path)
        got = {r["id"]: tuple(r["pos"]) for r in data["entities"]
               if r.get("pos") is not None}
        for eid, pos in want.items():
            assert eid in got
    # the copy-mode fallback announced itself (loud, once)
    assert w._resident_copy_warned is True


def test_snapshot_chain_capture_pinned_across_ticks():
    from goworld_tpu.freeze import SnapshotChain

    w = _world(seed=3, snapshot_keyframe_every=4)
    w.tick()
    chain = SnapshotChain(w, ".", keyframe_every=4)
    captured = chain.capture()
    for _ in range(3):
        w.tick()
    data, tick = SnapshotChain.complete_capture(captured)
    assert any(r.get("pos") is not None for r in data["entities"])


def test_unpinned_stale_ref_raises_the_fence_is_load_bearing():
    """The exact bug the pin exists for: a worker holding the RAW
    state across a tick hits deleted buffers. Must raise loudly."""
    w = _world()
    w.tick()
    stale = w.state                # what the old capture used to keep
    w.tick()
    with pytest.raises(RuntimeError):
        jax.device_get({"pos": stale.pos, "yaw": stale.yaw,
                        "npc_moving": stale.npc_moving})


# =======================================================================
# governor swap mid-churn with donation on
# =======================================================================
def test_governor_swap_mid_churn_donated_oracle_exact():
    """A live config swap on a RESIDENT world, with the warm set
    compiled under the same donation contract: oracle-exact on the
    very next tick, zero entity loss, and the donated carry keeps
    deleting (the swap never silently drops back to copy mode)."""
    from goworld_tpu.autotune.warmset import WarmSet
    from goworld_tpu.scenarios.runner import build_world, check_oracle
    from goworld_tpu.scenarios.spec import get_scenario

    w, ents, clients = build_world(
        get_scenario("flock"), n=40, skin=4.0, client_frac=0.15,
        seed=11, world_kw={"resident": True})
    assert w.resident
    w.tick()
    ws = WarmSet(w.cfg, 1, w.policy, telemetry=True,
                 donate=True, donate_fold=True)
    assert ws.ensure("skin=0", block=True)
    assert ws.ensure("sort=counting,skin=0", block=True)

    space = next(iter(w.spaces.values()))
    rng = np.random.default_rng(5)
    live = [e for e in w.entities.values()
            if not e.destroyed and not e.is_space]
    n0 = len(live)

    def churn():
        victim = live.pop(int(rng.integers(len(live))))
        tname = victim.type_name
        victim.destroy()
        live.append(w.create_entity(
            tname, space=space,
            pos=(float(rng.uniform(1, 199)), 0.0,
                 float(rng.uniform(1, 199))),
            moving=True))

    for label in ("skin=0", "sort=counting,skin=0", "skin=0"):
        churn()
        e = ws.entry(label)
        w.apply_tick_config(
            e.cfg, e.exe, telem_fold=e.fold_exe, telem_acc0=e.acc0,
            telem_skin_on=e.skin_on, telem_half_skin=e.half_skin)
        pre = w.state
        w.tick()  # the very next tick after the swap
        # the AOT exe donates too: the captured carry's nbr plane is
        # consumed (pos is NOT asserted — the churn's staging scatter
        # legitimately replaced it before dispatch)
        assert pre.nbr.is_deleted()
        bad = check_oracle(w, clients)
        assert bad == [], f"swap to {label}: {bad[:3]}"
        churn()
        w.tick()
        assert check_oracle(w, clients) == []
    assert len([e for e in w.entities.values()
                if not e.destroyed and not e.is_space]) == n0


# =======================================================================
# devprof: could-reclaim vs did-reclaim
# =======================================================================
def test_donation_applied_reported_next_to_reclaimable():
    w = _world()
    rep = w.cost_report()
    assert rep.error is None
    assert rep.donation_applied is not None
    assert rep.donation_applied == rep.alias_size
    # a resident world's step aliases the carry: applied dominates
    assert rep.donation_applied > rep.donation_reclaimable
    d = rep.as_dict()
    assert "donation_applied" in d and "donation_reclaimable" in d

    w2 = _world(resident=False)
    rep2 = w2.cost_report()
    assert rep2.error is None
    # without donation nothing is applied and the bound is the carry
    assert (rep2.donation_applied or 0) < rep2.donation_reclaimable
