"""Multihost mutation-log backpressure observability (VERDICT r3 #6).

``GameServer._mh_drain_pending`` ships at most MH_LOG_BYTES_PER_TICK of
queued World mutations per tick and carries the surplus over IN ORDER
(r3 backpressure). These tests pin the new gauges: backlog bytes/packets
exposed every drain, and the sustained-growth alarm after 8 consecutive
carry-over ticks. Driven directly on a stub (the logic touches nothing
but the queue + counters), so no multihost process pair is needed.
"""

import logging

from goworld_tpu.net.game import GameServer
from goworld_tpu.utils import opmon, overload


class _Stub:
    MH_LOG_BYTES_PER_TICK = GameServer.MH_LOG_BYTES_PER_TICK
    _mh_drain_pending = GameServer._mh_drain_pending

    def __init__(self):
        self.game_id = 1
        self._mh_pending = []
        self._mh_backlog_ticks = 0
        self.world = type("W", (), {"op_stats": {}})()
        # the sustained-backlog alarm reports the overload plane's
        # state + shed deltas (ISSUE 4 satellite)
        self.overload = overload.OverloadGovernor("stub-mh")
        self._shed_at_alarm = {}


def test_drain_orders_and_reports_backlog():
    s = _Stub()
    big = b"x" * (600 << 10)  # 600 KB each: only one fits per tick
    s._mh_pending = [(10, big), (11, big), (12, b"small")]
    blob = s._mh_drain_pending()
    assert blob[:2] == (10).to_bytes(2, "little")  # order preserved
    assert len(s._mh_pending) == 2                 # carry-over intact
    assert opmon.vars()["mh_mutation_backlog_packets"] == 2
    assert opmon.vars()["mh_mutation_backlog_bytes"] > len(big)
    assert s.world.op_stats["mh_mutation_backlog_bytes"] > len(big)
    assert s._mh_backlog_ticks == 1

    s._mh_drain_pending()  # drains 11
    s._mh_drain_pending()  # drains 12 -> queue empty
    assert not s._mh_pending
    assert opmon.vars()["mh_mutation_backlog_bytes"] == 0
    assert s._mh_backlog_ticks == 0


def test_sustained_backlog_alarm(caplog):
    s = _Stub()
    big = b"x" * (600 << 10)
    with caplog.at_level(logging.WARNING, logger="goworld_tpu.game"):
        for _ in range(8):  # producer outruns the cap every tick
            s._mh_pending.extend([(10, big), (11, big)])
            s._mh_drain_pending()
    assert s._mh_backlog_ticks == 8
    assert any("backlog sustained" in r.message for r in caplog.records)
