"""The AOI impl defaults have ONE source of truth (VERDICT r4 weak #7).

GridSpec (kernel level), GameConfig.aoi_* (ini level) and bench.py's
env-defaulted grid knobs must all resolve to consts.DEFAULT_SWEEP_IMPL /
DEFAULT_TOPK_IMPL, so a direct GridSpec user gets the same measured
winner the production stack and the benchmark run. Also locks in that
bench autotune can never silently select a fidelity-degrading config
(the "approx" top-k's recall is unmeasurable off-TPU — VERDICT r4 weak
#4/#6 — and "shift" drops cap-overflowed entities as watchers).
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from goworld_tpu.config import GameConfig
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.utils import consts


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_one_source_of_truth():
    gs = GridSpec(radius=10.0)
    gc = GameConfig()
    assert gs.sweep_impl == consts.DEFAULT_SWEEP_IMPL
    assert gs.topk_impl == consts.DEFAULT_TOPK_IMPL
    assert gs.sort_impl == consts.DEFAULT_SORT_IMPL
    assert gs.skin == consts.DEFAULT_AOI_SKIN
    assert gc.aoi_sweep_impl == consts.DEFAULT_SWEEP_IMPL
    assert gc.aoi_topk_impl == consts.DEFAULT_TOPK_IMPL
    assert gc.aoi_sort_impl == consts.DEFAULT_SORT_IMPL
    assert gc.aoi_skin == consts.DEFAULT_AOI_SKIN


def test_bench_grid_defaults_agree(monkeypatch):
    for var in ("BENCH_TOPK", "BENCH_SWEEP", "BENCH_SORT", "BENCH_SKIN"):
        monkeypatch.delenv(var, raising=False)
    bench = _load_bench()
    kw = bench._grid_kw_from_env(131072)
    assert kw["sweep_impl"] == consts.DEFAULT_SWEEP_IMPL
    assert kw["topk_impl"] == consts.DEFAULT_TOPK_IMPL
    assert kw["sort_impl"] == consts.DEFAULT_SORT_IMPL
    # the bench WORKLOAD defaults the skin ON (its movement speed is
    # known, so the skin can be sized; consts keeps the library off) —
    # documented divergence, pinned here so it stays deliberate
    assert kw["skin"] == bench.BENCH_SKIN_DEFAULT > 0.0


def test_autotune_never_selects_fidelity_degrading_configs(monkeypatch):
    """Every autotune candidate using the approx top-k (recall < 1 on
    TPU, unmeasurable off-TPU), the shift sweep (drops cap-overflowed
    entities as watchers), or a REDUCED cell_cap (drops candidates in
    overflowing cells) must be marked non-selectable so autotune cannot
    pick a config whose fidelity at the bench workload is worse than
    the default's."""
    monkeypatch.delenv("BENCH_CELL_CAP", raising=False)
    bench = _load_bench()
    default_cap = bench._grid_kw_from_env(131072)["cell_cap"]

    def degrading(ov: dict) -> bool:
        return (ov.get("topk_impl") == "approx"
                or ov.get("sweep_impl") == "shift"
                or ov.get("cell_cap", default_cap) < default_cap)

    cands = bench.AUTOTUNE_CANDIDATES
    assert any(degrading(ov) for _, ov in cands), \
        "expected diagnostic candidates present"
    for sel, ov in cands:
        if degrading(ov):
            assert not sel, f"fidelity-degrading candidate selectable: {ov}"
