"""Grid AOI kernel vs the NumPy oracle (reference semantics: Chebyshev XZ
interest within per-space radius, go-aoi XZList — Space.go:91-106)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from goworld_tpu.ops.aoi import GridSpec, grid_neighbors, neighbors_oracle


def random_world(n, seed, extent=200.0, alive_frac=1.0):
    rng = np.random.default_rng(seed)
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, extent, n)
    pos[:, 1] = rng.uniform(0, 10, n)
    pos[:, 2] = rng.uniform(0, extent, n)
    alive = rng.uniform(size=n) < alive_frac
    return pos, alive


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("alive_frac", [1.0, 0.7])
def test_grid_matches_oracle(seed, alive_frac):
    n = 300
    radius = 25.0
    pos, alive = random_world(n, seed, alive_frac=alive_frac)
    # caps chosen large enough for exactness at this density
    spec = GridSpec(
        radius=radius, extent_x=200.0, extent_z=200.0,
        k=128, cell_cap=128, row_block=128,
    )
    nbr, cnt = jax.jit(grid_neighbors, static_argnums=0)(
        spec, jnp.asarray(pos), jnp.asarray(alive)
    )
    nbr, cnt = np.asarray(nbr), np.asarray(cnt)
    oracle = neighbors_oracle(pos, alive, radius)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert len(got) == cnt[i]
        assert got == oracle[i], f"row {i}"


def test_sorted_and_sentinel_padded():
    n = 200
    pos, alive = random_world(n, 3)
    spec = GridSpec(radius=30.0, extent_x=200.0, extent_z=200.0,
                    k=64, cell_cap=64, row_block=64)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    nbr = np.asarray(nbr)
    assert (np.diff(nbr, axis=1) >= 0).all()
    for i in range(n):
        assert (nbr[i, cnt[i]:] == n).all()
        assert (nbr[i, :cnt[i]] < n).all()


def test_k_cap_keeps_nearest():
    # 10 entities in one spot, k=4 -> keep 4 nearest (all dist 0 ties ok)
    pos = np.zeros((10, 3), np.float32)
    pos[:, 0] = np.arange(10) * 0.1
    alive = np.ones(10, bool)
    spec = GridSpec(radius=50.0, extent_x=64.0, extent_z=64.0,
                    k=4, cell_cap=16, row_block=16)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    assert (np.asarray(cnt) == 4).all()


def test_dead_entities_invisible():
    pos = np.zeros((4, 3), np.float32)
    alive = np.array([True, False, True, True])
    spec = GridSpec(radius=10.0, extent_x=32.0, extent_z=32.0,
                    k=8, cell_cap=8, row_block=4)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    nbr, cnt = np.asarray(nbr), np.asarray(cnt)
    assert cnt[1] == 0
    for i in (0, 2, 3):
        assert 1 not in set(nbr[i][nbr[i] < 4].tolist())
        assert cnt[i] == 2


def test_row_blocking_consistent():
    n = 500
    pos, alive = random_world(n, 7)
    a = GridSpec(radius=20.0, extent_x=200.0, extent_z=200.0,
                 k=64, cell_cap=64, row_block=500)
    b = GridSpec(radius=20.0, extent_x=200.0, extent_z=200.0,
                 k=64, cell_cap=64, row_block=100)
    nbr_a, cnt_a = grid_neighbors(a, jnp.asarray(pos), jnp.asarray(alive))
    nbr_b, cnt_b = grid_neighbors(b, jnp.asarray(pos), jnp.asarray(alive))
    assert (np.asarray(nbr_a) == np.asarray(nbr_b)).all()
    assert (np.asarray(cnt_a) == np.asarray(cnt_b)).all()


def test_approx_topk_matches_oracle():
    """topk_impl='approx' (lax.approx_min_k over f32-bitcast packed keys)
    plumbing check: same neighbor sets as the oracle, flags aligned. On
    CPU the lowering is exact so this proves the bit packing, NOT TPU
    recall — on TPU approx may miss a true neighbor with ~2% per-call
    probability (see the GridSpec.topk_impl caveat; knob is opt-in)."""
    from goworld_tpu.ops.aoi import grid_neighbors_flags, neighbors_oracle

    n = 400
    pos, alive = random_world(n, 13)
    oracle = neighbors_oracle(pos, alive, 25.0)
    spec = GridSpec(radius=25.0, extent_x=200.0, extent_z=200.0,
                    k=64, cell_cap=64, row_block=128, topk_impl="approx")
    rng = np.random.default_rng(13)
    fb = rng.integers(0, 4, n).astype(np.int32)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(pos), jnp.asarray(alive),
        flag_bits=jnp.asarray(fb),
    )
    nbr, cnt, fl = np.asarray(nbr), np.asarray(cnt), np.asarray(fl)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        want = oracle[i] if alive[i] else set()
        assert got == want, (i, got, want)
        for j in range(spec.k):
            if nbr[i, j] < n:
                assert fl[i, j] == (fb[nbr[i, j]] & 3)


def test_ranges_sweep_matches_table_and_oracle():
    """sweep_impl='ranges' (tableless: candidates sliced straight from
    the cell-sorted array) must equal the table impl bit-for-bit while
    no cell overflows cell_cap, and equal the oracle."""
    from goworld_tpu.ops.aoi import grid_neighbors_flags, neighbors_oracle

    n = 500
    pos, alive = random_world(n, 21)
    oracle = neighbors_oracle(pos, alive, 25.0)
    rng = np.random.default_rng(21)
    fb = rng.integers(0, 4, n).astype(np.int32)
    base = dict(radius=25.0, extent_x=200.0, extent_z=200.0,
                k=64, cell_cap=64, row_block=128)
    outs = {}
    for impl in ("table", "ranges"):
        spec = GridSpec(**base, sweep_impl=impl)
        nbr, cnt, fl = grid_neighbors_flags(
            spec, jnp.asarray(pos), jnp.asarray(alive),
            flag_bits=jnp.asarray(fb),
        )
        outs[impl] = (np.asarray(nbr), np.asarray(cnt), np.asarray(fl))
    for a, b in zip(outs["table"], outs["ranges"]):
        assert (a == b).all()
    nbr, cnt, fl = outs["ranges"]
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == (oracle[i] if alive[i] else set()), i
        for j in range(64):
            if nbr[i, j] < n:
                assert fl[i, j] == (fb[nbr[i, j]] & 3)


def test_ranges_sweep_pools_cell_cap():
    """The ranges impl's cap is pooled per z-triple (3*cell_cap): a cell
    overflowing cell_cap keeps strictly more true neighbors than the
    per-cell table cap — never fewer."""
    m = 40
    pos = np.zeros((m, 3), np.float32)
    rng = np.random.default_rng(4)
    pos[:30, 0] = 5.0 + rng.random(30)   # 30 entities in ONE cell
    pos[:30, 2] = 5.0 + rng.random(30)
    pos[30:, 0] = pos[30:, 2] = 100.0
    alive = np.ones(m, bool)
    base = dict(radius=10.0, extent_x=120.0, extent_z=120.0,
                k=64, cell_cap=8, row_block=m)
    cnt = {}
    for impl in ("table", "ranges"):
        spec = GridSpec(**base, sweep_impl=impl)
        _, c = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
        cnt[impl] = int(np.asarray(c)[0])
    assert cnt["ranges"] >= cnt["table"]
    assert cnt["ranges"] >= 20          # pooled cap 24 admits most of 29


def test_big_grid_argsort_path_matches_oracle():
    """Worlds with >= 2^10 padded cell rows take the argsort path (the
    packed single-array sort can't encode the row id); it must agree
    with the oracle exactly like the packed path does."""
    n = 400
    pos, alive = random_world(n, 31)
    spec = GridSpec(radius=2.0, extent_x=200.0, extent_z=200.0,
                    k=32, cell_cap=16, row_block=128)
    assert (spec.cells_x + 2) * (spec.cells_z + 2) >= (1 << 10)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    nbr = np.asarray(nbr)
    oracle = neighbors_oracle(pos, alive, 2.0)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == (oracle[i] if alive[i] else set()), i
