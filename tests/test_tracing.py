"""Distributed tracing (ISSUE 2): context wire format + zero-cost
untraced framing, span recorder linkage, debug-http trace endpoints,
cluster merge with flow synthesis, and end-to-end propagation of one
sampled client RPC across gate -> dispatcher -> game in a standalone
cluster over real sockets."""

import json
import struct
import threading
import time
import urllib.error
import urllib.request

import pytest

from goworld_tpu.net import proto
from goworld_tpu.net.packet import (
    MSGTYPE_MASK,
    TRACE_FLAG,
    Packet,
    decode_wire,
    frame,
    new_packet,
    wire_payload,
)
from goworld_tpu.utils import debug_http, tracing


@pytest.fixture(autouse=True)
def _reset_tracing():
    """Every test starts with sampling off and an empty span ring."""
    tracing.set_sample_rate(0.0)
    tracing.recorder.clear()
    yield
    tracing.set_sample_rate(0.0)
    tracing.recorder.clear()


# =======================================================================
# context + sampling
# =======================================================================
def test_context_pack_unpack_roundtrip():
    ctx = tracing.new_trace()
    b = ctx.pack()
    assert len(b) == tracing.CTX_WIRE_SIZE == 25
    back = tracing.TraceContext.unpack(b)
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled
    with pytest.raises(ValueError):
        tracing.TraceContext.unpack(b[:-1])


def test_child_keeps_trace_id_fresh_span_id():
    ctx = tracing.new_trace()
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id
    assert kid.span_id != ctx.span_id
    assert kid.sampled == ctx.sampled


def test_sampling_rates():
    tracing.set_sample_rate(0.0)
    assert all(tracing.maybe_sample() is None for _ in range(50))
    tracing.set_sample_rate(1.0)
    roots = [tracing.maybe_sample() for _ in range(10)]
    assert all(r is not None and r.sampled for r in roots)
    # distinct trace ids per root
    assert len({r.trace_id for r in roots}) == 10


def test_disarm_resets_fast_path_flag():
    tracing.set_sample_rate(0.5)
    assert tracing.active
    tracing.set_sample_rate(0.0)
    assert not tracing.active  # untraced processes pay one global load
    # an inbound traced hop re-raises it so propagation still stamps
    with tracing.use(tracing.new_trace()):
        assert tracing.active


def test_current_context_nests():
    assert tracing.current() is None
    a, b = tracing.new_trace(), tracing.new_trace()
    with tracing.use(a):
        assert tracing.current() is a
        with tracing.use(b):
            assert tracing.current() is b
        assert tracing.current() is a
    assert tracing.current() is None


# =======================================================================
# wire format: zero bytes when untraced, trailer strip when traced
# =======================================================================
def test_untraced_frame_is_byte_identical_to_pre_tracing_wire():
    """ISSUE 2 acceptance: with sampling disabled, packet bytes on the
    wire are unchanged — golden-framed against the documented
    [u32 size][u16 msgtype][payload] layout."""
    p = new_packet(proto.MT_CALL_ENTITY_METHOD)
    p.append_entity_id("e" * 16)
    p.append_var_str("Ping")
    p.append_args(("x", 1))
    payload = bytes(p.buf)
    golden = struct.pack("<I", len(payload)) + payload
    assert frame(p) == golden
    assert wire_payload(p) == payload
    # msgtype field carries no flag bit
    assert struct.unpack_from("<H", payload)[0] == \
        proto.MT_CALL_ENTITY_METHOD
    mt, q = decode_wire(payload)
    assert mt == proto.MT_CALL_ENTITY_METHOD and q.trace is None


def test_traced_frame_trailer_and_strip():
    p = new_packet(proto.MT_CALL_ENTITY_METHOD)
    p.append_var_str("hello")
    plain = bytes(p.buf)
    p.trace = tracing.new_trace()
    wire = wire_payload(p)
    # flag bit set, 25B trailer appended
    assert len(wire) == len(plain) + tracing.CTX_WIRE_SIZE
    assert struct.unpack_from("<H", wire)[0] == \
        proto.MT_CALL_ENTITY_METHOD | TRACE_FLAG
    mt, q = decode_wire(wire)
    assert mt == proto.MT_CALL_ENTITY_METHOD
    assert bytes(q.buf) == plain  # handler sees identical payload
    assert q.trace is not None
    assert q.trace.trace_id == p.trace.trace_id
    assert q.trace.span_id == p.trace.span_id


def test_truncated_trace_trailer_rejected():
    p = new_packet(proto.MT_HEARTBEAT)
    p.trace = tracing.new_trace()
    wire = wire_payload(p)[:10]  # flagged but trailer cut off
    with pytest.raises(ConnectionError):
        decode_wire(wire)


def test_release_clears_trace_context():
    p = new_packet(proto.MT_HEARTBEAT)
    p.trace = tracing.new_trace()
    p.release()
    q = Packet.alloc()
    assert q.trace is None


def test_new_packet_autostamps_under_current_context():
    ctx = tracing.new_trace()
    with tracing.use(ctx):
        p = new_packet(proto.MT_CALL_ENTITY_METHOD)
    assert p.trace is ctx
    q = new_packet(proto.MT_CALL_ENTITY_METHOD)
    assert q.trace is None


def test_pending_queues_preserve_trace_context():
    """Packets queued while a peer is away (game reconnecting, entity
    blocked mid-migration) must come out of the queue still traced —
    the queueing delay is exactly the hop a p99 investigation needs."""
    from goworld_tpu.net.cluster import DispatcherConn
    from goworld_tpu.net.dispatcher import _GameInfo
    from goworld_tpu.utils import overload

    ctx = tracing.new_trace()
    gi = _GameInfo(1)  # conn is None: send() queues
    p = new_packet(proto.MT_CALL_ENTITY_METHOD)
    p.append_var_str("x")
    p.trace = ctx
    gi.send(p, release=False)
    # the pend queue is class-prioritized now (ISSUE 4); an entity RPC
    # lands in the rpc-class deque
    mt, q = decode_wire(gi.pending[overload.CLASS_RPC][0])
    assert mt == proto.MT_CALL_ENTITY_METHOD
    assert q.trace is not None and q.trace.trace_id == ctx.trace_id
    assert q.read_var_str() == "x"

    conn = DispatcherConn(0, ("127.0.0.1", 1), lambda *a: None, None)
    p2 = new_packet(proto.MT_CALL_ENTITY_METHOD)
    p2.trace = ctx
    conn.send(p2, release=False)
    mt2, q2 = decode_wire(conn._pending[0])
    assert mt2 == proto.MT_CALL_ENTITY_METHOD
    assert q2.trace is not None and q2.trace.span_id == ctx.span_id


# =======================================================================
# span recorder
# =======================================================================
def test_recorder_span_linkage_and_chrome_events():
    root = tracing.new_trace()
    with tracing.hop("route", "dispatcher1", root, msgtype=8) as my:
        time.sleep(0.002)
        with tracing.hop("handle", "game1", my, msgtype=8):
            pass
    recs = tracing.recorder.records()
    assert [r[0] for r in recs] == ["handle", "route"]  # inner closes first
    handle, route = recs[0], recs[1]
    assert route[2] == handle[2] == root.trace_hex
    assert route[4] == root.span_hex          # route parents to the root
    assert handle[4] == route[3]              # handle parents to route
    assert route[6] >= 2000                   # >= 2ms in us

    events = tracing.recorder.chrome_events(pid=42)
    tracks = {e["args"]["name"] for e in events
              if e["name"] == "thread_name"}
    assert tracks == {"dispatcher1", "game1"}
    spans = [e for e in events if e["ph"] == "X"]
    assert {s["name"] for s in spans} == {"route", "handle"}
    by_name = {s["name"]: s for s in spans}
    assert by_name["handle"]["args"]["parent_id"] == \
        by_name["route"]["args"]["span_id"]
    assert by_name["route"]["args"]["msgtype"] == 8
    json.dumps(events)  # valid JSON


def test_recorder_ring_bounds():
    rec = tracing.SpanRecorder(capacity=16)
    ctx = tracing.new_trace()
    for i in range(50):
        rec.record("s", "t", ctx, None, 0.0, 1.0)
    assert len(rec) == 16


# =======================================================================
# debug-http: /clock, /tracing, gzip /trace, /profile
# =======================================================================
def _get(url: str, headers: dict | None = None, timeout: float = 5):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture()
def http_srv():
    srv = debug_http.start(0, process_name="tracetest")
    yield srv, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_clock_endpoint(http_srv):
    _, base = http_srv
    t0 = time.time() * 1e6
    code, _, body = _get(base + "/clock")
    t1 = time.time() * 1e6
    assert code == 200
    clock = json.loads(body)
    assert t0 <= clock["wall_us"] <= t1
    assert clock["mono_us"] > 0
    assert clock["process_name"] == "tracetest"


def test_tracing_control_endpoint(http_srv):
    _, base = http_srv
    code, _, body = _get(base + "/tracing?rate=0.25")
    assert code == 200
    assert json.loads(body)["rate"] == 0.25
    assert tracing.sample_rate() == 0.25
    ctx = tracing.new_trace()
    with tracing.recorder.span("s", "t", ctx, None):
        pass
    code, _, body = _get(base + "/tracing")
    assert json.loads(body)["spans"] == 1
    code, _, body = _get(base + "/tracing?rate=0&clear=1")
    out = json.loads(body)
    assert out["rate"] == 0 and out["spans"] == 0
    # value-less form counts too (`curl .../tracing?clear`)
    with tracing.recorder.span("s2", "t", ctx, None):
        pass
    code, _, body = _get(base + "/tracing?clear")
    assert json.loads(body)["spans"] == 0


def test_trace_endpoint_merges_spans_and_gzips(http_srv):
    _, base = http_srv
    from goworld_tpu.utils import metrics

    metrics.timeline.begin_tick()
    with metrics.timeline.span("tick_phase"):
        pass
    metrics.timeline.end_tick()
    ctx = tracing.new_trace()
    with tracing.recorder.span("rpc_span", "gate1", ctx, None):
        pass

    code, headers, body = _get(base + "/trace")
    assert code == 200 and headers.get("Content-Encoding") is None
    names = {e["name"] for e in json.loads(body)["traceEvents"]}
    assert {"tick_phase", "rpc_span"} <= names

    import gzip as _gz

    code, headers, zbody = _get(base + "/trace",
                                {"Accept-Encoding": "gzip"})
    assert code == 200 and headers.get("Content-Encoding") == "gzip"
    assert json.loads(_gz.decompress(zbody)) == json.loads(body)


def test_profile_endpoint_start_stop(http_srv, tmp_path):
    _, base = http_srv
    # first start_trace in a process initializes the profiler (~10s on
    # a cold jax); give the request room
    code, _, body = _get(
        base + f"/profile?logdir={tmp_path}/prof", timeout=90)
    out = json.loads(body)
    if code == 501:
        assert "unavailable" in out["error"]
        return  # environment without jax.profiler: clear JSON error
    assert code == 200 and out["started"]
    # double start is a clear conflict, not a crash
    code2, _, body2 = _get(base + f"/profile?logdir={tmp_path}/p2")
    assert code2 == 409
    code3, _, body3 = _get(base + "/profile?stop=1", timeout=90)
    assert code3 == 200 and json.loads(body3)["stopped"]
    # stop without a capture
    code4, _, _ = _get(base + "/profile?stop=1")
    assert code4 == 409


def test_profile_seconds_auto_stop_releases_lock(http_srv, tmp_path):
    """`?seconds=N` regression (ISSUE 8 satellite): a started capture
    that is never stopped used to hold the per-process profiler lock
    forever; with auto-stop the lock frees itself and a new capture
    can start."""
    _, base = http_srv
    code, _, body = _get(
        base + f"/profile?logdir={tmp_path}/auto&seconds=0.5",
        timeout=90)
    out = json.loads(body)
    if code == 501:
        assert "unavailable" in out["error"]
        return
    assert code == 200 and out["started"] and out["auto_stop_s"] == 0.5
    # ?status reports without side effects while active or not
    deadline = time.time() + 30
    while time.time() < deadline:
        _, _, sbody = _get(base + "/profile?status=1")
        if not json.loads(sbody)["active"]:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("auto-stop never fired within 30s")
    # the lock is free again: a fresh capture starts cleanly and a
    # manual stop still works (no stale-timer interference)
    code2, _, _ = _get(base + f"/profile?logdir={tmp_path}/fresh",
                       timeout=90)
    assert code2 == 200
    code3, _, _ = _get(base + "/profile?stop=1", timeout=90)
    assert code3 == 200
    # a malformed seconds value is a 400, never a wedged capture
    code4, _, body4 = _get(
        base + f"/profile?logdir={tmp_path}/bad&seconds=abc")
    assert code4 == 400 and "seconds" in json.loads(body4)["error"]
    code5, _, _ = _get(
        base + f"/profile?logdir={tmp_path}/bad&seconds=-1")
    assert code5 == 400
    # non-finite values defeat the auto-stop guarantee: nan's Timer
    # fires immediately, inf's never — both must be 400s
    for bad in ("nan", "inf"):
        code6, _, _ = _get(
            base + f"/profile?logdir={tmp_path}/bad&seconds={bad}")
        assert code6 == 400, bad
    # ...and it left NO capture behind
    _, _, sbody = _get(base + "/profile?status=1")
    assert not json.loads(sbody)["active"]


# =======================================================================
# end-to-end: one sampled client RPC across a standalone cluster
# =======================================================================
from goworld_tpu.core.state import WorldConfig  # noqa: E402
from goworld_tpu.entity.entity import Entity  # noqa: E402
from goworld_tpu.entity.manager import World  # noqa: E402
from goworld_tpu.net.botclient import BotClient  # noqa: E402
from goworld_tpu.net.game import GameServer  # noqa: E402
from goworld_tpu.net.standalone import ClusterHarness  # noqa: E402
from goworld_tpu.ops.aoi import GridSpec  # noqa: E402


class TracedAccount(Entity):
    ATTRS = {"status": "client"}

    def Ping_Client(self, text):
        # a client RPC emitted INSIDE the handler stages a client event
        # under the active trace -> exercises the game -> dispatcher ->
        # gate egress leg (attr fan-out happens later in the tick,
        # outside any handler context, and is deliberately untraced)
        self.call_client("OnPing", text)


@pytest.fixture()
def traced_cluster():
    harness = ClusterHarness(n_dispatchers=1, n_gates=1,
                             desired_games=1)
    harness.start()
    world = World(
        WorldConfig(capacity=64, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0)),
        n_spaces=1,
    )
    world.register_entity("TracedAccount", TracedAccount)
    world.create_nil_space()
    gs = GameServer(1, world, list(harness.dispatcher_addrs),
                    boot_entity="TracedAccount",
                    gc_freeze_on_boot=False)
    gs.start_network()
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            gs.pump()
            gs.tick()
            time.sleep(0.01)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    assert gs.ready_event.wait(20), "deployment never became ready"
    tracing.recorder.clear()
    tracing.set_sample_rate(1.0)
    yield harness, world, gs
    stop.set()
    t.join(timeout=5)
    gs.stop()
    harness.stop()


async def _ping_script(bot: BotClient):
    import asyncio

    await bot.connect()
    asyncio.ensure_future(bot._recv_loop())
    await asyncio.wait_for(bot.player_ready.wait(), 10)
    bot.call_server("Ping_Client", "pong")
    for _ in range(100):
        if any(m == "OnPing" for _, m, _a in bot.rpc_log):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("Ping RPC round trip never completed")


def _spans_by_name(name):
    return [r for r in tracing.recorder.records() if r[0] == name]


def test_e2e_client_rpc_spans_link_across_services(traced_cluster):
    """ISSUE 2 acceptance: a single traced client RPC appears as
    causally-linked spans on gate, dispatcher and game tracks sharing
    one trace_id with correct parentage."""
    harness, world, gs = traced_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port)
    harness.submit(_ping_script(bot)).result(timeout=30)

    # the RPC leg (client -> game) completes before the script returns;
    # the response leg (events batch -> gate) lands within a tick
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not _spans_by_name("gate_egress"):
        time.sleep(0.05)

    rpc_mt = proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT
    ingress = [r for r in _spans_by_name("gate_ingress")
               if (r[7] or {}).get("msgtype") == rpc_mt]
    assert ingress, "no gate_ingress span for the client RPC"
    gate_span = ingress[-1]
    trace_id = gate_span[2]
    assert gate_span[4] is None  # rooted at the gate edge

    routes = [r for r in _spans_by_name("route")
              if r[2] == trace_id and (r[7] or {}).get("msgtype") == rpc_mt]
    assert routes, "dispatcher recorded no route span for the trace"
    assert routes[0][1] == "dispatcher1"
    assert routes[0][4] == gate_span[3]  # parented to gate_ingress

    handles = [r for r in _spans_by_name("handle")
               if r[2] == trace_id
               and (r[7] or {}).get("msgtype") == rpc_mt]
    assert handles, "game recorded no handle span for the trace"
    assert handles[0][1] == "game1"
    assert handles[0][4] == routes[0][3]  # parented to the route span

    invokes = [r for r in _spans_by_name("invoke") if r[2] == trace_id]
    assert invokes and invokes[0][4] == handles[0][3]
    assert invokes[0][7]["method"] == "Ping_Client"

    # response leg: the client-events batch rode the SAME trace through
    # dispatcher (msgtype 1504) to the gate's egress span
    batch_routes = [r for r in _spans_by_name("route")
                    if r[2] == trace_id and (r[7] or {}).get("msgtype")
                    == proto.MT_CLIENT_EVENTS_BATCH]
    assert batch_routes, "events batch lost the trace at the dispatcher"
    egress = [r for r in _spans_by_name("gate_egress")
              if r[2] == trace_id]
    assert egress, "gate recorded no egress span for the response"
    assert egress[0][4] == batch_routes[0][3]


def test_e2e_merged_cluster_trace_is_perfetto_loadable(traced_cluster):
    """ISSUE 2 acceptance: the merge tool produces ONE Perfetto JSON
    from the live cluster with flow arrows linking the hop spans."""
    import importlib.util
    import os as _os

    harness, world, gs = traced_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port)
    harness.submit(_ping_script(bot)).result(timeout=30)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline \
            and not _spans_by_name("gate_egress"):
        time.sleep(0.05)

    spec = importlib.util.spec_from_file_location(
        "merge_traces",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "tools", "merge_traces.py"),
    )
    merger = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(merger)

    srv = debug_http.start(0, process_name="standalone")
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        merged, errors = merger.collect([("standalone", base)])
        assert not errors
        json.dumps(merged)  # loadable JSON
        events = merged["traceEvents"]
        tracks = {e["args"]["name"] for e in events
                  if e["name"] == "thread_name"}
        assert {"gate1", "dispatcher1", "game1"} <= tracks
        spans = [e for e in events if e.get("ph") == "X"
                 and "span_id" in (e.get("args") or {})]
        names = {e["name"] for e in spans}
        assert {"gate_ingress", "route", "handle"} <= names
        # flow arrows were synthesized from the parent/child linkage
        flow_starts = [e for e in events if e.get("ph") == "s"]
        flow_ends = [e for e in events if e.get("ph") == "f"]
        assert flow_starts and len(flow_starts) == len(flow_ends)
        # every flow id pairs a start with an end
        assert {e["id"] for e in flow_starts} == \
            {e["id"] for e in flow_ends}
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_supplied_context_is_discarded(traced_cluster):
    """A client shipping its own (flagged) trace context must not have
    it honored: no span parents to it, and NOTHING the gate sends back
    to the client carries the flag bit — the client wire stays clean
    and the sampling rate cannot be bypassed from outside."""
    import asyncio

    harness, world, gs = traced_cluster
    tracing.set_sample_rate(0.0)  # only a honored context could trace
    tracing.recorder.clear()
    host, port = harness.gate_addrs[0]
    rogue = tracing.new_trace()

    async def rogue_heartbeat():
        reader, writer = await asyncio.open_connection(host, port)
        p = new_packet(proto.MT_HEARTBEAT)
        p.trace = rogue
        writer.write(frame(p))
        await writer.drain()
        # read raw frames until the heartbeat echo; every client-bound
        # frame must have bit 15 clear (boot-flow packets may precede)
        for _ in range(20):
            hdr = await asyncio.wait_for(reader.readexactly(4), 10)
            (size,) = struct.unpack("<I", hdr)
            body = await asyncio.wait_for(reader.readexactly(size), 10)
            mt = struct.unpack_from("<H", body)[0]
            assert mt & TRACE_FLAG == 0, \
                f"client wire carries trace flag on msgtype {mt}"
            if mt == proto.MT_HEARTBEAT:
                break
        else:
            raise AssertionError("no heartbeat echo")
        writer.close()

    harness.submit(rogue_heartbeat()).result(timeout=30)
    # the rogue context never rooted anything
    assert all(r[2] != rogue.trace_hex
               for r in tracing.recorder.records())


def test_untraced_cluster_pays_zero_wire_bytes(traced_cluster):
    """With sampling off mid-run, the gate forwards packets with no
    flag bit and no trailer (spot-checked at the framing layer by
    test_untraced_frame_is_byte_identical_to_pre_tracing_wire; here we
    assert no spans are recorded for unsampled traffic)."""
    harness, world, gs = traced_cluster
    tracing.set_sample_rate(0.0)
    tracing.recorder.clear()
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port)
    harness.submit(_ping_script(bot)).result(timeout=30)
    time.sleep(0.3)  # let any (wrongly) traced response leg land
    assert tracing.recorder.records() == []
