"""WebSocket client edge (reference gate's websocket listener,
``GateService.go:121-168``, and test_client's ``-ws`` flag)."""

import os
import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.net.botclient import BotClient
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec


class Account(Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"

    def Echo_Client(self, text):
        self.call_client("OnEcho", text)


@pytest.fixture()
def ws_cluster():
    harness = ClusterHarness(n_dispatchers=1, n_gates=1, desired_games=1,
                             with_ws=True)
    harness.start()
    world = World(
        WorldConfig(capacity=64,
                    grid=GridSpec(radius=20.0, extent_x=80.0,
                                  extent_z=80.0)),
        n_spaces=1,
    )
    world.register_entity("Account", Account)
    world.create_nil_space()
    gs = GameServer(1, world, list(harness.dispatcher_addrs))
    gs.start_network()
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            gs.pump()
            gs.tick()
            time.sleep(0.01)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    assert gs.ready_event.wait(20)
    yield harness
    stop.set()
    t.join(timeout=5)
    gs.stop()
    harness.stop()


async def _ws_login(bot: BotClient):
    import asyncio

    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 15)
        assert bot.player.type_name == "Account"
        for _ in range(100):
            if bot.player.attrs.get("status") == "online":
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("status") == "online"
        bot.call_server("Echo_Client", "ping")
        for _ in range(100):
            if any(m == "OnEcho" for _, m, _ in bot.rpc_log):
                break
            await asyncio.sleep(0.05)
        assert any(
            m == "OnEcho" and a == ["ping"] for _, m, a in bot.rpc_log
        ), bot.rpc_log
    finally:
        recv.cancel()
        await bot.conn.close()


def test_ws_login_and_rpc(ws_cluster):
    harness = ws_cluster
    host, port = harness.gate_ws_addrs[0]
    bot = BotClient(host, port, ws=True)
    fut = harness.submit(_ws_login(bot))
    fut.result(timeout=40)
    assert not bot.errors, bot.errors


def test_ws_shim_roundtrip():
    """The stdlib RFC6455 shim (net/ws.py — the fallback that makes
    the gate's ws edge work without the third-party ``websockets``
    package): handshake, binary/text echo, 16/64-bit length paths,
    transparent ping->pong, clean close."""
    import asyncio

    from goworld_tpu.net import ws

    async def main():
        async def handler(sock):
            async for msg in sock:
                await sock.send(msg)  # echo, type-preserving

        srv = await ws.serve(handler, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        c = await ws.connect(f"ws://127.0.0.1:{port}")
        assert c.open
        await c.send(b"\x00\x01bin")
        assert await c.recv() == b"\x00\x01bin"
        await c.send("text")
        assert await c.recv() == "text"
        mid = os.urandom(1000)          # 16-bit length path
        await c.send(mid)
        assert await c.recv() == mid
        big = os.urandom(70 * 1024)     # 64-bit length path
        await c.send(big)
        assert await c.recv() == big
        # a ping is answered transparently; the next data frame still
        # arrives in order
        await c._send_frame(ws.OP_PING, b"hb")
        await c.send(b"after-ping")
        assert await c.recv() == b"after-ping"
        await c.close()
        assert not c.open
        srv.close()
        await srv.wait_closed()

    asyncio.run(main())
