"""Fuzz the reactive attr tree: journal replay must reconstruct the tree.

This is the property the whole client-sync pipeline rests on (reference:
every MapAttr/ListAttr mutation emits a path-delta the client applies to
its mirror, ``Entity.go:814-917``; the strict bot asserts mirror
equality). Random op sequences are applied to a MapAttr root while a
separate replayer consumes ONLY the emitted AttrDelta journal; after
every operation the replayed mirror must equal ``to_dict()`` exactly.
"""

import random

import pytest

from goworld_tpu.entity.attrs import (
    AttrDelta, ListAttr, MapAttr, make_root,
)


def replay(mirror: dict, d: AttrDelta) -> None:
    """Apply one journal delta to a plain-python mirror (what a client
    does with MT_NOTIFY_*_ATTR messages)."""
    *parents, last = d.path if d.op in ("set", "del", "insert") else \
        (*d.path, None)
    node = mirror
    for p in parents:
        node = node[p]
    if d.op == "set":
        node[last] = d.value
    elif d.op == "del":
        del node[last]
    elif d.op == "insert":
        node.insert(last, d.value)
    elif d.op == "append":
        node.append(d.value)
    elif d.op == "pop":
        idx = d.value
        node.pop(idx)
    else:
        raise AssertionError(f"unknown op {d.op}")


def all_nodes(root: MapAttr):
    """Every attached (node, kind) in the tree, root included."""
    out = [root]
    stack = [root]
    while stack:
        n = stack.pop()
        vals = n._d.values() if isinstance(n, MapAttr) else n._l
        for v in vals:
            if isinstance(v, (MapAttr, ListAttr)):
                out.append(v)
                stack.append(v)
    return out


@pytest.mark.parametrize("seed", [1, 7, 42, 1234])
def test_journal_replay_reconstructs_tree(seed):
    rng = random.Random(seed)
    journal: list[AttrDelta] = []
    root = make_root(journal.append)
    mirror: dict = {}

    def rand_value(depth=0):
        r = rng.random()
        if depth < 2 and r < 0.15:
            return {f"k{rng.randrange(4)}": rand_value(depth + 1)
                    for _ in range(rng.randrange(3))}
        if depth < 2 and r < 0.3:
            return [rand_value(depth + 1) for _ in range(rng.randrange(3))]
        return rng.choice([
            rng.randrange(1000), rng.random(), f"s{rng.randrange(99)}",
            True, False,
        ])

    for step in range(400):
        nodes = all_nodes(root)
        node = rng.choice(nodes)
        try:
            if isinstance(node, MapAttr):
                op = rng.random()
                if op < 0.55 or len(node) == 0:
                    node.set(f"k{rng.randrange(8)}", rand_value())
                elif op < 0.75:
                    node.delete(rng.choice(list(node.keys())))
                else:
                    node.setdefault(f"k{rng.randrange(8)}", rand_value())
            else:  # ListAttr
                op = rng.random()
                if op < 0.4 or len(node) == 0:
                    node.append(rand_value())
                elif op < 0.6:
                    node.set(rng.randrange(len(node)), rand_value())
                elif op < 0.8:
                    node.pop(rng.randrange(len(node)))
                else:
                    node.insert(rng.randrange(len(node) + 1), rand_value())
        except ValueError:  # pragma: no cover - defensive
            raise AssertionError(
                "unexpected re-parenting rejection from fresh values"
            )
        for d in journal:
            replay(mirror, d)
        journal.clear()
        assert mirror == root.to_dict(), f"divergence at step {step}"


def test_replay_across_nested_node_moves():
    """Setting a plain dict/list under a nested path journals the WHOLE
    subtree value; later mutations inside it journal relative paths that
    must resolve on the mirror."""
    journal: list[AttrDelta] = []
    root = make_root(journal.append)
    mirror: dict = {}
    root["inv"] = {"slots": [{"id": 1}, {"id": 2}]}
    bag = root["inv"]["slots"]
    bag[0]["count"] = 5
    bag.append({"id": 3})
    bag[2]["count"] = 9
    root["inv"]["gold"] = 100
    bag.pop(1)
    for d in journal:
        replay(mirror, d)
    assert mirror == root.to_dict()
    assert mirror["inv"]["slots"][1] == {"id": 3, "count": 9}


def test_reattaching_node_raises():
    """Re-parenting an attached subtree is rejected (reference panics,
    MapAttr.go:84-115) and leaves the tree + journal coherent."""
    journal: list[AttrDelta] = []
    root = make_root(journal.append)
    root["a"] = {"x": 1}
    sub = root["a"]
    with pytest.raises(ValueError):
        root.set("b", sub)
    mirror: dict = {}
    for d in journal:
        replay(mirror, d)
    assert mirror == root.to_dict() == {"a": {"x": 1}}
