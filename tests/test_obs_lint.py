"""tools/obs_lint.py — observability drift lint, in tier-1 (jax-free).

Two live contracts plus proof the lint can actually catch drift:

* the REAL repo is clean (every `debug_http._ENDPOINTS` entry has its
  docs/OBSERVABILITY.md table row, every conftest marker appears in
  README.md) — this test IS the drift gate;
* synthetic repos with a missing doc row / undocumented marker /
  stale doc row exit 2 with a problem naming the offender.
"""

import importlib.util
import os

import pytest

pytestmark = pytest.mark.audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "obs_lint_under_test",
    os.path.join(REPO, "tools", "obs_lint.py"))
LINT = importlib.util.module_from_spec(spec)
spec.loader.exec_module(LINT)


# ---------------------------------------------------------------- parsers

def test_parse_endpoints_reads_the_literal():
    src = ('X = 1\n_ENDPOINTS = ["/healthz",\n    "/metrics",\n'
           '    "/audit"]\nY = 2\n')
    assert LINT.parse_endpoints(src) == ["/healthz", "/metrics",
                                         "/audit"]


def test_parse_endpoints_missing_list_is_empty():
    assert LINT.parse_endpoints("ENDPOINTS = None\n") == []


def test_parse_doc_endpoints_first_cell_only():
    doc = ("| path | content |\n"
           "|---|---|\n"
           "| `/metrics` | counters |\n"
           "| `/audit` | see `/metrics` for the counter mirror |\n"
           "prose mentioning `/ghost` outside a table\n")
    # /ghost (prose) and the second-cell /metrics mention must NOT
    # count as documentation rows
    assert LINT.parse_doc_endpoints(doc) == ["/metrics", "/audit"]


def test_parse_markers_reads_registrations():
    src = ('    config.addinivalue_line(\n        "markers",\n'
           '        "soak: long-running load tests",\n    )\n'
           '    config.addinivalue_line(\n        "markers",\n'
           '        "audit: correctness audit plane suites",\n    )\n')
    assert LINT.parse_markers(src) == ["soak", "audit"]


def test_marker_documented_forms():
    readme = "run `-m soak` or select the `audit` suite"
    assert LINT.marker_documented("soak", readme)
    assert LINT.marker_documented("audit", readme)
    assert not LINT.marker_documented("ghost", readme)


# ---------------------------------------------------------- the live gate

def test_real_repo_is_clean():
    problems, facts = LINT.lint(REPO)
    assert problems == [], problems
    assert facts["endpoints"] >= 17  # the full debug-http map
    assert facts["markers"] >= 15


def test_cli_exits_zero_on_repo(capsys):
    assert LINT.main(["--repo", REPO]) == 0
    assert "obs_lint: ok" in capsys.readouterr().out


# ----------------------------------------------------- drift is caught

def _write_repo(root, *, endpoints, doc_rows, markers, readme):
    os.makedirs(os.path.join(root, "goworld_tpu", "utils"))
    os.makedirs(os.path.join(root, "docs"))
    os.makedirs(os.path.join(root, "tests"))
    eps = ", ".join(f'"{e}"' for e in endpoints)
    with open(os.path.join(root, "goworld_tpu", "utils",
                           "debug_http.py"), "w") as fh:
        fh.write(f"_ENDPOINTS = [{eps}]\n")
    rows = "\n".join(f"| `{e}` | docs |" for e in doc_rows)
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"),
              "w") as fh:
        fh.write(f"| path | content |\n|---|---|\n{rows}\n")
    regs = "".join(
        f'    config.addinivalue_line(\n        "markers",\n'
        f'        "{m}: something",\n    )\n' for m in markers)
    with open(os.path.join(root, "tests", "conftest.py"), "w") as fh:
        fh.write(f"def pytest_configure(config):\n{regs}")
    with open(os.path.join(root, "README.md"), "w") as fh:
        fh.write(readme)


def test_missing_doc_row_is_drift(tmp_path, capsys):
    root = str(tmp_path / "r")
    _write_repo(root, endpoints=["/metrics", "/audit"],
                doc_rows=["/metrics"], markers=["soak"],
                readme="`-m soak`\n")
    problems, _ = LINT.lint(root)
    assert any("/audit" in p and "no row" in p for p in problems)
    assert LINT.main(["--repo", root]) == 2
    assert "/audit" in capsys.readouterr().err


def test_stale_doc_row_is_drift(tmp_path):
    root = str(tmp_path / "r")
    _write_repo(root, endpoints=["/metrics"],
                doc_rows=["/metrics", "/deleted"], markers=["soak"],
                readme="`-m soak`\n")
    problems, _ = LINT.lint(root)
    assert any("/deleted" in p and "does not serve" in p
               for p in problems)


def test_undocumented_marker_is_drift(tmp_path):
    root = str(tmp_path / "r")
    _write_repo(root, endpoints=["/metrics"], doc_rows=["/metrics"],
                markers=["soak", "ghost"], readme="`-m soak`\n")
    problems, _ = LINT.lint(root)
    assert any("'ghost'" in p and "README" in p for p in problems)


def test_clean_synthetic_repo_passes(tmp_path):
    root = str(tmp_path / "r")
    _write_repo(root, endpoints=["/metrics", "/audit"],
                doc_rows=["/metrics", "/audit"],
                markers=["soak", "audit"],
                readme="run `-m soak` and `-m audit`\n")
    problems, facts = LINT.lint(root)
    assert problems == []
    assert facts == {"endpoints": 2, "documented_endpoints": 2,
                     "markers": 2}


def test_missing_input_file_is_loud(tmp_path):
    problems, _ = LINT.lint(str(tmp_path))
    assert problems and "unreadable" in problems[0]
