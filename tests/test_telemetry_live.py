"""Live serving telemetry (ISSUE 11): the device-resident lane carry
on the PRODUCTION World tick (zero host syncs asserted under
``jax.transfer_guard``), the drained-lane -> metrics/signature
plumbing, one-trace-per-config stability, the megaspace lane set, and
the end-to-end acceptance: a live (non-bench) GameServer serves a
workload signature at /workload and an induced SLO breach yields a
correlated bundle at /incidents."""

import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops import telemetry
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.utils import debug_http, flightrec, metrics

pytestmark = pytest.mark.flightrec


class Arena(Space):
    pass


class Npc(Entity):
    pass


def _world(skin=2.0, n=24, telemetry_live=True):
    w = World(
        WorldConfig(capacity=64, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0, skin=skin)),
        n_spaces=1, telemetry_live=telemetry_live,
    )
    w.register_space("Arena", Arena, use_aoi=True)
    w.register_entity("Npc", Npc)
    w.create_nil_space()
    sp = w.create_space("Arena")
    for i in range(n):
        w.create_entity("Npc", space=sp,
                        pos=(2.0 * (i % 5), 0.0, 2.0 * (i // 5)),
                        moving=True)
    return w


# =======================================================================
# zero added host syncs on the live path
# =======================================================================
def test_live_fold_zero_sync_under_transfer_guard():
    """The per-tick accumulation (compiled step + telemetry fold) must
    run with NO host transfers — the ISSUE 11 acceptance bound. The
    staging flush and the drain are host work by design and sit
    outside the guard."""
    w = _world()
    for _ in range(3):
        w.tick()  # trace both executables first
    inputs = w._flush_staging()  # host->device, outside the guard
    with jax.transfer_guard("disallow"):
        st2, outs = w._step(w.state, inputs, w.policy)
        acc2 = w._telem_fn(w._telem_acc, outs)
        jax.block_until_ready(acc2)
    # sanity: the guarded fold really accumulated a tick
    reb = np.asarray(acc2["rebuilt"])
    assert int(reb.sum()) == int(np.asarray(
        w._telem_acc["rebuilt"]).sum()) + 1


def test_one_trace_per_config_and_signature_stability():
    """TRACE_COUNTS: the live fold compiles ONCE per World config, and
    the signature classes are stable across further ticks (no
    per-tick or per-signature retrace)."""
    w = _world()
    w.tick()
    traces0 = telemetry.TRACE_COUNTS.get("telemetry_update_live", 0)
    for _ in range(10):
        w.tick()
    sig1 = w.workload_signature()
    for _ in range(10):
        w.tick()
    sig2 = w.workload_signature()
    assert telemetry.TRACE_COUNTS["telemetry_update_live"] == traces0
    assert sig1["sig"] == sig2["sig"]
    assert sig1["config"] == sig2["config"]


# =======================================================================
# drained lanes: parity, metrics feed, occupancy
# =======================================================================
def test_drained_lanes_track_the_live_world():
    w = _world(n=24)
    ticks = 12
    for _ in range(ticks):
        w.tick()
    lanes = w._telem_lanes
    # every tick contributed exactly one rebuilt sample
    assert sum(lanes["rebuilt"]["counts"]) == ticks
    # skin on: the slack lane exists and carries a sample per tick
    assert sum(lanes["skin_slack"]["counts"]) == ticks
    # occupancy: one sample per shard per tick; per_tile mirrors the
    # true device population (24 NPCs alive in the one shard)
    assert sum(lanes["occupancy"]["counts"]) == ticks
    assert lanes["occupancy"]["per_tile"] == [24]
    # quiet world: the oracle gauges stayed silent
    assert lanes["over_cap_cells"]["counts"][0] == ticks
    sig = w.workload_signature()
    assert sig["density"] == "exact"
    assert sig["ticks"] == ticks

    # vmapped S>1 worlds clear the skin: the lane set follows
    w2 = World(
        WorldConfig(capacity=32, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0, skin=2.0)),
        n_spaces=2,
    )
    w2.create_nil_space()
    w2.tick()
    assert "skin_slack" not in w2._telem_lanes
    assert w2._telem_lanes["occupancy"]["per_tile"] == [0, 0]
    assert w2.workload_signature()["churn"] == "skinless"


def test_pipelined_world_drains_one_tick_behind():
    """pipeline_decode: the drained accumulator is swapped one tick
    back like the outputs — fetching the CURRENT tick's acc would
    depend on the in-flight step and re-serialize exactly the
    host/device overlap the mode exists to buy."""
    w = World(
        WorldConfig(capacity=32, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0)),
        n_spaces=1, pipeline_decode=True,
    )
    w.create_nil_space()
    ticks = 5
    for _ in range(ticks):
        w.tick()
    # folded every tick, drained one behind
    assert sum(w._telem_lanes["rebuilt"]["counts"]) == ticks - 1
    assert int(np.asarray(w._telem_acc["rebuilt"]).sum()) == ticks


def test_lanes_feed_metrics_registry():
    w = _world(n=10)
    for _ in range(3):
        w.tick()
    text = metrics.REGISTRY.expose_text()
    # drained lanes land as shared-ladder histograms + per-tile gauges
    assert "telemetry_rebuilt_count" in text
    assert "telemetry_over_cap_cells_bucket" in text
    assert 'telemetry_tile_occupancy{tile="0"} 10' in text
    snap = metrics.REGISTRY.histogram_snapshot("telemetry_rebuilt")
    assert snap and snap[0][1]["count"] >= 1


def test_telemetry_live_off_is_really_off():
    w = _world(telemetry_live=False)
    for _ in range(3):
        w.tick()
    assert w._telem_fn is None and w._telem_lanes is None
    assert w.workload_signature() is None


def test_histogram_add_counts_rejects_mismatch():
    h = metrics.Histogram(buckets=(1.0, 2.0))
    h.add_counts([1, 2, 3])
    assert h.count == 6
    with pytest.raises(ValueError, match="buckets"):
        h.add_counts([1, 2])


# =======================================================================
# megaspace: comms lanes + per-tile occupancy
# =======================================================================
@pytest.mark.multichip
def test_mega_live_lanes_and_tile_skew():
    from goworld_tpu.parallel.mesh import make_mesh

    n_dev = 4
    radius, tile_w = 10.0, 50.0
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=60.0),
    )
    mesh = make_mesh(n_dev)
    w = World(cfg, n_spaces=n_dev, mesh=mesh, megaspace=True,
              halo_cap=32, migrate_cap=16)
    w.register_space("Mega", Space, megaspace=True)
    w.register_entity("Npc", Npc)
    w.create_nil_space()
    sp = w.create_space("Mega")
    # a deliberate hotspot: every NPC on tile 0
    for i in range(12):
        w.create_entity("Npc", space=sp,
                        pos=(2.0 + (i % 4), 0.0, 5.0 + i // 4),
                        moving=False)
    for _ in range(4):
        w.tick()
    lanes = w._telem_lanes
    # the mega comms lanes ride the live carry
    for nm in ("halo_demand", "migrate_demand", "migrate_dropped"):
        assert sum(lanes[nm]["counts"]) == 4
    assert lanes["occupancy"]["per_tile"] == [12, 0, 0, 0]
    sig = w.workload_signature()
    assert sig["tiles"] == n_dev
    assert sig["skew"] == "hotspot"
    assert "skew=hotspot" in sig["sig"]


# =======================================================================
# acceptance: live GameServer -> /workload + /incidents
# =======================================================================
def test_live_game_serves_workload_and_incidents():
    """ISSUE 11 acceptance: a live (non-bench) GameServer accumulates
    device telemetry per tick, serves its workload signature at
    /workload, and an induced SLO breach (a tick budget far below a
    real tick) freezes a correlated bundle retrievable at
    /incidents."""
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.standalone import ClusterHarness

    flightrec.reset()
    harness = ClusterHarness(n_dispatchers=1, n_gates=1,
                             desired_games=1)
    harness.start()
    world = _world(n=16)
    # budget ~0.05 ms/tick: every real tick (ms-scale on CPU) breaches
    gs = GameServer(1, world, list(harness.dispatcher_addrs),
                    tick_interval=5e-5, gc_freeze_on_boot=False,
                    flightrec_cooldown_secs=0.2)
    gs.start_network()
    t = threading.Thread(target=gs.serve_forever, daemon=True)
    t.start()
    srv = debug_http.start(0, process_name="game1")
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if gs.flightrec is not None \
                    and gs.flightrec.snapshot()["incident_count"] >= 1 \
                    and world.tick_count >= 65:
                break
            time.sleep(0.05)
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/workload") as resp:
            wl = json.loads(resp.read().decode())
        assert wl["game_id"] == 1
        assert wl["density"] == "exact"
        assert "recommendation" in wl and "sig" in wl
        assert wl["ticks"] > 0

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/incidents") as resp:
            inc = json.loads(resp.read().decode())
        rec = inc["game1"]
        assert rec["incident_count"] >= 1
        triggers = {b["trigger"] for b in rec["incidents"]}
        assert "slo_breach" in triggers
        bundle = next(b for b in rec["incidents"]
                      if b["trigger"] == "slo_breach")
        # the bundle is CORRELATED: per-tick frames around the breach
        # + freeze-time context with the resolved kernel config
        assert bundle["frames"]
        last = bundle["frames"][-1]
        assert last["tick_ms"] > last["budget_ms"]
        assert "sweep_impl=" in bundle["context"]["kernel_config"]
        assert "stage" in last and "over_cap" in last
        # the signature refresh cadence stamped signature marks into
        # the frame stream (tick 64+ reached above)
        snap = gs.flightrec.snapshot(frames=True)
        assert any("signature" in f for f in snap["live_frames"]) \
            or any("signature" in f for b in rec["incidents"]
                   for f in b["frames"])
    finally:
        srv.shutdown()
        srv.server_close()
        gs.stop()
        t.join(timeout=5)
        harness.stop()
        flightrec.reset()
