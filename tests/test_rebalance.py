"""Self-healing deployment rebalance (ISSUE 19): the pure
sustained-DEGRADED decision policy (hold-run hysteresis, the
plan-window cancellation point, per-pair ping-pong cooldown,
byte-identical decision-log replay), the bounded cohort handoff
executor (space-affine cohorts, rate-limited sends, admission pause,
the timeout abort that restores every unacked entity live on the
source), the burst-aware conservation grace, the ``/rebalance``
endpoint, the ``rebalance_action`` trigger, and a live two-world
controller drive through the real migration machinery."""

import importlib.util
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import goworld_tpu.rebalance as rebalance
from goworld_tpu.rebalance import (
    HandoffExecutor,
    RebalanceController,
    RebalancePolicy,
    canonical_observation,
    scraped_observation,
)
from goworld_tpu.utils import audit, debug_http, flightrec, metrics
from goworld_tpu.utils.overload import state_rank

pytestmark = pytest.mark.rebalance


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_registries():
    metrics.REGISTRY.reset()
    rebalance.reset()
    yield
    metrics.REGISTRY.reset()
    rebalance.reset()


def _obs(e1, e2, s1="NORMAL", s2="NORMAL", p1=True, p2=True):
    return {
        "game1": {"stage": s1, "entities": e1, "present": p1},
        "game2": {"stage": s2, "entities": e2, "present": p2},
    }


HOT = _obs(100, 10, s1="DEGRADED")
COLD = _obs(100, 10)


# =======================================================================
# policy: hold-run hysteresis and the plan->commit window
# =======================================================================
def test_state_rank_orders_states_and_tolerates_unknown():
    assert state_rank("NORMAL") == 0
    assert state_rank("DEGRADED") == 1
    assert state_rank("SHEDDING") == 2
    assert state_rank("REJECTING") == 3
    # a scrape gap / version skew must never synthesize load
    assert state_rank("WAT") == 0


def test_canonical_observation_sorts_and_defaults():
    canon = canonical_observation(
        {"game2": {"entities": "7"}, "game1": {"stage": "DEGRADED",
                                               "entities": 3}})
    assert list(canon) == ["game1", "game2"]
    assert canon["game2"] == {"stage": "NORMAL", "entities": 7,
                              "present": True}
    assert canon["game1"]["stage"] == "DEGRADED"


def test_policy_validates_knobs_loudly():
    with pytest.raises(ValueError):
        RebalancePolicy(hold_windows=0)
    with pytest.raises(ValueError):
        RebalancePolicy(batch=0)
    with pytest.raises(ValueError):
        RebalancePolicy(cooldown_windows=0)
    with pytest.raises(ValueError):
        HandoffExecutor(object(), game_id=1, batch=0)


def test_one_noisy_window_resets_the_hold_run():
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=4)
    assert p.observe(HOT) is None
    assert p.observe(HOT) is None
    assert p.observe(COLD) is None   # run resets
    assert p.observe(HOT) is None
    assert p.observe(HOT) is None
    assert p.planned == 0            # never reached hold_windows
    assert p.observe(HOT) is None    # run=3: plan staged, not committed
    assert p.planned == 1 and p.committed == 0


def test_commit_fires_one_window_after_plan():
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=4)
    for _ in range(3):
        assert p.observe(HOT) is None
    action = p.observe(HOT)
    assert action == {"frm": "game1", "to": "game2", "batch": 8,
                      "reason": "sustained_DEGRADED", "window": 4}
    assert p.committed == 1


def test_donor_recovery_during_planning_cancels_the_move():
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=4)
    for _ in range(3):
        p.observe(HOT)               # plan staged at window 3
    assert p.observe(COLD) is None   # the cause evaporated
    assert p.cancelled == 1 and p.committed == 0
    assert any("cancel cause=donor_recovered" in ln
               for ln in p.log.lines)
    # the cancel is not a cooldown: a fresh sustained run commits
    for _ in range(3):
        p.observe(HOT)
    assert p.observe(HOT) is not None


def test_target_losing_headroom_during_planning_cancels():
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=4)
    for _ in range(3):
        p.observe(HOT)
    # target ballooned: 95 + 8 > 100 — no strict improvement left
    assert p.observe(_obs(100, 95, s1="DEGRADED")) is None
    assert p.cancelled == 1
    assert any("cancel cause=target_unfit" in ln for ln in p.log.lines)


def test_target_vanishing_during_planning_cancels():
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=4)
    for _ in range(3):
        p.observe(HOT)
    assert p.observe(_obs(100, 10, s1="DEGRADED", p2=False)) is None
    assert p.cancelled == 1


def test_no_target_without_strict_improvement():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=4)
    # 96 + 8 > 100: moving the batch would just trade places
    near = _obs(100, 96, s1="DEGRADED")
    p.observe(near)
    p.observe(near)
    p.observe(near)
    assert p.planned == 0
    assert any(ln.startswith("no_target") for ln in p.log.lines)


def test_absent_game_is_never_hot_and_never_a_target():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=4)
    ghost = _obs(100, 10, s1="DEGRADED", p1=False)
    for _ in range(4):
        p.observe(ghost)
    assert p.planned == 0            # absent donor never builds a run
    gone = _obs(100, 10, s1="DEGRADED", p2=False)
    for _ in range(4):
        p.observe(gone)
    assert p.planned == 0            # absent target is never fit


# =======================================================================
# policy: ping-pong suppression (satellite 3)
# =======================================================================
def test_alternating_load_commits_at_most_one_move_per_cooldown():
    """Load alternating between two games must not trade the same
    cohort back and forth: the sorted-pair cooldown suppresses the
    reverse move, so any two commits are >= cooldown_windows apart."""
    p = RebalancePolicy(hold_windows=3, batch=8, cooldown_windows=8)
    commits = []
    for w in range(1, 33):
        # roles swap every 4 windows — game1 hot, then game2 hot, ...
        if (w - 1) // 4 % 2 == 0:
            obs = _obs(100, 10, s1="DEGRADED")
        else:
            obs = _obs(10, 100, s2="DEGRADED")
        if p.observe(obs) is not None:
            commits.append(w)
    assert commits, "alternating load never committed a single move"
    for a, b in zip(commits, commits[1:]):
        assert b - a >= p.cooldown_windows, commits
    assert any(ln.startswith("cooldown") for ln in p.log.lines)


def test_cooldown_suppresses_the_reverse_move():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=10)
    for _ in range(2):
        p.observe(HOT)
    assert p.observe(HOT) is not None        # game1 -> game2 commits
    rev = _obs(10, 100, s2="DEGRADED")       # roles instantly swap
    for _ in range(5):
        assert p.observe(rev) is None        # reverse move suppressed
    assert p.committed == 1
    assert any("cooldown frm=game2 to=game1" in ln
               for ln in p.log.lines)


def test_abort_feedback_rearms_the_pair_cooldown():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=6)
    for _ in range(2):
        p.observe(HOT)
    assert p.observe(HOT) is not None
    p.feedback("abort", cause="timeout", frm="game1", to="game2",
               restored=8)
    # the donor stays hot but the pair that just crashed mid-handoff
    # must not be hammered again inside the re-armed cooldown
    for _ in range(5):
        assert p.observe(HOT) is None
    assert p.committed == 1
    assert any(ln.startswith("result cause=timeout") or
               "cause=timeout" in ln for ln in p.log.lines)


# =======================================================================
# policy: byte-identical replay (the governor/promotion convention)
# =======================================================================
def test_decision_log_replays_byte_identical():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=5)
    seq = [HOT, HOT, HOT, COLD, HOT, HOT,
           _obs(100, 95, s1="DEGRADED"),   # target_unfit cancel
           HOT, HOT, HOT]
    for obs in seq:
        p.observe(obs)
    p.feedback("abort", cause="timeout", frm="game1", to="game2",
               restored=8)
    for obs in (HOT, COLD, HOT):
        p.observe(obs)
    assert p.log.dump() == RebalancePolicy.replay(
        p.log.inputs, hold_windows=2, batch=8, cooldown_windows=5)


def test_replay_diverges_for_different_knobs():
    p = RebalancePolicy(hold_windows=2, batch=8, cooldown_windows=5)
    for _ in range(4):
        p.observe(HOT)
    assert p.log.dump() != RebalancePolicy.replay(
        p.log.inputs, hold_windows=4, batch=8, cooldown_windows=5)


# =======================================================================
# satellite 1: burst-aware conservation grace
# =======================================================================
def _ledger_snap(tick, in_flight, ins=(), live=0, created=0,
                 destroyed=0):
    return {"kind": "game", "entities": live, "created": created,
            "destroyed": destroyed, "tick": tick,
            "in_flight": list(in_flight), "in_records": list(ins),
            "violations_total": {}}


def test_rate_limited_batch_straddling_verdict_stays_green():
    """A 64-entity rebalance batch drains at 8 entities/tick across
    ticks 93..100; a batched scraper precomputed every record's
    ``age_ticks`` anchored at the batch HEAD (stale by the whole batch
    span). The verdict must re-age each record from its OWN
    migrate-out tick — every true age is <= 8, so nothing is lost."""
    recs = []
    for i in range(64):
        out_tick = 93 + i // 8
        recs.append({"eid": f"B{i:03d}", "seq": 2, "target": 2,
                     "tick": out_tick,
                     # the poisoned batch-head anchor: 100 - 93 + junk
                     "age_ticks": 57})
    snap = _ledger_snap(100, recs, live=36, created=100)
    v = audit.conservation_verdict([snap])
    assert v["ok"], v["problems"]
    assert v["in_flight"] == 64
    assert v["lost"] == []


def test_genuinely_old_record_in_a_fresh_batch_is_still_named():
    recs = [{"eid": f"B{i:03d}", "seq": 2, "target": 2, "tick": 99,
             "age_ticks": 0} for i in range(8)]
    # one record whose OWN out tick is ancient — a fresh batch around
    # it must not launder it through a batch-level age
    recs.append({"eid": "LOST0", "seq": 3, "target": 2, "tick": 80,
                 "age_ticks": 0})
    snap = _ledger_snap(100, recs, live=91, created=100)
    v = audit.conservation_verdict([snap])
    assert not v["ok"]
    assert any("LOST0" in pr for pr in v["problems"])
    assert all("B00" not in pr for pr in v["problems"])


def test_verdict_falls_back_to_precomputed_age_without_tick():
    rec = {"eid": "X1", "seq": 2, "target": 2, "age_ticks": 50}
    snap = _ledger_snap(100, [rec], live=99, created=100)
    v = audit.conservation_verdict([snap])
    assert not v["ok"]               # an honest peer-provided age
    assert any("X1" in pr for pr in v["problems"])


def test_cross_game_out_matched_by_in_record_is_not_outstanding():
    out = _ledger_snap(
        100, [{"eid": "M1", "seq": 4, "target": 2, "tick": 50,
               "age_ticks": 50}],
        live=9, created=10)
    tgt = _ledger_snap(100, [], ins=[{"eid": "M1", "seq": 4,
                                      "tick": 52}],
                       live=1, created=0)
    v = audit.conservation_verdict([out, tgt])
    assert v["ok"], v["problems"]
    assert v["in_flight"] == 0


# =======================================================================
# executor on real worlds
# =======================================================================
@pytest.fixture
def world_factory():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.ops.aoi import GridSpec

    class Mob(Entity):
        ATTRS = {"hp": "allclients hot:0"}

    made = []

    def make(game_id, n=12, seed=31):
        cfg = WorldConfig(
            capacity=64,
            grid=GridSpec(radius=30.0, extent_x=200.0,
                          extent_z=200.0),
            input_cap=64,
        )
        w = World(cfg, n_spaces=1, game_id=game_id, audit=True)
        w.register_entity("Mob", Mob)
        w.register_space("Arena", Space)
        w.create_nil_space()
        sp = w.create_space("Arena")
        rng = np.random.default_rng(seed)
        ents = []
        for _ in range(n):
            x, z = rng.uniform(20.0, 180.0, 2)
            ents.append(sp.create_entity(
                "Mob", pos=(float(x), 0.0, float(z))))
        made.append(w)
        return w, sp, ents

    yield make
    for w in made:
        audit.unregister(f"game{w.game_id}")
        if w.audit is not None:
            w.audit.close()


def _census(w):
    out = {e.id for e in w.entities.values() if not e.destroyed}
    if w.nil_space is not None:
        out.discard(w.nil_space.id)
    return out


def test_plan_cohort_is_sorted_space_affine_and_capped(world_factory):
    donor, dsp, ents = world_factory(941)
    agent = HandoffExecutor(donor, game_id=941, batch=4)
    sid, eids = agent.plan_cohort()
    assert sid == dsp.id             # the most populated non-nil space
    assert eids == sorted(e.id for e in ents)[:4]
    _, all_eids = agent.plan_cohort(batch=64)
    assert all_eids == sorted(e.id for e in ents)


def test_clean_handoff_partitions_census_and_counts_moves(
        world_factory):
    donor, dsp, _ents = world_factory(941)
    recv, rsp, _ = world_factory(942, n=0)
    agent = HandoffExecutor(donor, game_id=941, batch=6)
    acked = []

    def send(eid, data):
        recv.restore_from_migration(data, space=rsp)
        agent.ack(eid)
        acked.append(eid)

    original = _census(donor)
    rbase = _census(recv)
    n = agent.start(942, "sustained_DEGRADED", send, batch=6, rate=3)
    assert n == 6 and agent.busy
    assert not donor.admission_allowed(dsp.id)   # paused mid-move
    assert agent.pump() == 3                     # rate-limited window
    assert agent.busy
    donor.audit.drain(); recv.audit.drain()
    v = audit.conservation_verdict([
        donor.audit.snapshot(tick=donor.tick_count),
        recv.audit.snapshot(tick=recv.tick_count)])
    assert v["ok"], v["problems"]                # green MID-batch
    assert agent.pump() == 3
    assert not agent.busy and agent.completed == 1
    moved = _census(recv) - rbase
    assert len(moved) == 6 == len(acked)
    assert (_census(donor) | moved) == original  # zero lost
    assert not (_census(donor) & moved)          # zero duplicated
    assert donor.admission_allowed(dsp.id)       # resumed on finish
    res = agent.take_result()
    assert res == {"kind": "done", "cause": "", "target": 942,
                   "restored": 0, "moved": 6}
    assert agent.take_result() is None           # consumed once
    assert agent.snapshot()["moves_total"] == {
        "game941->game942:sustained_DEGRADED": 6}
    donor.audit.drain(); recv.audit.drain()
    v = audit.conservation_verdict([
        donor.audit.snapshot(tick=donor.tick_count),
        recv.audit.snapshot(tick=recv.tick_count)])
    assert v["ok"], v["problems"]


def test_timeout_abort_restores_every_unacked_entity(world_factory):
    donor, _dsp, _ = world_factory(943)
    agent = HandoffExecutor(donor, game_id=943, batch=6)
    limbo = []
    original = _census(donor)
    n = agent.start(9, "sustained_SHEDDING",
                    send=lambda eid, data: limbo.append(eid),
                    batch=6, rate=6, timeout_windows=2)
    assert n == 6
    assert agent.pump() == 6
    assert len(_census(donor)) == len(original) - 6
    donor.audit.drain()
    v = audit.conservation_verdict(
        [donor.audit.snapshot(tick=donor.tick_count)])
    assert v["ok"], v["problems"]    # in flight, inside the grace
    for _ in range(3):               # idle windows 1..3 > 2
        agent.pump()
    assert not agent.busy and agent.aborted == 1
    assert agent.aborts_total == {"timeout": 1}
    assert _census(donor) == original  # every unacked entity is LIVE
    donor.audit.drain()
    v = audit.conservation_verdict(
        [donor.audit.snapshot(tick=donor.tick_count)])
    assert v["ok"], v["problems"]    # the self-round-trip retired it
    res = agent.take_result()
    assert res["kind"] == "abort" and res["cause"] == "timeout"
    assert res["restored"] == 6 and res["moved"] == 0
    note = agent.take_action_note()
    assert note is not None and "abort" in note


def test_admission_pause_blocks_creates_until_abort(world_factory):
    from goworld_tpu.entity.manager import AdmissionPausedError

    donor, dsp, _ = world_factory(944)
    agent = HandoffExecutor(donor, game_id=944, batch=4)
    agent.start(9, "manual", send=lambda *a: None, batch=4, rate=2)
    with pytest.raises(AdmissionPausedError):
        dsp.create_entity("Mob", pos=(50.0, 0.0, 50.0))
    agent.abort("operator")
    e = dsp.create_entity("Mob", pos=(50.0, 0.0, 50.0))
    assert e.id in _census(donor)
    assert agent.aborts_total == {"operator": 1}


def test_start_refuses_to_interleave_handoffs(world_factory):
    donor, _dsp, _ = world_factory(945)
    agent = HandoffExecutor(donor, game_id=945, batch=4)
    agent.start(9, "manual", send=lambda *a: None, batch=4)
    with pytest.raises(RuntimeError):
        agent.start(8, "manual", send=lambda *a: None, batch=4)
    agent.abort("operator")


# =======================================================================
# live two-world controller drive (satellite 3, live half)
# =======================================================================
def test_live_controller_hands_off_once_and_donor_recovers(
        world_factory):
    donor, _dsp, _ = world_factory(947, n=12)
    recv, rsp, _ = world_factory(948, n=0)
    policy = RebalancePolicy(hold_windows=2, batch=4,
                             cooldown_windows=6)
    agent = HandoffExecutor(donor, game_id=947, batch=4)
    mailbox = []
    ctl = RebalanceController(
        policy, agents={"game947": agent},
        transport=lambda action: (
            lambda eid, data: mailbox.append((eid, data))),
        rate=2)
    original = _census(donor)
    rbase = _census(recv)
    hot = len(original) - 2          # NORMAL once half the batch left
    commits, stages = [], []
    for w_i in range(1, 15):
        arriving, mailbox[:] = mailbox[:], []
        for eid, data in arriving:   # one-window wire
            recv.restore_from_migration(data, space=rsp)
            agent.ack(eid)
        d_stage = ("DEGRADED" if len(_census(donor)) >= hot
                   else "NORMAL")
        stages.append(d_stage)
        obs = {
            "game947": {"stage": d_stage,
                        "entities": len(_census(donor)),
                        "present": True},
            "game948": {"stage": "NORMAL",
                        "entities": len(_census(recv) - rbase),
                        "present": True},
        }
        if ctl.step(obs) is not None:
            commits.append(w_i)
    assert commits == [3]            # exactly one move, no ping-pong
    moved = _census(recv) - rbase
    assert len(moved) == 4
    assert (_census(donor) | moved) == original
    assert not (_census(donor) & moved)
    assert "NORMAL" in stages[3:]    # the donor OBSERVED healthy again
    assert agent.completed == 1 and agent.aborted == 0
    # the whole live run replays byte-identically from its inputs
    assert policy.log.dump() == RebalancePolicy.replay(
        policy.log.inputs, hold_windows=2, batch=4,
        cooldown_windows=6)
    donor.audit.drain(); recv.audit.drain()
    v = audit.conservation_verdict([
        donor.audit.snapshot(tick=donor.tick_count),
        recv.audit.snapshot(tick=recv.tick_count)])
    assert v["ok"], v["problems"]


# =======================================================================
# scraped observations, /rebalance endpoint, flightrec trigger
# =======================================================================
def test_scraped_observation_takes_worst_governor_state():
    row = scraped_observation(
        "game3",
        {"governors": {"aoi": {"state": "NORMAL"},
                       "tick": {"state": "SHEDDING"}}},
        {"entities": 42})
    assert row == {"name": "game3", "stage": "SHEDDING",
                   "entities": 42, "present": True}
    gone = scraped_observation("game4", None, None, present=False)
    assert gone["present"] is False and gone["stage"] == "NORMAL"
    assert gone["entities"] == 0


def test_rebalance_endpoint_serves_snapshot_and_handoff_action(
        world_factory):
    donor, _dsp, _ = world_factory(946)
    rebalance.register(
        "game946", HandoffExecutor(donor, game_id=946, batch=4))
    calls = []
    rebalance.set_handoff_hook(
        lambda target, batch: (calls.append((target, batch))
                               or {"status": "queued",
                                   "target": target}))
    srv = debug_http.start(0, process_name="game946")
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/rebalance", timeout=5) as r:
            payload = json.loads(r.read())
        assert payload["agents"]["game946"]["busy"] is False
        assert payload["agents"]["game946"]["handoffs"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/rebalance?handoff=2&batch=8",
                timeout=5) as r:
            assert json.loads(r.read()) == {"status": "queued",
                                            "target": 2}
        assert calls == [(2, 8)]
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/rebalance?handoff=nope",
                timeout=5)
    finally:
        srv.shutdown()


def test_request_handoff_without_hook_is_an_honest_error():
    out = rebalance.request_handoff(2, 8)
    assert "error" in out


def test_rebalance_action_trigger_freezes_the_frame():
    rec = flightrec.FlightRecorder(ring=8)
    assert rec.record({"tick": 1}) == []
    incidents = rec.record(
        {"tick": 2,
         "rebalance": "start to=game2 batch=4 space=S reason=manual"})
    assert [i["trigger"] for i in incidents] == ["rebalance_action"]
    assert "start to=game2" in incidents[0]["detail"]


# =======================================================================
# config knobs
# =======================================================================
def test_config_rebalance_knobs_default_off_and_parse(tmp_path):
    from goworld_tpu import config as cfgmod

    dflt = cfgmod.ClusterConfig()
    assert dflt.rebalance is False
    assert dflt.rebalance_hold_windows == 3
    assert dflt.rebalance_batch == 64
    assert dflt.rebalance_cooldown_secs == 30.0
    p = tmp_path / "goworld_tpu.ini"
    p.write_text("[deployment]\nrebalance = true\n"
                 "rebalance_hold_windows = 5\nrebalance_batch = 32\n"
                 "rebalance_cooldown_secs = 12.5\n")
    cfg = cfgmod.load(str(p))
    assert cfg.rebalance is True
    assert cfg.rebalance_hold_windows == 5
    assert cfg.rebalance_batch == 32
    assert cfg.rebalance_cooldown_secs == 12.5


# =======================================================================
# cluster scrapers: obs_aggregate + scrape_metrics rebalance lines
# =======================================================================
_AGENT_SNAP = {
    "game": "game3", "busy": True,
    "job": {"target": "game5", "space_id": "sp1", "queued": 4,
            "unacked": 6, "sent": 18, "acked": 12, "windows": 2,
            "reason": "sustained_DEGRADED"},
    "handoffs": 2, "completed": 1, "aborted": 0,
    "moves_total": {"game3->game5:sustained_DEGRADED": 24},
    "aborts_total": {},
}


def test_obs_aggregate_rebalance_lines_render_agents_and_controller():
    agg_tool = _load_tool("obs_aggregate")
    agg = {"rebalance": {
        "agents": [
            {"source": "game3:game3", **_AGENT_SNAP},
            # idle, history-free wiring must stay silent
            {"source": "game4:game4", "game": "game4", "busy": False,
             "job": None, "handoffs": 0, "completed": 0,
             "aborted": 0, "moves_total": {}, "aborts_total": {}},
        ],
        "controller": {"source": "dispatcher", "policy": {
            "window": 41, "committed": 2, "planned": 3,
            "pending": {"frm": "game3", "to": "game5"},
            "runs": {"game3": 2},
        }},
    }}
    lines = agg_tool.rebalance_lines(agg)
    assert len(lines) == 2
    assert "rebalance game3 BUSY" in lines[0]
    assert "12/18 acked" in lines[0]
    assert "6 in flight" in lines[0]
    assert "24 entities moved" in lines[0]
    assert "controller (dispatcher)" in lines[1]
    assert "2 committed / 3 planned" in lines[1]
    assert "hot runs game3:2" in lines[1]
    assert agg_tool.rebalance_lines({"rebalance": {}}) == []


def test_scrape_metrics_rebalance_lines_per_process():
    scraper = _load_tool("scrape_metrics")
    scraped = {"game3": {"agents": {"game3": _AGENT_SNAP}},
               "game4": {"agents": {"game4": {
                   "game": "game4", "busy": False, "job": None,
                   "handoffs": 0, "completed": 0, "aborted": 0,
                   "moves_total": {}, "aborts_total": {}}}}}
    lines = scraper.rebalance_lines(scraped)
    assert len(lines) == 1
    assert lines[0].startswith("game3: rebalance game3 BUSY")
    assert "-> game5 12/18 acked, 6 in flight" in lines[0]


def test_aggregate_rebalance_totals_from_live_endpoint():
    """aggregate_rebalance against a REAL debug-http process: the
    registry's agents land with source labels and the deployment
    totals sum over them."""
    from goworld_tpu import rebalance as rb_registry
    from goworld_tpu.utils import debug_http

    agg_tool = _load_tool("obs_aggregate")

    class _StubAgent:
        def snapshot(self):
            return dict(_AGENT_SNAP)

    rb_registry.register("game3", _StubAgent())
    srv = debug_http.start(0, process_name="rbtest")
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        agg = agg_tool.aggregate_rebalance([("rbtest", base)])
        assert [a["source"] for a in agg["agents"]] \
            == ["rbtest:game3"]
        assert agg["busy"] == 1
        assert agg["moves_total"] == 24
        assert agg["aborts_total"] == 0
    finally:
        srv.shutdown()


# =======================================================================
# chaos soak wiring (tier-1 smoke) + the full soak (slow)
# =======================================================================
def test_chaos_soak_wires_the_rebalance_scenario():
    soak = _load_tool("chaos_soak")
    assert callable(soak.run_rebalance)
    assert callable(soak._run_rebalance_variant)
    src = open(os.path.join(REPO, "tools", "chaos_soak.py")).read()
    assert '"rebalance"' in src.split("add_argument(\"--scenario\"")[1]\
        .split(")")[0]
    # the in-process branch (no --dir needed) includes it
    assert 'args.scenario in ("governor", "audit", "failover",' in src


@pytest.mark.slow
def test_chaos_soak_rebalance_scenario_converges():
    """tools/chaos_soak.py --scenario rebalance end-to-end: the clean
    handoff fires after the hold, the donor recovers within budget,
    zero entities lost or duplicated, the conservation verdict green
    every window including mid-batch, AND the target-kill variant
    aborts by timeout with every unacked entity restored live on the
    source — the ISSUE-19 acceptance run."""
    soak = _load_tool("chaos_soak")
    report = soak.run_rebalance(seed=77)
    clean, kill = report["clean"], report["target_kill"]
    assert clean.get("error") is None, clean
    assert clean["converged"], clean
    assert clean["entities_moved"] == clean["batch"]
    assert clean["max_in_flight_seen"] > 0
    assert kill.get("error") is None, kill
    assert kill["converged"], kill
    assert kill["abort_cause"] == "timeout"
    assert kill["entities_restored"] == (kill["batch"]
                                         - kill["entities_moved"])
    assert report["converged"]


@pytest.mark.slow
def test_chaos_soak_rebalance_is_seed_deterministic():
    """Same seed, same decision log — the seeded-replay guarantee
    extends to the whole soak harness, not just the pure policy."""
    soak = _load_tool("chaos_soak")
    a = soak._run_rebalance_variant(7, kill_target=False)
    b = soak._run_rebalance_variant(7, kill_target=False)
    assert a.get("error") is None, a
    assert a["decision_log"] == b["decision_log"]
    assert a["entities_moved"] == b["entities_moved"]
    assert a["commit_window"] == b["commit_window"]
