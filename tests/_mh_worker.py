"""Worker process for tests/test_multihost.py — runs one controller of a
2-process megaspace over a global 8-device mesh and prints JSON results.

Invoked as: python -m tests._mh_worker <process_id> <coordinator_port>
(env must already carry JAX_PLATFORMS=cpu and
 XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""

import json
import sys


def main() -> int:
    pid = int(sys.argv[1])
    port = sys.argv[2]
    mode = sys.argv[3] if len(sys.argv) > 3 else "walker"

    from goworld_tpu.parallel.multihost import (
        global_mesh, init_distributed, local_shard_indices,
        local_shard_outputs,
    )
    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

    import jax
    import numpy as np
    from goworld_tpu.core.state import WorldConfig, spawn
    from goworld_tpu.ops.aoi import GridSpec
    from goworld_tpu.parallel import MegaConfig, MultiTickInputs
    from goworld_tpu.parallel.megaspace import (
        create_mega_state, make_mega_tick,
    )
    from goworld_tpu.parallel.mesh import shard_state

    n_dev, tile_w, radius = 8, 100.0, 10.0
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=8, cell_cap=16, row_block=16),
        npc_speed=5.0,
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mc = MegaConfig(cfg=cfg, n_dev=n_dev, tile_w=tile_w,
                    halo_cap=8, migrate_cap=4)
    mesh = global_mesh()
    assert mesh.devices.size == n_dev, "expected 8 global devices"
    step = make_mega_tick(mc, mesh)
    st = create_mega_state(mc)

    from tests.conftest import spawn_on

    if mode == "stress":
        return stress(pid, mesh, mc, cfg, step, st, spawn_on)

    # IDENTICAL program on both controllers (SPMD): a walker just west of
    # the tile-3/tile-4 border (the process boundary: devices 0-3 are
    # process 0, 4-7 process 1) heading east, plus a stationary watcher
    # on tile 4 that must see the walker as a ghost before it migrates.
    st = spawn_on(st, 3, 0, pos=(398.5, 0.0, 50.0))
    st = spawn_on(st, 4, 0, pos=(401.0, 0.0, 50.0))
    st = shard_state(st, mesh)

    inputs = MultiTickInputs.empty(cfg, n_dev)
    # drive the walker east by client position syncs: 1 unit/tick for a
    # FIXED 2 ticks (398.5 -> 400.5 crosses the border), then stop — the
    # drive schedule must be identical on both controllers (SPMD: the
    # input arrays must never depend on process-local observations)
    enters_seen = []
    migrated_tick = -1
    x = 398.5
    for t in range(6):
        x += 1.0
        base = inputs.base
        base = base.replace(
            pos_sync_idx=base.pos_sync_idx.at[:, 0].set(0),
            pos_sync_vals=base.pos_sync_vals.at[:, 0, :].set(
                jax.numpy.asarray([x, 0.0, 50.0, 0.0])
            ),
            pos_sync_n=base.pos_sync_n.at[3].set(1 if t < 2 else 0),
        )
        st, out = step(st, inputs.replace(base=base), None)
        idxs, outs = local_shard_outputs(out, mesh)
        for i, o in zip(idxs, outs):
            if int(o.arr_n) > 0 and migrated_tick < 0 and i == 4:
                migrated_tick = t
            n_ent = int(o.base.enter_n)
            for w, j in zip(
                np.asarray(o.base.enter_w)[:n_ent],
                np.asarray(o.base.enter_j)[:n_ent],
            ):
                enters_seen.append((i, int(w), int(j)))
    ga = int(np.asarray(
        out.global_alive.addressable_shards[0].data
    ).ravel()[0])
    print(json.dumps({
        "process": pid,
        "local_shards": local_shard_indices(mesh),
        "migrated_tick": migrated_tick,
        "enters": enters_seen[:16],
        "global_alive": ga,
    }), flush=True)
    return 0


def stress(pid, mesh, mc, cfg, step, st, spawn_on) -> int:
    """Churny SPMD run: 60 movers spread over all 8 tiles for 40 ticks
    with the deterministic random walk (identical device rng on both
    controllers). Reports per-tick global_alive, local shard occupancy
    and migration counts for cross-controller consistency checks."""
    import jax
    import numpy as np
    from goworld_tpu.parallel.multihost import (
        local_shard_indices, local_shard_outputs,
    )

    n_dev = mc.n_dev
    rng = np.random.default_rng(13)           # same seed on BOTH
    next_slot = [0] * n_dev
    for _ in range(60):
        tile = int(rng.integers(0, n_dev))
        slot = next_slot[tile]
        next_slot[tile] += 1
        st = spawn_on(
            st, tile, slot,
            pos=(rng.uniform(tile * mc.tile_w, (tile + 1) * mc.tile_w),
                 0.0, rng.uniform(0, 100.0)),
            npc_moving=True,
        )
    from goworld_tpu.parallel.mesh import shard_state
    st = shard_state(st, mesh)
    from goworld_tpu.parallel import MultiTickInputs
    inputs = MultiTickInputs.empty(cfg, n_dev)

    galive = []
    migrations = 0
    dropped = 0
    for _ in range(40):
        st, out = step(st, inputs, None)
        idxs, outs = local_shard_outputs(out, mesh)
        galive.append(int(np.asarray(
            out.global_alive.addressable_shards[0].data
        ).ravel()[0]))
        for o in outs:
            migrations += int(o.arr_n)
            dropped += int(o.migrate_dropped)
    # local occupancy from addressable state shards only
    occ = {}
    for s_ in st.alive.addressable_shards:
        row = s_.index[0].start or 0
        occ[row] = int(np.asarray(s_.data).sum())
    print(json.dumps({
        "process": pid,
        "local_shards": local_shard_indices(mesh),
        "global_alive": galive,
        "occupancy": occ,
        "migrations": migrations,
        "dropped": dropped,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
