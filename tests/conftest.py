"""Test env: force CPU with 8 virtual devices so mesh/sharding tests run
without TPU hardware (the multi-node-without-a-cluster capability noted in
SURVEY.md#4). Must run before jax is imported anywhere."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env sets axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup (to
# register the axon TPU plugin), which binds jax_platforms=axon BEFORE this
# conftest runs — the env override above is then too late and every mesh
# test would silently run on the single TPU device. jax.config.update still
# works as long as no backend client has been created, so force it here.
if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "soak: long-running load tests (the reload-under-load soak)",
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-process / long tests (multihost mesh, soak)",
    )
    config.addinivalue_line(
        "markers",
        "soak_full: the reference CI's 200-bot/300s profile "
        "(RUN_SOAK_FULL=1 to enable; ~7 min)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (tests/test_chaos.py); the "
        "fast smoke runs in tier-1, the full soak is also marked slow",
    )
    config.addinivalue_line(
        "markers",
        "overload: overload-protection ladder tests "
        "(tests/test_overload.py); the live smoke runs in tier-1, the "
        "chaos_soak overload scenario is also marked slow",
    )
    config.addinivalue_line(
        "markers",
        "pallas: interpret-mode Pallas kernel suites (the fused AOI "
        "back half and the counting-sort fill kernel); all run in "
        "tier-1 on CPU — the marker exists to select exactly the "
        "kernel-parity set before/after a relay window",
    )
    config.addinivalue_line(
        "markers",
        "scenarios: adversarial-workload suites (tests/test_scenarios"
        ".py + the scenario-driven AOI regressions); the small-N "
        "oracle gates run in tier-1, long soaks are also marked slow",
    )
    config.addinivalue_line(
        "markers",
        "multichip: megaspace mesh suites (the scan-driven multichip "
        "bench path, halo_impl async/ppermute parity, mesh "
        "schema/trend gates — tests/test_multichip_bench.py, "
        "test_halo_async.py); tier-1 on 8 fake CPU devices at small N "
        "— the marker selects exactly the mesh set before/after a "
        "relay window",
    )
    config.addinivalue_line(
        "markers",
        "devprof: device-plane observability suites (XLA cost auditor, "
        "in-graph telemetry lanes, roofline audit, bench trend/schema "
        "gates — tests/test_devprof.py, test_bench_trend.py, "
        "test_bench_schema.py); all run in tier-1 on CPU",
    )
    config.addinivalue_line(
        "markers",
        "precision: quantized state-plane suites (the q16 lattice "
        "sweep/Verlet parity vs the snapped oracle, the delta-sync "
        "codec, the delta snapshot chain — tests/test_precision.py + "
        "the precision rows in test_aoi_parity.py); all run in tier-1 "
        "on CPU — the marker selects exactly the quantized-plane set "
        "before/after a relay window",
    )
    config.addinivalue_line(
        "markers",
        "flightrec: live workload-signature + incident flight-recorder "
        "suites (the production telemetry carry, /workload + "
        "/incidents, trigger/dedup/replay determinism — "
        "tests/test_flightrec.py, tests/test_telemetry_live.py); all "
        "run in tier-1 on CPU",
    )
    config.addinivalue_line(
        "markers",
        "governor: online kernel-governor suites (goworld_tpu/autotune "
        "— policy hysteresis/replay determinism, warm-set AOT "
        "executables, live mid-churn swap oracle exactness, the "
        "regret guard, /governor, the recommendation-key contract — "
        "tests/test_governor.py); all run in tier-1 on CPU "
        "(docs/AUTOTUNE.md)",
    )
    config.addinivalue_line(
        "markers",
        "syncage: end-to-end sync-age plane suites (the per-batch "
        "stamp trailer, gate age-at-delivery histograms, the "
        "deployment aggregator, the sync_age_breach trigger — "
        "tests/test_syncage.py); all run in tier-1 on CPU "
        "(docs/OBSERVABILITY.md \"End-to-end sync age\")",
    )
    config.addinivalue_line(
        "markers",
        "residency: serve-loop residency plane suites (host-sync "
        "bubble accounting, alloc-churn census, the scan-marginal vs "
        "serve gap, /residency, the residency_regression trigger — "
        "tests/test_residency.py); all run in tier-1 on CPU "
        "(docs/OBSERVABILITY.md \"Serve-loop residency\")",
    )
    config.addinivalue_line(
        "markers",
        "audit: correctness audit plane suites (entity-ownership "
        "ledger census/seq semantics, deployment conservation "
        "verdicts, the sampled live AOI oracle, mirror probes, "
        "/audit, the audit_violation trigger, the trailer "
        "coexistence wire contract — tests/test_audit.py); all run "
        "in tier-1 on CPU (docs/OBSERVABILITY.md \"Correctness "
        "audit plane\")",
    )
    config.addinivalue_line(
        "markers",
        "replication: hot-standby replication suites (stream frame "
        "CRC chaining + torn-stream taxonomy, double-apply lattice "
        "determinism, the bounded replication worker's "
        "never-block-the-tick contract, standby apply/mirror "
        "semantics, kvreg promotion arbitration incl. both "
        "stale-claim race orders, /standby — "
        "tests/test_replication.py); all run in tier-1 on CPU "
        "(docs/ROBUSTNESS.md \"Hot-standby & promotion\")",
    )
    config.addinivalue_line(
        "markers",
        "resident: resident-world runtime suites (carry donation "
        "deleted-buffer fencing on freeze/census/governor paths, "
        "donation on/off bit-parity across the skin/precision/vmap "
        "matrix, mid-churn governor swap exactness under donation, "
        "the 0-realloc census verdict, the resident_ab trend gate — "
        "tests/test_resident.py); all run in tier-1 on CPU "
        "(docs/OBSERVABILITY.md \"Serve-loop residency\")",
    )
    config.addinivalue_line(
        "markers",
        "rebalance: self-healing deployment rebalance suites "
        "(goworld_tpu/rebalance — sustained-DEGRADED hold/hysteresis "
        "policy, ping-pong cooldown suppression, plan-window "
        "cancellation, byte-identical decision-log replay, bounded "
        "cohort handoff + abort restore through the migration "
        "protocol, admission pause, the burst-aware conservation "
        "grace, /rebalance, the rebalance_action trigger — "
        "tests/test_rebalance.py); all run in tier-1 on CPU "
        "(docs/ROBUSTNESS.md \"Elastic rebalancing\")",
    )


def spawn_on(states, dev, slot, **kw):
    """Spawn into one device's shard of a stacked [n_dev, ...] state
    (shared by the parallel/megaspace/multihost tests)."""
    import jax

    from goworld_tpu.core.state import spawn

    one = jax.tree.map(lambda x: x[dev], states)
    one = spawn(one, slot, **kw)
    return jax.tree.map(
        lambda full, new: full.at[dev].set(new), states, one
    )
