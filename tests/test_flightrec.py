"""Incident flight recorder (utils/flightrec.py) + workload-signature
reducer (ops/telemetry.py): trigger grammar, dedup/cooldown, ring
bounds, deterministic replay, and the /workload + /incidents
endpoints. All jax-free except the endpoint smoke."""

import json
import urllib.error
import urllib.request

import pytest

from goworld_tpu.ops import telemetry
from goworld_tpu.utils import debug_http, flightrec

pytestmark = pytest.mark.flightrec


class FakeClock:
    """Deterministic injectable clock (replay tests)."""

    def __init__(self, step: float = 1.0):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return self.t


def _frame(tick, tick_ms=1.0, budget=16.0, stage="NORMAL",
           over_cap=0, **kw):
    f = {"tick": tick, "tick_ms": tick_ms, "budget_ms": budget,
         "stage": stage, "over_cap": over_cap}
    f.update(kw)
    return f


# =======================================================================
# triggers
# =======================================================================
def test_slo_breach_trigger_and_bundle_shape():
    rec = flightrec.FlightRecorder(ring=32, cooldown_secs=0.0,
                                   clock=FakeClock())
    for i in range(10):
        assert rec.record(_frame(i)) == []
    new = rec.record(_frame(10, tick_ms=40.0))
    assert len(new) == 1
    b = new[0]
    assert b["trigger"] == "slo_breach"
    assert b["tick"] == 10
    # the bundle carries the ring tail, newest last, breach included
    assert b["frames"][-1]["tick"] == 10
    assert len(b["frames"]) == 11


def test_overload_transition_trigger():
    rec = flightrec.FlightRecorder(ring=16, cooldown_secs=0.0,
                                   clock=FakeClock())
    rec.record(_frame(0, stage="NORMAL"))
    assert rec.record(_frame(1, stage="NORMAL")) == []
    new = rec.record(_frame(2, stage="DEGRADED"))
    assert [b["trigger"] for b in new] == ["overload_transition"]
    assert "NORMAL>DEGRADED" in new[0]["detail"]
    # recovery is a transition too (post-mortems need both edges)
    new = rec.record(_frame(3, stage="NORMAL"))
    assert [b["trigger"] for b in new] == ["overload_transition"]


def test_over_cap_fires_only_after_quiet():
    rec = flightrec.FlightRecorder(ring=64, cooldown_secs=0.0,
                                   quiet_ticks=4, clock=FakeClock())
    # steady saturation from tick 0: never "after quiet", never fires
    for i in range(8):
        assert rec.record(_frame(i, over_cap=3)) == []
    # quiet run, then the anomaly
    for i in range(8, 14):
        assert rec.record(_frame(i, over_cap=0)) == []
    new = rec.record(_frame(14, over_cap=2))
    assert [b["trigger"] for b in new] == ["over_cap_after_quiet"]
    # still overflowing next tick: quiet run was reset, no re-fire
    assert rec.record(_frame(15, over_cap=2)) == []


def test_signature_change_trigger():
    rec = flightrec.FlightRecorder(ring=16, cooldown_secs=0.0,
                                   clock=FakeClock())
    rec.record(_frame(0, signature="churn=flock_like"))
    assert rec.record(_frame(1, signature="churn=flock_like")) == []
    new = rec.record(_frame(2, signature="churn=teleport_like"))
    assert [b["trigger"] for b in new] == ["signature_change"]


# =======================================================================
# dedup / cooldown / bounds
# =======================================================================
def test_cooldown_dedups_per_kind():
    clock = FakeClock(step=1.0)  # 1 s per observation
    rec = flightrec.FlightRecorder(ring=16, cooldown_secs=10.0,
                                   clock=clock)
    fired = sum(
        len(rec.record(_frame(i, tick_ms=40.0))) for i in range(25)
    )
    # ~1 fire per 10 clock-seconds over 25 seconds of breaches
    assert fired == 3
    snap = rec.snapshot()
    assert snap["fired"]["slo_breach"] == 25
    assert snap["suppressed"]["slo_breach"] == 22
    assert snap["incident_count"] == 3
    # cooldown is PER KIND: a transition still freezes during an
    # slo_breach cooldown window
    new = rec.record(_frame(26, tick_ms=40.0, stage="DEGRADED"))
    assert [b["trigger"] for b in new] == ["overload_transition"]


def test_ring_and_incident_bounds():
    rec = flightrec.FlightRecorder(ring=8, cooldown_secs=0.0,
                                   snapshot_frames=999,
                                   max_incidents=4, clock=FakeClock())
    for i in range(100):
        rec.record(_frame(i, tick_ms=40.0))
    snap = rec.snapshot(frames=True)
    assert len(snap["live_frames"]) == 8       # ring bound holds
    assert snap["incident_count"] == 4          # incident bound holds
    assert snap["frames_recorded"] == 100
    # snapshot_frames clamps to the ring
    assert all(len(b["frames"]) <= 8 for b in snap["incidents"])
    # bounded incidents keep the NEWEST
    assert snap["incidents"][-1]["tick"] == 99


def test_rejects_zero_ring():
    with pytest.raises(ValueError, match="ring"):
        flightrec.FlightRecorder(ring=0)


# =======================================================================
# deterministic replay
# =======================================================================
def test_replay_is_byte_identical():
    frames = []
    for i in range(200):
        frames.append(_frame(
            i,
            tick_ms=40.0 if i % 37 == 0 else 1.0,
            stage="DEGRADED" if 50 <= i < 80 else "NORMAL",
            over_cap=2 if i in (120, 121) else 0,
            signature="a" if i < 150 else "b",
        ))

    def run():
        rec = flightrec.FlightRecorder(ring=32, cooldown_secs=13.0,
                                       clock=FakeClock(step=0.5))
        out = []
        for f in frames:
            out.extend(rec.record(f))
        # wall_time is the one non-injected stamp; everything else is a
        # pure function of the (frame, clock) stream
        for b in out:
            b.pop("wall_time", None)
        return out

    a, b = run(), run()
    assert a  # the stream actually fires
    assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                       sort_keys=True)


# =======================================================================
# workload-signature reducer (jax-free)
# =======================================================================
def _lanes(ticks=100, rebuilds=10, over_k=0, over_cap=0, enter_hi=0,
           enter_bucket=7, skin=True, occ=None):
    """Synthetic drained lanes: `rebuilds` of `ticks` in the rebuilt
    bucket, overflow gauges nonzero on `over_*` ticks, `enter_hi`
    ticks with ~1000 enter events."""

    def hist(edges, nonzero, hi_bucket=1):
        counts = [0] * (len(edges) + 1)
        counts[0] = ticks - nonzero
        counts[hi_bucket] = nonzero
        return {"edges": list(edges), "counts": counts}

    lanes = {
        "rebuilt": hist(telemetry.REBUILD_EDGES, rebuilds),
        "over_k_rows": hist(telemetry.COUNT_EDGES, over_k),
        "over_cap_cells": hist(telemetry.COUNT_EDGES, over_cap),
        "enter_n": hist(telemetry.COUNT_EDGES, enter_hi,
                        hi_bucket=enter_bucket),
        "leave_n": hist(telemetry.COUNT_EDGES, enter_hi,
                        hi_bucket=enter_bucket),
        "sync_n": hist(telemetry.COUNT_EDGES, 0),
        "tick_ms": hist(telemetry.TICK_MS_EDGES, 0),
    }
    if skin:
        lanes["skin_slack"] = hist(telemetry.SLACK_EDGES, ticks,
                                   hi_bucket=6)
    if occ is not None:
        lanes["occupancy"] = {
            "edges": list(telemetry.COUNT_EDGES),
            "counts": [0] * (len(telemetry.COUNT_EDGES) + 1),
            "per_tile": occ,
        }
    return lanes


def test_signature_classes():
    sig = telemetry.workload_signature(_lanes(rebuilds=10))
    assert sig["churn"] == "flock_like"
    assert sig["density"] == "exact"
    assert sig["events"] == "quiet"
    assert sig["recommendation"]["aoi_skin"] == "keep"
    assert sig["sig"] == "churn=flock_like|density=exact|events=quiet"

    sig = telemetry.workload_signature(_lanes(rebuilds=95))
    assert sig["churn"] == "teleport_like"
    assert sig["recommendation"]["aoi_skin"] == 0

    sig = telemetry.workload_signature(_lanes(skin=False))
    assert sig["churn"] == "skinless"
    assert "aoi_skin" not in sig["recommendation"]

    sig = telemetry.workload_signature(_lanes(over_k=30))
    assert sig["density"] == "over_k"
    assert sig["recommendation"]["aoi_sort_impl"] == "counting"
    assert sig["recommendation"]["aoi_k"] == "raise"

    sig = telemetry.workload_signature(_lanes(over_k=5, over_cap=30))
    assert sig["density"] == "over_cap"     # loudest degradation wins
    assert sig["recommendation"]["aoi_cell_cap"] == "raise"

    sig = telemetry.workload_signature(_lanes(enter_hi=95))
    assert sig["events"] == "moderate"

    sig = telemetry.workload_signature(
        _lanes(enter_hi=95, enter_bucket=9))
    assert sig["events"] == "heavy"


def test_signature_recommends_delta_sync_when_dirty_is_low():
    """ISSUE 12 satellite: under quiet/flock_like windows with a low
    sync-record duty the reducer recommends `[gameN] sync_delta = 1`
    (the int16-delta fan-out pays off exactly there); teleport-like
    churn never recommends it (every jump overflows the int16 delta
    range — the stream would be all keyframes)."""
    sig = telemetry.workload_signature(_lanes(rebuilds=10))
    assert sig["churn"] == "flock_like" and sig["events"] == "quiet"
    assert sig["recommendation"]["sync_delta"] == 1

    # quiet + skinless: still recommended (dirty volume is the gate)
    sig = telemetry.workload_signature(_lanes(skin=False))
    assert sig["events"] == "quiet"
    assert sig["recommendation"]["sync_delta"] == 1

    # teleport-like churn: excluded even when quiet
    sig = telemetry.workload_signature(_lanes(rebuilds=95))
    assert sig["churn"] == "teleport_like"
    assert "sync_delta" not in sig["recommendation"]

    # heavy sync volume: the p50 gate holds it back
    lanes = _lanes(rebuilds=10)
    lanes["sync_n"]["counts"] = [0] * len(lanes["sync_n"]["counts"])
    lanes["sync_n"]["counts"][9] = 100     # p50 in a high bucket
    sig = telemetry.workload_signature(lanes)
    assert sig.get("sync_p50", 0) > 64
    assert "sync_delta" not in sig["recommendation"]


def test_signature_tile_skew():
    sig = telemetry.workload_signature(
        _lanes(occ=[100, 100, 100, 100]))
    assert sig["skew"] == "balanced"
    assert sig["tiles"] == 4
    sig = telemetry.workload_signature(_lanes(occ=[380, 10, 5, 5]))
    assert sig["skew"] == "hotspot"
    assert sig["tile_skew"] > 3.0
    assert "skew=hotspot" in sig["sig"]
    # one tile = no skew class (nothing to compare)
    sig = telemetry.workload_signature(_lanes(occ=[100]))
    assert "skew" not in sig


def test_signature_honest_on_empty():
    assert "error" in telemetry.workload_signature({})
    assert "error" in telemetry.workload_signature(_lanes(ticks=0))


def test_lanes_delta():
    cur = _lanes(ticks=100, rebuilds=40, occ=[7, 9])
    prev = _lanes(ticks=60, rebuilds=35)
    d = telemetry.lanes_delta(cur, prev)
    assert sum(d["rebuilt"]["counts"]) == 40
    assert d["rebuilt"]["counts"][1] == 5
    # point-in-time extras come from CUR, never differenced
    assert d["occupancy"]["per_tile"] == [7, 9]
    # no prior window: the cumulative IS the window
    assert telemetry.lanes_delta(cur, None) is cur


# =======================================================================
# registry + endpoints
# =======================================================================
def test_workload_and_incidents_endpoints():
    flightrec.reset()
    rec = flightrec.register(
        "game9", flightrec.FlightRecorder(ring=16, cooldown_secs=0.0,
                                          clock=FakeClock()))
    rec.record(_frame(0))
    rec.record(_frame(1, tick_ms=99.0))
    flightrec.set_workload_provider(
        lambda: {"sig": "churn=skinless|density=exact|events=quiet",
                 "ticks": 2})
    srv = debug_http.start(0, process_name="game9")
    try:
        port = srv.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}") as resp:
                return json.loads(resp.read().decode())

        wl = get("/workload")
        assert wl["sig"].startswith("churn=")
        inc = get("/incidents")
        assert inc["game9"]["incident_count"] == 1
        assert inc["game9"]["incidents"][0]["trigger"] == "slo_breach"
        assert "live_frames" not in inc["game9"]
        inc = get("/incidents?frames=1")
        assert len(inc["game9"]["live_frames"]) == 2
        # endpoint list advertises the new paths
        try:
            get("/nope")
        except urllib.error.HTTPError as e:
            listing = json.loads(e.read().decode())["endpoints"]
            assert "/workload" in listing and "/incidents" in listing
    finally:
        srv.shutdown()
        srv.server_close()
        flightrec.reset()


def test_scrape_workload_lines_format():
    """tools/scrape_metrics.py workload_lines: one signature +
    incident-count line per GAME process; processes without a live
    world (gates/dispatchers serving the endpoint, 404s) skip
    silently — the /costs convention."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "scrape_metrics_under_test",
        os.path.join(repo, "tools", "scrape_metrics.py"))
    scraper = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(scraper)

    scraped = {
        "game1": {
            "workload": {
                "sig": "churn=flock_like|density=exact|events=low",
                "ticks": 128,
                "recommendation": {"aoi_skin": "keep"},
            },
            "incidents": {"game1": {"incident_count": 2}},
        },
        # a gate answering /workload with the honest no-provider error
        "gate1": {"workload": {"error": "no live workload provider"}},
    }
    lines = scraper.workload_lines(scraped)
    assert len(lines) == 1
    assert lines[0].startswith("game1: workload churn=flock_like")
    assert "recommend aoi_skin=keep" in lines[0]
    assert lines[0].endswith("| incidents 2")


def test_workload_endpoint_honest_without_provider():
    flightrec.reset()
    assert "error" in flightrec.workload_snapshot()
    flightrec.set_workload_provider(lambda: None)
    assert "error" in flightrec.workload_snapshot()

    def boom():
        raise RuntimeError("provider died")

    flightrec.set_workload_provider(boom)
    assert "provider died" in flightrec.workload_snapshot()["error"]
    flightrec.reset()
