"""Adversarial scenario matrix (goworld_tpu/scenarios, ISSUE 7).

tier-1 gates, one per registry scenario: the full interest-set contract
(device lists == brute-force per-entity-radius oracle, interested_by
mirrors it, client mirrors == interest sets) must hold under EVERY
adversarial workload — hotspot convergence, battle-royale shrink,
teleport churn (incl. host-side respawn churn through the real World
API) and mixed-radius populations. Plus the heterogeneous-dispatch
acceptance criterion: a >= 3-behavior mix compiles to ONE traced tick
(one vmapped ``lax.switch``; asserted via the TRACE_COUNTS trace
counters in scenarios/behaviors.py — zero per-behavior retrace across
ticks), and the spec registry's validation / bench-name resolution.
"""

import dataclasses

import numpy as np
import pytest

from goworld_tpu.scenarios.runner import run_scenario
from goworld_tpu.scenarios.spec import (
    BEHAVIORS,
    LEGACY_BEHAVIORS,
    SCENARIOS,
    ScenarioSpec,
    assign_behavior_ids,
    assign_watch_radii,
    bench_workloads,
    get_scenario,
    resolve_bench_behavior,
    scenario_names,
)

pytestmark = pytest.mark.scenarios

_INF = float("inf")


# ----------------------------------------------------------------------
# spec validation (GridSpec.__post_init__ style: loud at construction)
# ----------------------------------------------------------------------

class TestSpecValidation:
    def test_unknown_mix_behavior_rejected(self):
        with pytest.raises(ValueError, match="mix behavior must be"):
            ScenarioSpec(name="x", mix=(("warp_drive", 1.0),))

    def test_mix_fractions_must_sum_to_one(self):
        with pytest.raises(ValueError, match="sum to 1"):
            ScenarioSpec(name="x", mix=(("hotspot", 0.5),
                                        ("flock", 0.4)))

    def test_zero_fraction_rejected(self):
        with pytest.raises(ValueError, match="fraction"):
            ScenarioSpec(name="x", mix=(("hotspot", 0.0),
                                        ("flock", 1.0)))

    def test_zero_radius_class_rejected(self):
        # radius 0 would silently exclude the class from AOI
        with pytest.raises(ValueError, match="radii must be > 0"):
            ScenarioSpec(name="x", radius_mix=((0.0, 0.5), (_INF, 0.5)))

    def test_radius_fractions_must_sum_to_one(self):
        with pytest.raises(ValueError, match="sum to 1"):
            ScenarioSpec(name="x", radius_mix=((10.0, 0.5),))

    def test_churn_rate_bounds(self):
        with pytest.raises(ValueError, match="churn_rate"):
            ScenarioSpec(name="x", churn_rate=1.0)

    def test_teleport_prob_bounds(self):
        with pytest.raises(ValueError, match="teleport_prob"):
            ScenarioSpec(name="x", teleport_prob=1.5)

    def test_phase_periods_positive(self):
        with pytest.raises(ValueError, match="shrink_over"):
            ScenarioSpec(name="x", shrink_over=0)

    def test_unknown_scenario_lists_registry(self):
        with pytest.raises(KeyError, match="hotspot"):
            get_scenario("nope")

    def test_registry_covers_roadmap_worst_cases(self):
        names = scenario_names()
        for nm in ("hotspot", "shrink", "flock", "teleport",
                   "mixed_radius", "mixed"):
            assert nm in names
        # the acceptance spec: >= 3 behaviors in ONE world
        assert len(get_scenario("mixed").behavior_names) >= 3


# ----------------------------------------------------------------------
# bench workload resolution (the BENCH_BEHAVIOR satellite: ONE home for
# the accepted set and its error message)
# ----------------------------------------------------------------------

class TestBenchResolution:
    def test_legacy_behaviors_resolve_homogeneous(self):
        for b in LEGACY_BEHAVIORS:
            assert resolve_bench_behavior(b) == (b, None)

    def test_scenario_names_resolve_to_specs(self):
        for nm in scenario_names():
            behavior, spec = resolve_bench_behavior(nm)
            assert behavior == "random_walk"
            assert spec is SCENARIOS[nm]

    def test_unknown_name_error_names_both_sets(self):
        with pytest.raises(ValueError) as exc:
            resolve_bench_behavior("warp")
        msg = str(exc.value)
        for nm in bench_workloads():
            assert nm in msg

    def test_bench_workloads_is_union(self):
        assert bench_workloads() == LEGACY_BEHAVIORS + scenario_names()


# ----------------------------------------------------------------------
# deterministic population assignment
# ----------------------------------------------------------------------

class TestAssignment:
    def test_behavior_ids_exact_proportions(self):
        spec = get_scenario("mixed")
        ids = assign_behavior_ids(spec, 100)
        counts = np.bincount(ids, minlength=len(spec.mix))
        for i, (_, f) in enumerate(spec.mix):
            assert abs(int(counts[i]) - f * 100) <= 1
        assert counts.sum() == 100

    def test_behavior_ids_deterministic_and_shuffled(self):
        spec = get_scenario("mixed")
        a = assign_behavior_ids(spec, 64)
        b = assign_behavior_ids(spec, 64)
        assert np.array_equal(a, b)
        # slot order must not correlate with behavior: not sorted
        assert not np.array_equal(a, np.sort(a))

    def test_single_member_mix_fills_every_slot(self):
        spec = get_scenario("hotspot")
        assert np.all(assign_behavior_ids(spec, 17) == 0)

    def test_watch_radii_match_mix(self):
        spec = get_scenario("mixed_radius")
        radii = assign_watch_radii(spec, 50)
        vals, counts = np.unique(radii, return_counts=True)
        want = {r: f for r, f in spec.radius_mix}
        assert set(vals) == set(want)
        for v, c in zip(vals, counts):
            assert abs(int(c) - want[float(v)] * 50) <= 1


# ----------------------------------------------------------------------
# the tier-1 oracle gates: EVERY registry scenario, full contract
# ----------------------------------------------------------------------

@pytest.mark.parametrize("name", scenario_names())
def test_registry_scenario_oracle_exact(name):
    """Interest sets == brute-force oracle, interested_by mirrors,
    client mirrors == interest sets — checked repeatedly while the
    adversarial motion (and, for teleport, respawn churn) runs through
    the real World API."""
    rep = run_scenario(name, n=40, ticks=6, oracle_every=3,
                       client_frac=0.25, seed=3,
                       # 2 units/tick: enough motion that interest
                       # actually churns inside 6 ticks (default-speed
                       # drift is ~0.08/tick — a near-static gate)
                       cfg_kw=dict(npc_speed=120.0),
                       raise_on_mismatch=True)
    assert rep.oracle_ok
    assert rep.oracle_ticks_checked == 2


def test_teleport_churn_exercises_slot_reuse():
    """Respawn churn high enough to actually recycle slots at small N:
    every freed slot is re-spawned same-tick (the one-tick quarantine
    path) and the contract still holds on every checked tick."""
    spec = dataclasses.replace(get_scenario("teleport"),
                               churn_rate=0.15)
    rep = run_scenario(spec, n=40, ticks=8, oracle_every=2,
                       client_frac=0.2, seed=5,
                       raise_on_mismatch=True)
    assert rep.churned >= 6 * 7  # 6 per tick from tick 1
    assert rep.oracle_ok


def test_skin_cadence_flock_reuses_teleport_thrashes():
    """The workload-vs-kernel interplay the subsystem exists to expose:
    under one skin setting, flock (slow correlated motion) almost never
    rebuilds while teleport rebuilds nearly every tick — both exact."""
    flock = run_scenario("flock", n=48, ticks=10, oracle_every=5,
                         skin=6.0, client_frac=0.0, seed=7,
                         raise_on_mismatch=True)
    # at small N the registry's 1% churn leaves whole ticks teleport-
    # free; 20% makes >= 1 jump per tick near-certain (and the jump is
    # world-scale, >> skin/2 by construction)
    tspec = dataclasses.replace(get_scenario("teleport"),
                                teleport_prob=0.2, churn_rate=0.0)
    tele = run_scenario(tspec, n=48, ticks=10, oracle_every=5,
                        skin=6.0, client_frac=0.0, seed=7,
                        raise_on_mismatch=True)
    assert flock.rebuilds <= 3          # cold build + stragglers
    assert tele.rebuilds >= 8           # ~every tick trips the cond
    assert flock.oracle_ok and tele.oracle_ok


def test_shrink_migration_pressure_rises():
    """The battle-royale phase schedule produces sustained interest
    migration: enter events keep arriving well after the start (the
    zone keeps forcing movement), and the density (AOI demand) grows
    as the zone contracts."""
    spec = dataclasses.replace(get_scenario("shrink"), shrink_over=30)
    # npc_speed 180 -> 3 units/tick at 60 Hz: the default 5 moves
    # ~0.08/tick, which would leave every enter event on tick 1 and
    # make both assertions vacuously compare identical runs
    kw = dict(cfg_kw=dict(npc_speed=180.0))
    early = run_scenario(spec, n=48, ticks=4, oracle_every=0,
                         client_frac=0.0, seed=11, **kw)
    late = run_scenario(spec, n=48, ticks=28, oracle_every=0,
                        client_frac=0.0, seed=11, **kw)
    assert late.demand_max > early.demand_max
    assert late.enter_events > early.enter_events


# ----------------------------------------------------------------------
# heterogeneous dispatch: ONE traced tick, no per-behavior retrace
# ----------------------------------------------------------------------

def _scenario_cfg(spec, n=96, skin=0.0):
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.ops.aoi import GridSpec

    return WorldConfig(
        capacity=n,
        grid=GridSpec(radius=20.0, extent_x=150.0, extent_z=150.0,
                      k=16, cell_cap=32, row_block=n, skin=skin),
        npc_speed=5.0,
        scenario=spec,
    )


def test_mixed_population_single_trace_no_retrace():
    """The ISSUE 7 acceptance criterion, asserted via trace counting:
    a >= 3-behavior world compiles each member kernel in ONE trace of
    the tick, and ticking N more times re-traces NOTHING."""
    import jax

    from goworld_tpu.core.state import create_state, spawn
    from goworld_tpu.core.step import TickInputs, make_tick
    from goworld_tpu.scenarios import behaviors as B

    spec = get_scenario("mixed")
    assert len(spec.behavior_names) >= 3
    cfg = _scenario_cfg(spec)
    st = create_state(cfg, seed=1)
    rng = np.random.default_rng(1)
    for s in range(64):
        st = spawn(st, s, pos=(rng.random() * 150, 0.0,
                               rng.random() * 150),
                   npc_moving=True)
    tick = make_tick(cfg)
    ins = TickInputs.empty(cfg)

    before = dict(B.TRACE_COUNTS)
    st, out = tick(st, ins, None)         # the one compile
    jax.block_until_ready(st.pos)
    after_compile = dict(B.TRACE_COUNTS)
    deltas = {
        name: after_compile.get(name, 0) - before.get(name, 0)
        for name in spec.behavior_names
    }
    # every mix member traced, all as part of the SAME switch trace
    assert all(d >= 1 for d in deltas.values()), deltas
    assert len(set(deltas.values())) == 1, deltas

    for _ in range(5):                    # steady state: zero retrace
        st, out = tick(st, ins, None)
    jax.block_until_ready(st.pos)
    assert dict(B.TRACE_COUNTS) == after_compile, \
        "per-behavior retrace detected"


def test_mixed_legacy_members_need_and_get_policy():
    """random_walk/mlp/btree as switch members of one population: the
    World auto-builds the MLP policy when the mix demands it and the
    oracle contract holds for the heterogeneous world."""
    spec = ScenarioSpec(
        name="legacy_mix_test",
        mix=(("random_walk", 0.34), ("mlp", 0.33), ("btree", 0.33)),
    )
    assert spec.needs_policy
    rep = run_scenario(spec, n=36, ticks=6, oracle_every=3,
                       client_frac=0.2, seed=13,
                       raise_on_mismatch=True)
    assert rep.oracle_ok


def test_scenario_velocity_requires_behavior_lane():
    """A scenario config with a lane-less state fails loudly (not with
    a shape error three layers deep)."""
    import jax

    from goworld_tpu.core.state import create_state
    from goworld_tpu.scenarios.behaviors import scenario_velocity

    cfg = _scenario_cfg(get_scenario("hotspot"), n=16)
    st = create_state(cfg, seed=0).replace(behavior_id=None)
    with pytest.raises(ValueError, match="behavior_id"):
        scenario_velocity(cfg, jax.random.PRNGKey(0), st.pos, st.yaw,
                          st, None)

    # and an mlp mix without a policy names the real problem
    mspec = ScenarioSpec(name="mlp_only_test", mix=(("mlp", 1.0),))
    mcfg = _scenario_cfg(mspec, n=16)
    mst = create_state(mcfg, seed=0)
    with pytest.raises(ValueError, match="MLPPolicy"):
        scenario_velocity(mcfg, jax.random.PRNGKey(0), mst.pos,
                          mst.yaw, mst, None)


# ----------------------------------------------------------------------
# phase schedule: closed-form in the traced tick counter
# ----------------------------------------------------------------------

def test_scenario_context_schedule():
    import jax.numpy as jnp

    from goworld_tpu.scenarios.behaviors import scenario_context

    spec = dataclasses.replace(get_scenario("shrink"), shrink_over=100)
    cfg = _scenario_cfg(spec, n=16)
    half = 0.5 * min(cfg.grid.extent_x, cfg.grid.extent_z)
    c0 = scenario_context(spec, cfg, jnp.asarray(0, jnp.int32))
    cmid = scenario_context(spec, cfg, jnp.asarray(50, jnp.int32))
    cend = scenario_context(spec, cfg, jnp.asarray(100, jnp.int32))
    cpast = scenario_context(spec, cfg, jnp.asarray(500, jnp.int32))
    assert float(c0["zone_r"]) == pytest.approx(half)
    assert float(c0["zone_r"]) > float(cmid["zone_r"]) \
        > float(cend["zone_r"])
    # shrink holds at the floor, never collapses to 0
    assert float(cend["zone_r"]) == pytest.approx(
        half * spec.shrink_min_frac)
    assert float(cpast["zone_r"]) == float(cend["zone_r"])
    # the hotspot attractor stays strictly inside the world
    for t in (0, 450, 900, 1350):
        c = scenario_context(spec, cfg, jnp.asarray(t, jnp.int32))
        ax, az = (float(c["attractor"][0]), float(c["attractor"][1]))
        assert 0.0 < ax < cfg.grid.extent_x
        assert 0.0 < az < cfg.grid.extent_z
