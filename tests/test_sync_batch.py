"""Vectorized upstream client->server position sync (VERDICT r3 #3).

The reference batches this direction end-to-end: gates append 16B records
per dispatcher (``GateService.go:402-429``), dispatchers split per game
(``DispatcherService.go:770-808``), games decode per record in Go. Here
both Python leg decoders are one searchsorted each:
``World.stage_pos_sync_batch`` (game leg, eid->(shard,slot) intern index)
and ``DispatcherService._h_sync_upstream`` (router leg, eid->game route
index). These tests pin the semantics against the old per-record path and
prove the 10K-clients-in-<5ms budget.
"""

import time

import numpy as np

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net import proto
from goworld_tpu.net.dispatcher import DispatcherService, _SYNC_REC_DTYPE
from goworld_tpu.net.packet import new_packet
from goworld_tpu.ops.aoi import GridSpec


class Npc(Entity):
    pass


class Arena(Space):
    pass


def _mk_world(capacity=64, input_cap=32):
    cfg = WorldConfig(
        capacity=capacity,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=capacity),
        npc_speed=0.0, turn_prob=0.0,
        enter_cap=2048, leave_cap=2048, sync_cap=2048,
        input_cap=input_cap,
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Npc", Npc)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    return w


def _batch(pairs):
    """[(eid, (x, y, z, yaw)), ...] -> (S16[N], f32[N,4])."""
    eids = np.array([e.encode("ascii") for e, _ in pairs], dtype="S16")
    vals = np.array([v for _, v in pairs], np.float32)
    return eids, vals


def test_batch_stage_semantics_match_per_record_path():
    w = _mk_world()
    arena = w.create_space("Arena")
    withc = [
        w.create_entity("Npc", space=arena, pos=(float(i), 0.0, 1.0),
                        client=GameClient(1, f"CID{i:013d}", w))
        for i in range(4)
    ]
    noc = w.create_entity("Npc", space=arena, pos=(50.0, 0.0, 1.0))
    w.tick()

    staged = w.stage_pos_sync_batch(*_batch([
        (withc[0].id, (10.0, 0.0, 10.0, 1.0)),
        (withc[1].id, (20.0, 0.0, 20.0, 2.0)),
        # duplicate for withc[0]: LAST record wins (wire arrival order)
        (withc[0].id, (11.0, 0.0, 11.0, 1.5)),
        # client-less entity and unknown eid: dropped, exactly like the
        # per-record path's `e is None or e.client is None` skip
        (noc.id, (99.0, 0.0, 99.0, 9.0)),
        ("X" * 16, (77.0, 0.0, 77.0, 7.0)),
    ]))
    assert staged == 2

    # host reads see the staged value immediately (reference applies
    # client syncs to the entity synchronously, Entity.go:430-435)
    assert withc[0].position == (11.0, 0.0, 11.0)
    assert withc[0].yaw == 1.5
    assert noc.position == (50.0, 0.0, 1.0)

    w.tick()
    assert np.allclose(w.read_pos(withc[0].shard, withc[0].slot),
                       (11.0, 0.0, 11.0))
    assert np.allclose(w.read_pos(withc[1].shard, withc[1].slot),
                       (20.0, 0.0, 20.0))
    assert np.allclose(w.read_yaw(withc[1].shard, withc[1].slot), 2.0)
    assert np.allclose(w.read_pos(noc.shard, noc.slot), (50.0, 0.0, 1.0))
    # staging consumed: nothing lingers for the next tick
    assert not w._batch_pos_any


def test_host_set_position_shadows_batch_record():
    w = _mk_world()
    arena = w.create_space("Arena")
    e = w.create_entity("Npc", space=arena, pos=(1.0, 0.0, 1.0),
                        client=GameClient(1, "C" * 13, w))
    w.tick()
    w.stage_pos_sync_batch(*_batch([(e.id, (30.0, 0.0, 30.0, 3.0))]))
    e.set_position((60.0, 0.0, 60.0))  # host logic wins over client sync
    w.tick()
    assert np.allclose(w.read_pos(e.shard, e.slot), (60.0, 0.0, 60.0))


def test_client_unbind_invalidates_intern_index():
    w = _mk_world()
    arena = w.create_space("Arena")
    e = w.create_entity("Npc", space=arena, pos=(1.0, 0.0, 1.0),
                        client=GameClient(1, "C" * 13, w))
    w.tick()
    assert w.stage_pos_sync_batch(
        *_batch([(e.id, (5.0, 0.0, 5.0, 0.0))])) == 1
    e.set_client(None)
    assert w.stage_pos_sync_batch(
        *_batch([(e.id, (9.0, 0.0, 9.0, 0.0))])) == 0


def test_despawn_clears_staged_batch_record():
    w = _mk_world()
    arena = w.create_space("Arena")
    e = w.create_entity("Npc", space=arena, pos=(1.0, 0.0, 1.0),
                        client=GameClient(1, "C" * 13, w))
    w.tick()
    w.stage_pos_sync_batch(*_batch([(e.id, (5.0, 0.0, 5.0, 0.0))]))
    sh, sl = e.shard, e.slot
    e.destroy()
    assert not w._batch_pos_mask[sh, sl]
    w.tick()  # no stale scatter onto a freed slot


def test_batch_overflow_defers_to_next_tick():
    w = _mk_world(input_cap=4)
    arena = w.create_space("Arena")
    ents = [
        w.create_entity("Npc", space=arena, pos=(float(i), 0.0, 1.0),
                        client=GameClient(1, f"CID{i:013d}", w))
        for i in range(6)
    ]
    w.tick()
    w.stage_pos_sync_batch(*_batch([
        (e.id, (float(10 + i), 0.0, float(10 + i), 0.0))
        for i, e in enumerate(ents)
    ]))
    w.tick()
    assert w._batch_pos_any          # overflow rows carried over
    w.tick()
    assert not w._batch_pos_any
    for i, e in enumerate(ents):
        assert np.allclose(w.read_pos(e.shard, e.slot),
                           (10.0 + i, 0.0, 10.0 + i))


def test_game_leg_decodes_10k_clients_under_5ms():
    """VERDICT r3 #3 budget: >=10K clients x 10 syncs/s -> one 10K-record
    batch per 100 ms flush, staged in < 5 ms."""
    n = 10_500
    w = _mk_world(capacity=16384, input_cap=16384)
    arena = w.create_space("Arena")
    ents = [
        w.create_entity("Npc", space=arena,
                        pos=(float(i % 120), 0.0, float(i % 100)),
                        client=GameClient(1, f"C{i:014d}", w))
        for i in range(n)
    ]
    w.tick()
    rng = np.random.default_rng(7)
    order = rng.permutation(n)[:10_000]
    eids = np.array([ents[i].id.encode("ascii") for i in order],
                    dtype="S16")
    vals = rng.uniform(0, 100, (10_000, 4)).astype(np.float32)
    w.stage_pos_sync_batch(eids, vals)  # warm (builds the intern index)
    best = min(
        _timed(lambda: w.stage_pos_sync_batch(eids, vals))
        for _ in range(7)
    )
    assert best < 5e-3, f"10K-record stage took {best * 1e3:.2f} ms"


def test_dispatcher_leg_routes_and_skips_blocked():
    d = DispatcherService(1, "127.0.0.1", 0, 2, 1)
    e1, e2, eb = "A" * 16, "B" * 16, "C" * 16
    d._entity_info(e1).game_id = 1
    d._entity_info(e2).game_id = 2
    ib = d._entity_info(eb)
    ib.game_id = 1
    ib.block(60.0)
    d._blocked_until[eb.encode("ascii")] = ib.block_until

    rec = np.zeros(4, _SYNC_REC_DTYPE)
    rec["eid"] = [e1.encode(), e2.encode(), eb.encode(),
                  b"Z" * 16]  # blocked + unknown both drop
    p = new_packet(proto.MT_SYNC_POSITION_YAW_FROM_CLIENT)
    p.append_bytes(rec.tobytes())
    p.rpos = 2
    d._h_sync_upstream(None, None, proto.MT_SYNC_POSITION_YAW_FROM_CLIENT, p)
    assert bytes(d._sync_pending[1]) == rec[0:1].tobytes()
    assert bytes(d._sync_pending[2]) == rec[1:2].tobytes()

    # unblock: records route again; rerouting invalidates the cache
    d._unblock_entity(eb)
    d._entity_info(e2).game_id = 1
    p2 = new_packet(proto.MT_SYNC_POSITION_YAW_FROM_CLIENT)
    p2.append_bytes(rec.tobytes())
    p2.rpos = 2
    d._h_sync_upstream(None, None,
                       proto.MT_SYNC_POSITION_YAW_FROM_CLIENT, p2)
    assert bytes(d._sync_pending[1]) == (
        rec[0:1].tobytes() + rec[0:3].tobytes()
    )


def test_dispatcher_leg_routes_10k_under_5ms():
    d = DispatcherService(1, "127.0.0.1", 0, 2, 1)
    n = 10_000
    eids = [f"E{i:015d}" for i in range(n)]
    for i, eid in enumerate(eids):
        d._entity_info(eid).game_id = 1 + i % 4
    rec = np.zeros(n, _SYNC_REC_DTYPE)
    rec["eid"] = [e.encode() for e in eids]

    def route():
        d._sync_pending.clear()
        p = new_packet(proto.MT_SYNC_POSITION_YAW_FROM_CLIENT)
        p.append_bytes(rec.tobytes())
        p.rpos = 2
        d._h_sync_upstream(
            None, None, proto.MT_SYNC_POSITION_YAW_FROM_CLIENT, p
        )

    route()  # warm (builds the route index)
    best = min(_timed(route) for _ in range(7))
    assert best < 5e-3, f"10K-record route took {best * 1e3:.2f} ms"
    assert sum(len(b) for b in d._sync_pending.values()) == n * 32


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
