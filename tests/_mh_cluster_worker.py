"""Worker for test_multihost.py::test_cross_controller_client_visibility.

The full cluster plane ON TOP of a 2-controller megaspace World: one
dispatcher (process 0), one gate per controller, a GameServer per
controller, and a strict-mirror bot on controller 0's gate whose Avatar
lives on a tile owned by controller 1. The bot must receive
create-entity and position-sync traffic for a Walker moving on that
remote tile — events decoded by controller 1 and routed to gate 1 over
the dispatcher wire by gate id (reference: any client on any gate sees
any entity, ``components/gate/GateService.go:258-306``).

Cross-controller mutation consistency rides the GameServer's per-tick
allgathered mutation log (net/game.py ``_mh_exchange_mutations``): the
client-connect / Login RPC packets land on one controller's dispatcher
connection but are applied on both (the SPMD contract).

Invoked as: python -m tests._mh_cluster_worker <pid> <coord_port> <disp_port>
(env: JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""

import asyncio
import json
import sys
import threading
import time

TICKS = 700
TICK_SLEEP = 0.02


def main() -> int:
    pid = int(sys.argv[1])
    coord_port = sys.argv[2]
    disp_port = int(sys.argv[3])

    from goworld_tpu.parallel.multihost import global_mesh, init_distributed
    init_distributed(f"127.0.0.1:{coord_port}", num_processes=2,
                     process_id=pid)

    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.net.botclient import BotClient
    from goworld_tpu.net.dispatcher import DispatcherService
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.gate import GateService
    from goworld_tpu.ops.aoi import GridSpec

    n_dev, tile_w, radius = 8, 100.0, 10.0
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=8, cell_cap=16, row_block=16),
        npc_speed=0.0,   # motion comes from staged set_position only
        enter_cap=256, leave_cap=256, sync_cap=256, input_cap=64,
    )
    mesh = global_mesh()
    w = World(cfg, n_spaces=n_dev, mesh=mesh, megaspace=True,
              halo_cap=8, migrate_cap=4)

    mega_box = {}

    class Mega(Space):
        pass

    class Account(Entity):
        ATTRS = {"status": "client"}

        def OnClientConnected(self):
            self.attrs["status"] = "online"

        def Login_Client(self, name):
            # Avatar lands on tile 4 (x=415) — controller 1's side
            avatar = self.world.create_entity(
                "Avatar", space=mega_box["sp"], pos=(415.0, 0.0, 50.0),
            )
            avatar.attrs["name"] = name
            self.give_client_to(avatar)
            self.destroy()

    class Avatar(Entity):
        ATTRS = {"name": "allclients"}

    class Walker(Entity):
        pass

    w.registry.register("Mega", Mega, is_space=True, megaspace=True)
    w.register_entity("Account", Account)
    w.register_entity("Avatar", Avatar)
    w.register_entity("Walker", Walker)
    w.create_nil_space()
    mega_box["sp"] = w.create_space("Mega")
    walker = w.create_entity(
        "Walker", space=mega_box["sp"], pos=(418.0, 0.0, 50.0),
        eid="walker_walker_00",
    )

    # ---- cluster plane services on a background asyncio thread --------
    services_ready = threading.Event()
    gate_port_box = {}
    loop_box = {}

    def services_thread() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_box["loop"] = loop

        async def boot():
            if pid == 0:
                d = DispatcherService(
                    1, "127.0.0.1", disp_port,
                    desired_games=2, desired_gates=2,
                )
                asyncio.ensure_future(d.serve())
                await d.started.wait()
            else:
                await asyncio.sleep(1.0)  # let the dispatcher bind first
            g = GateService(
                pid + 1, "127.0.0.1", 0, [("127.0.0.1", disp_port)],
                position_sync_interval_ms=20,
                exit_on_dispatcher_loss=False,
            )
            asyncio.ensure_future(g.serve())
            await g.started.wait()
            gate_port_box["port"] = g.bound_port

        loop.run_until_complete(boot())
        services_ready.set()
        loop.run_forever()

    t = threading.Thread(target=services_thread, daemon=True)
    t.start()
    assert services_ready.wait(30), "cluster services failed to start"

    gs = GameServer(pid + 1, w, [("127.0.0.1", disp_port)],
                    boot_entity="Account")
    gs.start_network()

    # count what THIS controller emits to clients (proof that controller
    # 1 — not 0 — fans out the remote tile's events to gate 1's bot)
    sent = {"create_entity": 0, "sync_records": 0, "attrs": 0,
            "destroy_entity": 0, "rpc": 0, "filter_prop": 0}
    orig_client_sink = w.client_sink
    orig_sync_sink = w.sync_sink

    def counting_client_sink(gate_id, client_id, msg):
        sent[msg["type"]] = sent.get(msg["type"], 0) + 1
        orig_client_sink(gate_id, client_id, msg)

    def counting_sync_sink(gate_id, cids, eids, vals):
        sent["sync_records"] += len(cids)
        orig_sync_sink(gate_id, cids, eids, vals)

    w.client_sink = counting_client_sink
    w.sync_sink = counting_sync_sink

    # ---- the bot (controller 0's gate only) ---------------------------
    bot = None
    bot_future = None
    if pid == 0:
        bot = BotClient("127.0.0.1", gate_port_box["port"], strict=True,
                        nosync=True)

        async def bot_script():
            while not gs.ready_event.is_set():
                await asyncio.sleep(0.1)
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 120)
                bot.call_server("Login_Client", "bob")
                t0 = time.time()
                while time.time() - t0 < 120:
                    if bot.player is not None \
                            and bot.player.type_name == "Avatar":
                        break
                    await asyncio.sleep(0.05)
                # wait until the remote tile's walker is mirrored AND its
                # synced position has visibly advanced
                t0 = time.time()
                while time.time() - t0 < 120:
                    me = bot.entities.get("walker_walker_00")
                    if me is not None and me.pos[0] > 420.5 \
                            and bot.sync_count >= 3:
                        break
                    await asyncio.sleep(0.05)
            finally:
                recv.cancel()
                # hang up: the gate's disconnect notification must
                # propagate through the mutation log and unbind the
                # avatar on BOTH controllers (checked after the loop)
                await bot.conn.close()
        bot_future = asyncio.run_coroutine_threadsafe(
            bot_script(), loop_box["loop"]
        )

    # ---- lockstep tick loop (identical count on both controllers) ----
    # had-client bookkeeping reads WORLD state, which is SPMD-identical,
    # so both controllers record the same facts at the same ticks
    walk_x = 418.0
    avatar_had_client = False
    avatar_gate = None
    for _t in range(TICKS):
        gs.pump()
        has_avatar = any(
            e.type_name == "Avatar" and not e.destroyed
            for e in w.entities.values()
        )
        if has_avatar and walk_x < 424.0:
            walk_x += 0.25
            walker.set_position((walk_x, 0.0, 50.0))
        gs.tick()
        for e in w.entities.values():
            if e.type_name == "Avatar" and e.client is not None:
                avatar_had_client = True
                avatar_gate = e.client.gate_id
        time.sleep(TICK_SLEEP)

    def _client_bound() -> bool:
        return any(
            e.type_name == "Avatar" and not e.destroyed
            and e.client is not None
            for e in w.entities.values()
        )

    # the bot hung up during (or right after) the main loop; keep
    # ticking until the disconnect propagates through the mutation log
    # — the condition is world state, so BOTH controllers run the same
    # number of extra ticks (lockstep preserved)
    extra = 0
    while extra < 400 and _client_bound():
        gs.pump()
        gs.tick()
        time.sleep(TICK_SLEEP)
        extra += 1

    out = {
        "process": pid,
        "local_shards": w.local_shards,
        "walker_shard": walker.shard,
        "sent": sent,
    }
    avatars = [e for e in w.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    out["avatar_shard"] = avatars[0].shard if avatars else None
    out["avatar_had_client"] = avatar_had_client
    out["avatar_gate"] = avatar_gate
    out["disconnect_propagated"] = not _client_bound()
    out["extra_ticks"] = extra
    if pid == 0:
        try:
            bot_future.result(timeout=30)
        except Exception as exc:  # surface, don't hang the exchange
            out["bot_script_error"] = repr(exc)
        me = bot.entities.get("walker_walker_00")
        out["bot_errors"] = bot.errors
        out["bot_player_type"] = (
            bot.player.type_name if bot.player else None
        )
        out["bot_player_name"] = (
            bot.player.attrs.get("name") if bot.player else None
        )
        out["walker_mirror_x"] = me.pos[0] if me is not None else None
        out["bot_sync_count"] = bot.sync_count
        out["bot_mirrors"] = sorted(bot.entities.keys())
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
