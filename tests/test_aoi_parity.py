"""Cross-impl AOI parity: {table, ranges, cellrow, shift, fused} x
{argsort, counting sort} x {skin off, skin on} must produce IDENTICAL
neighbor sets (vs the NumPy oracle) in non-overflow regimes, and the
front-half checksums (sweep_phase_checksum) must agree across sort
lowerings — the counting sort is stable, so it is a pure lowering
choice, and the Verlet skin is exact by the standard bound. The fused
Pallas back half (r6) must additionally be BIT-identical to its split
sibling "ranges" (same candidates, same packed keys, unique valid keys
→ the same top-k) — asserted on raw arrays, not just sets. Structure
follows tests/test_aoi_shift.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from goworld_tpu.ops.aoi import (
    GridSpec,
    grid_neighbors_flags,
    grid_neighbors_verlet,
    init_verlet_cache,
    neighbors_oracle,
    sweep_phase_checksum,
)

# the fused rows run the Pallas kernel in interpret mode on CPU — part
# of the kernel-parity set the `pallas` marker selects around a relay
FUSED = pytest.param("fused", marks=pytest.mark.pallas)

N = 600
EXTENT = 300.0
RADIUS = 25.0
SKIN = 7.5


def _world(seed=5):
    rng = np.random.default_rng(seed)
    pos = np.zeros((N, 3), np.float32)
    pos[:, 0] = rng.random(N) * EXTENT
    pos[:, 2] = rng.random(N) * EXTENT
    alive = rng.random(N) < 0.92
    fb = rng.integers(0, 4, N).astype(np.int32)
    # a second position set, every entity moved < SKIN/2 (reuse-legal)
    pos2 = pos.copy()
    step = rng.normal(0.0, 1.0, (N, 2)).astype(np.float32)
    step = np.clip(step, -SKIN / 2 + 0.1, SKIN / 2 - 0.1)
    pos2[:, 0] = np.clip(pos[:, 0] + step[:, 0], 0, EXTENT - 1e-3)
    pos2[:, 2] = np.clip(pos[:, 2] + step[:, 1], 0, EXTENT - 1e-3)
    return pos, pos2, alive, fb


POS, POS2, ALIVE, FB = _world()
ORACLE = neighbors_oracle(POS, ALIVE, RADIUS)
ORACLE2 = neighbors_oracle(POS2, ALIVE, RADIUS)


def _spec(sweep_impl, sort_impl, skin):
    # generous caps: no k/cell_cap/verlet_cap overflow at this density,
    # so every combo must be EXACT
    return GridSpec(
        radius=RADIUS, extent_x=EXTENT, extent_z=EXTENT,
        k=64, cell_cap=64, row_block=256,
        sweep_impl=sweep_impl, sort_impl=sort_impl, skin=skin,
        verlet_cap=128,
    )


def _sets(nbr):
    nbr = np.asarray(nbr)
    return [set(r[r < N].tolist()) for r in nbr]


def _check_flags(nbr, fl, fb):
    nbr, fl = np.asarray(nbr), np.asarray(fl)
    valid = nbr < N
    assert np.array_equal(fl[valid], fb[np.minimum(nbr, N - 1)][valid] & 3)


@pytest.mark.parametrize("sort_impl", ["argsort", "counting"])
@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "cellrow",
                                        "shift", FUSED])
def test_skinless_matrix_matches_oracle(sweep_impl, sort_impl):
    spec = _spec(sweep_impl, sort_impl, 0.0)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(POS), jnp.asarray(ALIVE),
        flag_bits=jnp.asarray(FB),
    )
    got = _sets(nbr)
    for i in range(N):
        want = ORACLE[i] if ALIVE[i] else set()
        assert got[i] == want, (sweep_impl, sort_impl, i)
    _check_flags(nbr, fl, FB)


@pytest.mark.parametrize("sort_impl", ["argsort", "counting"])
@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "cellrow",
                                        "shift", FUSED])
def test_skin_matrix_matches_oracle_rebuild_and_reuse(sweep_impl,
                                                      sort_impl):
    """Verlet path through every (sweep, sort) front half: the rebuild
    tick AND a moved reuse tick must both be oracle-exact."""
    spec = _spec(sweep_impl, sort_impl, SKIN)
    cache = init_verlet_cache(spec, N)
    nbr, cnt, fl, _s, cache, reb, _sl = grid_neighbors_verlet(
        spec, jnp.asarray(POS), jnp.asarray(ALIVE), cache,
        flag_bits=jnp.asarray(FB),
    )
    assert int(reb) == 1          # cold cache: the front half ran
    got = _sets(nbr)
    for i in range(N):
        want = ORACLE[i] if ALIVE[i] else set()
        assert got[i] == want, ("rebuild", sweep_impl, sort_impl, i)
    _check_flags(nbr, fl, FB)

    nbr2, cnt2, fl2, _s, cache, reb2, _sl = grid_neighbors_verlet(
        spec, jnp.asarray(POS2), jnp.asarray(ALIVE), cache,
        flag_bits=jnp.asarray(FB),
    )
    assert int(reb2) == 0         # under skin/2: the front half skipped
    got2 = _sets(nbr2)
    for i in range(N):
        want = ORACLE2[i] if ALIVE[i] else set()
        assert got2[i] == want, ("reuse", sweep_impl, sort_impl, i)
    _check_flags(nbr2, fl2, FB)


@pytest.mark.parametrize("sweep_impl", ["table", "ranges"])
def test_sweep_phase_checksums_agree_across_sort_impls(sweep_impl):
    """The bench sub-phase probes time the real helpers; the counting
    sort's (order, sorted_row) is bit-identical to argsort's, so the
    'sort' and 'build' checksums must agree exactly."""
    outs = {}
    for sort_impl in ("argsort", "counting"):
        spec = _spec(sweep_impl, sort_impl, 0.0)
        outs[sort_impl] = [
            float(sweep_phase_checksum(
                spec, jnp.asarray(POS), jnp.asarray(ALIVE), phase
            ))
            for phase in ("sort", "build")
        ]
    assert outs["argsort"] == outs["counting"]


@pytest.mark.pallas
@pytest.mark.parametrize("topk_impl", ["exact", "sort", "f32"])
def test_fused_bit_identical_to_ranges(topk_impl):
    """Stronger than the oracle matrix: the fused kernel shares the
    ranges front half and the _pack_keys encoder, and valid keys are
    unique, so its (nbr, cnt, flags) arrays must equal the split
    "ranges" sweep's BIT-FOR-BIT under every exact ranking. argsort
    front half and k=32 keep the interpret-mode cost down — the
    counting front half's bit-parity is proven by the oracle matrix
    above plus test_sort.py, and k only sizes the unrolled
    min-extract."""
    outs = {}
    for sweep_impl in ("ranges", "fused"):
        spec = GridSpec(
            radius=RADIUS, extent_x=EXTENT, extent_z=EXTENT,
            k=32, cell_cap=64, row_block=256,
            sweep_impl=sweep_impl, topk_impl=topk_impl,
        )
        nbr, cnt, fl = grid_neighbors_flags(
            spec, jnp.asarray(POS), jnp.asarray(ALIVE),
            flag_bits=jnp.asarray(FB),
        )
        outs[sweep_impl] = (np.asarray(nbr), np.asarray(cnt),
                            np.asarray(fl))
    for a, b in zip(outs["ranges"], outs["fused"]):
        assert np.array_equal(a, b)


@pytest.mark.pallas
def test_fused_phase_checksums_follow_ranges():
    """The front-half checksums ("sort"/"build") of a fused spec go
    through the shared `sweep_impl in ("ranges", "fused")` build
    branch — a real equality check that the fused front half IS the
    ranges front half. The back-half probes ("gather"/"pack"/"rank")
    are DEFINED to run the split sibling (sweep_phase_checksum maps
    fused -> ranges before calling _sweep), so equality there is the
    contract, not evidence — this leg only guards that a fused config
    can evaluate every bench sub-phase probe without tracing the
    Pallas kernel (finite scalar out, no crash)."""
    for phase in ("sort", "build"):
        a = float(sweep_phase_checksum(
            _spec("ranges", "argsort", 0.0),
            jnp.asarray(POS), jnp.asarray(ALIVE), phase))
        b = float(sweep_phase_checksum(
            _spec("fused", "argsort", 0.0),
            jnp.asarray(POS), jnp.asarray(ALIVE), phase))
        assert a == b, phase
    for phase in ("gather", "pack", "rank"):
        v = float(sweep_phase_checksum(
            _spec("fused", "argsort", 0.0),
            jnp.asarray(POS), jnp.asarray(ALIVE), phase))
        assert np.isfinite(v), phase


@pytest.mark.pallas
def test_pallas_impls_fall_back_to_interpret_off_tpu(monkeypatch,
                                                     caplog):
    """Regression (ISSUE 6 satellite): selecting a Pallas impl on a
    non-TPU backend must fall back to interpret mode with a ONE-TIME
    warning — never fail at trace time, never warn per re-trace."""
    import logging

    import jax

    from goworld_tpu.ops import pallas_compat

    if jax.default_backend() == "tpu":
        pytest.skip("fallback path is for non-TPU backends")
    monkeypatch.setattr(pallas_compat, "_WARNED", set())
    with caplog.at_level(logging.WARNING,
                         logger="goworld_tpu.ops.pallas"):
        for _ in range(2):   # second call: cached, no second warning
            nbr, _cnt, _fl = grid_neighbors_flags(
                _spec("fused", "pallas", 0.0),
                jnp.asarray(POS), jnp.asarray(ALIVE),
                flag_bits=jnp.asarray(FB),
            )
        got = [set(r[r < N].tolist()) for r in np.asarray(nbr)]
        for i in range(N):
            assert got[i] == (ORACLE[i] if ALIVE[i] else set()), i
    warns = [r.message for r in caplog.records
             if "interpret mode" in r.message]
    assert sorted(warns.count(m) for m in set(warns)) == [1, 1], warns
    assert any("aoi_fused_sweep" in m for m in warns)
    assert any("counting_sort_fill" in m for m in warns)


@pytest.mark.precision
@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "cellrow",
                                        "shift"])
def test_precision_q16_matrix_matches_snapped_oracle(sweep_impl):
    """precision=q16 rows of the parity matrix (ISSUE 12): every impl
    sweeps the SNAPPED lattice world, so the oracle over the snapped
    positions must hold exactly, and the packed-int16 "ranges" fast
    path must match the f32 impls bit-for-bit (deep coverage incl.
    Verlet reuse lives in tests/test_precision.py)."""
    from goworld_tpu.ops.aoi import quantize_positions

    spec = _spec(sweep_impl, "argsort", 0.0)
    import dataclasses as _dc

    spec = _dc.replace(spec, precision="q16")
    spos = np.asarray(quantize_positions(spec, jnp.asarray(POS)))
    oracle_q = neighbors_oracle(spos, ALIVE, RADIUS)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(POS), jnp.asarray(ALIVE),
        flag_bits=jnp.asarray(FB),
    )
    got = _sets(nbr)
    for i in range(N):
        want = oracle_q[i] if ALIVE[i] else set()
        assert got[i] == want, (sweep_impl, i)
    _check_flags(nbr, fl, FB)


def test_new_knob_validation_mirrors_existing_messages():
    """GridSpec.__post_init__ rejects bad values for the r5 knobs with
    the same shape as the topk_impl/sweep_impl errors: the named
    allowed set plus the repr of the offending value."""
    base = dict(radius=10.0)
    with pytest.raises(ValueError, match=r"argsort\|counting\|pallas"):
        GridSpec(**base, sort_impl="quicksort")
    with pytest.raises(ValueError, match=r"'quicksort'"):
        GridSpec(**base, sort_impl="quicksort")
    with pytest.raises(ValueError, match=r"skin must be >= 0.*-1\.5"):
        GridSpec(**base, skin=-1.5)
    with pytest.raises(ValueError, match=r"skin must be >= 0"):
        GridSpec(**base, skin=float("nan"))
    with pytest.raises(ValueError, match=r"verlet_cap must be 0.*-3"):
        GridSpec(**base, verlet_cap=-3)
    # in (0, k): _rank_candidates would ask _rank_packed for k of
    # fewer-than-k cached lanes — reject at construction, not deep in
    # the trace
    with pytest.raises(ValueError, match=r"verlet_cap must be 0.*or >= k"):
        GridSpec(**base, k=8, verlet_cap=4)
    GridSpec(**base, k=8, verlet_cap=8)  # == k is legal
    # effective cap past the 3x3 window's 9*cell_cap lanes: the
    # rebuild sweep could never fill it (cond branch shape mismatch
    # deep in the trace) — reject at construction
    with pytest.raises(ValueError, match=r"9\*cell_cap"):
        GridSpec(**base, k=32, cell_cap=3, skin=2.0)
    GridSpec(**base, k=32, cell_cap=3)  # fine while skin is off
    with pytest.raises(ValueError,
                       match=r"rebuild_every_max must be >= 0.*-7"):
        GridSpec(**base, rebuild_every_max=-7)
    # the existing knobs keep their messages (pinned here so the new
    # branches can't have reordered them away)
    with pytest.raises(ValueError,
                       match=r"table\|ranges\|cellrow\|shift\|fused"):
        GridSpec(**base, sweep_impl="bogus")
    with pytest.raises(ValueError, match=r"exact\|sort\|f32\|approx"):
        GridSpec(**base, topk_impl="bogus")
