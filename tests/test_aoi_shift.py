"""Cell-major "shift" sweep + "sort" top-k: parity with the table impl.

The shift impl (GridSpec.sweep_impl="shift") replaces the per-entity
windowed gather with 9 static slices of the padded cell table and one
unsort scatter (motivated by the r4 TPU attribution: gather+top_k was
~95% of the tick). While no cell exceeds cell_cap its results must be
bit-identical to the table impl on every path: flags, per-entity watch
radii, stats gauges, ghost query_rows, multi-block, and both exact
top-k lowerings ("exact" = lax.top_k, "sort" = full sort + slice).
Reference behavior: go-aoi XZList sweep (Space.go:244-252).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from goworld_tpu.ops.aoi import (
    GridSpec,
    grid_neighbors,
    grid_neighbors_flags,
    neighbors_oracle,
)


def _world(n, seed, extent=800.0):
    rng = np.random.default_rng(seed)
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.random(n) * extent
    pos[:, 2] = rng.random(n) * extent
    alive = rng.random(n) < 0.92
    fb = rng.integers(0, 4, n).astype(np.int32)
    return pos, alive, fb


BASE = dict(radius=25.0, extent_x=800.0, extent_z=800.0, k=32,
            cell_cap=24)


@pytest.mark.parametrize("topk_impl", ["exact", "sort", "f32"])
@pytest.mark.parametrize("row_block", [64, 100000])
def test_shift_matches_table_flags(topk_impl, row_block):
    pos, alive, fb = _world(2000, 3)
    outs = []
    for impl in ("table", "shift"):
        spec = GridSpec(**BASE, sweep_impl=impl, topk_impl=topk_impl,
                        row_block=row_block)
        nbr, cnt, fl = grid_neighbors_flags(
            spec, jnp.asarray(pos), jnp.asarray(alive),
            flag_bits=jnp.asarray(fb),
        )
        outs.append(tuple(np.asarray(x) for x in (nbr, cnt, fl)))
    for a, b in zip(*outs):
        assert np.array_equal(a, b)


def test_shift_matches_table_watch_radius_stats():
    pos, alive, fb = _world(1500, 11)
    wr = np.full(1500, np.inf, np.float32)
    wr[::17] = 0.0          # excluded from AOI entirely
    wr[::11] = 10.0         # reduced view distance
    outs = []
    for impl in ("table", "shift"):
        spec = GridSpec(**BASE, sweep_impl=impl, row_block=512)
        nbr, cnt, fl, stats = grid_neighbors_flags(
            spec, jnp.asarray(pos), jnp.asarray(alive),
            flag_bits=jnp.asarray(fb), watch_radius=jnp.asarray(wr),
            with_stats=True,
        )
        outs.append(
            tuple(np.asarray(x) for x in (nbr, cnt, fl))
            + (tuple(int(s) for s in stats),)
        )
    for a, b in zip(*outs):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_shift_matches_table_ghost_query_rows():
    pos, alive, _ = _world(900, 5)
    outs = []
    for impl in ("table", "shift"):
        spec = GridSpec(**BASE, sweep_impl=impl, row_block=256)
        nbr, cnt = grid_neighbors(
            spec, jnp.asarray(pos), jnp.asarray(alive), 600
        )
        outs.append((np.asarray(nbr), np.asarray(cnt)))
    for a, b in zip(*outs):
        assert np.array_equal(a, b)
    assert outs[0][0].shape == (600, BASE["k"])


def test_shift_matches_oracle():
    n = 500
    pos, alive, fb = _world(n, 21, extent=200.0)
    oracle = neighbors_oracle(pos, alive, 25.0)
    spec = GridSpec(radius=25.0, extent_x=200.0, extent_z=200.0,
                    k=64, cell_cap=64, row_block=128,
                    sweep_impl="shift")
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(pos), jnp.asarray(alive),
        flag_bits=jnp.asarray(fb),
    )
    nbr, fl = np.asarray(nbr), np.asarray(fl)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == (oracle[i] if alive[i] else set()), i
        for j in range(64):
            if nbr[i, j] < n:
                assert fl[i, j] == (fb[nbr[i, j]] & 3)


def test_sort_topk_matches_exact_entity_major():
    """topk_impl='sort' and 'f32' are exact (total order over packed
    keys; f32 ranks nonneg normal-float bit patterns, which order like
    the ints): the entity-major impl must return identical lists."""
    pos, alive, fb = _world(1200, 9)
    outs = []
    for tk in ("exact", "sort", "f32"):
        spec = GridSpec(**BASE, sweep_impl="table", topk_impl=tk,
                        row_block=4096)
        nbr, cnt, fl = grid_neighbors_flags(
            spec, jnp.asarray(pos), jnp.asarray(alive),
            flag_bits=jnp.asarray(fb),
        )
        outs.append(tuple(np.asarray(x) for x in (nbr, cnt, fl)))
    for other in outs[1:]:
        for a, b in zip(outs[0], other):
            assert np.array_equal(a, b)


def test_cellrow_bit_identical_to_table_even_under_overflow():
    """sweep_impl='cellrow' is a pure lowering change of the table impl
    (premerged windows + one canonical row-gather per query): its
    output must be bit-identical to 'table' in EVERY regime — including
    forced cell_cap overflow, per-entity radii, stats, and ghosts —
    unlike shift, which documents a beyond-cap divergence."""
    n = 2000
    pos, alive, fb = _world(n, 3)
    wr = np.full(n, np.inf, np.float32)
    wr[::13] = 0.0
    wr[::7] = 12.0
    base = dict(radius=25.0, extent_x=800.0, extent_z=800.0, k=32,
                cell_cap=6)          # cap 6 at this density: overflows
    outs = []
    for impl in ("table", "cellrow"):
        spec = GridSpec(**base, sweep_impl=impl, row_block=256)
        o = grid_neighbors_flags(
            spec, jnp.asarray(pos), jnp.asarray(alive),
            flag_bits=jnp.asarray(fb), watch_radius=jnp.asarray(wr),
            with_stats=True,
        )
        outs.append(
            tuple(np.asarray(x) for x in o[:3])
            + (tuple(int(s) for s in o[3]),)
        )
    for a, b in zip(*outs):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert outs[0][3][3] > 0          # the overflow regime really ran
    ghosts = []
    for impl in ("table", "cellrow"):
        spec = GridSpec(**base, sweep_impl=impl, row_block=100000)
        nbr, cnt = grid_neighbors(
            spec, jnp.asarray(pos), jnp.asarray(alive), 1500
        )
        ghosts.append((np.asarray(nbr), np.asarray(cnt)))
    for a, b in zip(*ghosts):
        assert np.array_equal(a, b)


def test_f32_topk_no_flags_matches_oracle():
    """The no-flags 'f32' path uses the 8-bit biased key layout (plain
    id word, no flag bits): its results must still match the oracle
    exactly when nothing overflows — pins the `& _ID_MASK` unpack and
    the normal-float guarantee for grid_neighbors users."""
    n = 400
    pos, alive, _ = _world(n, 13, extent=200.0)
    oracle = neighbors_oracle(pos, alive, 25.0)
    spec = GridSpec(radius=25.0, extent_x=200.0, extent_z=200.0,
                    k=64, cell_cap=64, row_block=128, topk_impl="f32")
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    nbr = np.asarray(nbr)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == (oracle[i] if alive[i] else set()), i


def test_shift_overflow_drops_watchers_with_alarm():
    """Beyond cell_cap the shift impl drops overflowed entities as
    watchers too (empty list for the tick) — documented divergence from
    the table impl, acceptable ONLY because the cell gauge alarms in
    exactly that regime. This test pins both halves of that contract."""
    m = 40
    pos = np.zeros((m, 3), np.float32)
    rng = np.random.default_rng(4)
    pos[:30, 0] = 5.0 + rng.random(30)   # 30 entities in ONE cell
    pos[:30, 2] = 5.0 + rng.random(30)
    pos[30:, 0] = pos[30:, 2] = 100.0
    alive = np.ones(m, bool)
    spec = GridSpec(radius=10.0, extent_x=120.0, extent_z=120.0,
                    k=64, cell_cap=8, row_block=m, sweep_impl="shift")
    nbr, cnt, fl, stats = grid_neighbors_flags(
        spec, jnp.asarray(pos), jnp.asarray(alive),
        flag_bits=jnp.zeros(m, jnp.int32), with_stats=True,
    )
    cnt = np.asarray(cnt)
    _, _, cell_max, over_cap = (int(s) for s in stats)
    # both crowded cells overflow: the 30-entity cluster AND the 10
    # parked at (100, 100) (occupancy 10 > cap 8)
    assert cell_max == 30 and over_cap == 2       # alarm fires
    assert (cnt[:30] > 0).sum() == 8              # the cap survivors
    assert (cnt[:30] == 0).sum() == 22            # dropped watchers
