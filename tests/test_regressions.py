"""Regression tests for review findings on the kernel core."""

import jax.numpy as jnp
import numpy as np

from goworld_tpu.core import TickInputs, WorldConfig, create_state, make_tick
from goworld_tpu.core.state import despawn, spawn
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.ops.integrate import apply_pos_inputs
from goworld_tpu.utils.ids import gen_entity_id, is_valid_entity_id


def cfg64():
    return WorldConfig(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=16, cell_cap=32, row_block=64),
    )


def test_spawned_stationary_entity_is_synced_once():
    """spawn() marks dirty -> watchers must get one sync record even though
    the new entity never moves (the syncInfoFlag analog)."""
    cfg = cfg64()
    tick = make_tick(cfg)
    st = create_state(cfg)
    st = spawn(st, 0, pos=(50.0, 0, 50.0), has_client=True)
    st, _ = tick(st, TickInputs.empty(cfg), None)
    st = spawn(st, 1, pos=(52.0, 0, 50.0))  # stationary, no client
    st, out = tick(st, TickInputs.empty(cfg), None)
    pairs = {(int(w), int(j)) for w, j in
             zip(np.asarray(out.sync_w)[: int(out.sync_n)],
                 np.asarray(out.sync_j)[: int(out.sync_n)])}
    assert (0, 1) in pairs
    # flag consumed: next tick, no further records for the stationary entity
    st, out = tick(st, TickInputs.empty(cfg), None)
    assert int(out.sync_n) == 0


def test_out_of_range_input_index_dropped_not_clamped():
    pos = jnp.zeros((4, 3))
    yaw = jnp.zeros((4,))
    idx = jnp.array([-5, 9999, 2], jnp.int32)
    vals = jnp.tile(jnp.array([[7.0, 8.0, 9.0, 1.0]]), (3, 1))
    p2, y2, touched = apply_pos_inputs(pos, yaw, idx, vals, jnp.int32(3))
    p2, touched = np.asarray(p2), np.asarray(touched)
    assert np.allclose(p2[0], 0) and np.allclose(p2[3], 0)  # not clamped onto
    assert np.allclose(p2[2], [7, 8, 9])                    # valid applied
    assert touched.tolist() == [False, False, True, False]


def test_despawn_clears_attr_dirty_and_spawn_resets_attrs():
    cfg = cfg64()
    tick = make_tick(cfg)
    st = create_state(cfg)
    st = spawn(st, 0, pos=(10.0, 0, 10.0), hot_attrs=[5.0] * cfg.attr_width)
    st = st.replace(attr_dirty=st.attr_dirty.at[0].set(jnp.uint32(1)))
    st = despawn(st, 0)
    st, out = tick(st, TickInputs.empty(cfg), None)
    assert int(out.attr_n) == 0          # no ghost attr records
    st = spawn(st, 0, pos=(10.0, 0, 10.0))  # reuse slot without hot_attrs
    assert np.allclose(np.asarray(st.hot_attrs[0]), 0.0)  # no inheritance
    assert int(st.gen[0]) == 2


def test_entity_id_validation_strict():
    assert is_valid_entity_id(gen_entity_id())
    assert not is_valid_entity_id("AAAAAAAAAAAA====")  # padded, 9-byte decode
    assert not is_valid_entity_id("short")
    assert not is_valid_entity_id("x" * 17)
    assert not is_valid_entity_id("!" * 16)


def test_mlp_speed_capped_by_magnitude():
    import jax
    from goworld_tpu.models.npc_policy import init_policy

    cfg = cfg64()
    cfg = WorldConfig(**{**cfg.__dict__, "behavior": "mlp", "npc_speed": 3.0})
    tick = make_tick(cfg)
    st = create_state(cfg)
    for s in range(4):
        st = spawn(st, s, pos=(50.0, 0, 50.0 + s), npc_moving=True)
    policy = init_policy(jax.random.PRNGKey(1))
    for _ in range(50):
        st, _ = tick(st, TickInputs.empty(cfg), policy)
    v = np.asarray(st.vel[:4])
    speed = np.sqrt(v[:, 0] ** 2 + v[:, 2] ** 2)
    assert (speed <= 3.0 + 1e-4).all()
