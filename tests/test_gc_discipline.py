"""The gc.freeze boot discipline must not leak destroyed entities.

The game logic loop freezes boot-time objects out of the cyclic GC
(net/game.py serve_forever, ini gc_freeze) so gen-2 collections stop
walking the whole world (~100 ms at a 131K shard —
docs/R5_MEASUREMENTS.md). Frozen objects can then ONLY be reclaimed by
refcounting, so a destroyed entity must not sit in a reference cycle:
destroy_entity severs the attr tree's back-references (attrs.sever_tree
— the root journal closure holds the entity, and every nested node
holds its parent)."""

import gc
import weakref

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.attrs import MapAttr, sever_tree
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec


class Npc(Entity):
    ATTRS = {"bag": "client persistent", "hp": "client hot:0"}


class Arena(Space):
    pass


def _world(n=64):
    cfg = WorldConfig(
        capacity=n,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=n),
        enter_cap=256, leave_cap=256, sync_cap=256,
        attr_sync_cap=16, input_cap=n, delta_rows_cap=n,
    )
    world = World(cfg, n_spaces=1)
    world.register_space("Arena", Arena)
    world.register_entity("Npc", Npc)
    world.create_nil_space()
    return world, world.create_space("Arena")


def test_destroyed_frozen_entity_is_refcount_reclaimable():
    world, arena = _world()
    e = world.create_entity("Npc", space=arena, pos=(5.0, 0.0, 5.0))
    # nested attr tree: parent<->child pointer cycles inside the tree
    e.attrs["bag"] = {"slots": [1, 2, 3], "gold": {"amount": 9}}
    eid = e.id

    # simulate the logic loop's boot discipline: everything alive now
    # (including e) becomes permanent — only refcounting can free it
    gc.collect()
    gc.freeze()
    try:
        ref = weakref.ref(e)
        world.destroy_entity(e)
        # tick twice: the slot-release quarantine holds the host object
        # until its leave events have decoded
        world.tick()
        world.tick()
        assert eid not in world.entities
        del e
        # NO gc.collect() here — frozen objects wouldn't get one. If
        # the cycle weren't severed, the weakref would still be alive.
        assert ref() is None, "destroyed frozen entity leaked (cycle)"
    finally:
        gc.unfreeze()


def test_sever_tree_breaks_all_back_references():
    deltas = []
    from goworld_tpu.entity.attrs import make_root
    root = make_root(deltas.append)
    root["m"] = {"a": [1, {"b": 2}]}
    m = root["m"]
    lst = m["a"]
    inner = lst[1]
    sever_tree(root)
    assert root._root_cb is None
    assert m.parent is None and lst.parent is None \
        and inner.parent is None
    # reads still work; mutations no longer journal
    assert m.to_dict() == {"a": [1, {"b": 2}]}
    n0 = len(deltas)
    m["c"] = 1
    assert len(deltas) == n0


def test_class_patched_aoi_hook_after_registration_fires():
    """Patching the hook on the CLASS after register_entity must also
    fire (the decode's per-class override cache is rebuilt every tick,
    not at registration)."""
    world, arena = _world()

    class Patched(Npc):
        pass

    world.register_entity("Patched", Patched)
    a = world.create_entity("Patched", space=arena, pos=(5.0, 0.0, 5.0))
    b = world.create_entity("Patched", space=arena, pos=(6.0, 0.0, 6.0))
    seen = []
    Patched.OnEnterAOI = lambda self, other: seen.append(
        (self.id, other.id))
    try:
        world.tick()
        world.tick()
    finally:
        del Patched.OnEnterAOI
    assert (a.id, b.id) in seen and (b.id, a.id) in seen


def test_instance_assigned_aoi_hook_still_fires():
    """The per-type has_enter_hook fast path must not skip hooks bound
    on an INSTANCE (walker.OnEnterAOI = fn — the multihost worker
    pattern)."""
    world, arena = _world()
    a = world.create_entity("Npc", space=arena, pos=(5.0, 0.0, 5.0))
    b = world.create_entity("Npc", space=arena, pos=(6.0, 0.0, 6.0))
    seen = []
    a.OnEnterAOI = lambda other: seen.append(other.id)
    world.tick()
    world.tick()
    assert b.id in seen
