"""Serve-loop residency plane (ISSUE 16): bubble/phase accounting from
perf_counter marks on the existing tick structure (transfer-guard-proven
zero added syncs), the donation-readiness buffer census on the vmapped
multi-space path, alloc-churn honesty, the ``/residency`` endpoint and
the deployment aggregator merge, the ``residency_regression``
flight-recorder trigger, the gc-callback idempotency contract, and the
<1%-of-frame overhead bound."""

import json
import os
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import Entity, Space, World
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.utils import debug_http, flightrec, metrics, residency

pytestmark = pytest.mark.residency


@pytest.fixture(autouse=True)
def _fresh_registries():
    """Metric families and the tracker registry are process-global;
    residency series must start empty per test or cross-test counts
    leak into bubble/census asserts."""
    metrics.REGISTRY.reset()
    residency.reset()
    yield
    metrics.REGISTRY.reset()
    residency.reset()


class _Mob(Entity):
    ATTRS = {"hp": "allclients hot:100"}


def _world(n_spaces=1, **kw):
    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=32, row_block=32),
        input_cap=32,
    )
    w = World(cfg, n_spaces=n_spaces, **kw)
    w.register_entity("Mob", _Mob)
    w.register_space("Arena", Space)
    w.create_nil_space()
    return w


# =======================================================================
# tracker core: bubble residual + phase lane accounting
# =======================================================================
def _mark_cycle(rt, covered_s=0.0, idle_s=0.0):
    rt.tick_begin()
    rt.mark_dispatch()
    rt.mark_fetch()
    rt.mark_visible()
    rt.mark_decode_done()
    if covered_s:
        rt.add_host(covered_s)
    if idle_s:
        rt.add_idle(idle_s)


def test_bubble_is_the_uncovered_residual():
    rt = residency.ResidencyTracker("t", sample_every=1 << 20)
    _mark_cycle(rt)  # opens the first gap (no verdict yet)
    assert rt.ticks == 0
    # an undeclared stall between dispatches IS the bubble...
    time.sleep(0.02)
    _mark_cycle(rt)
    assert rt.ticks == 1
    assert rt.last_bubble_ms >= 15.0
    # ...and the same stall declared as pacing sleep is NOT
    t0 = time.perf_counter()
    time.sleep(0.02)
    rt.add_idle(time.perf_counter() - t0)
    _mark_cycle(rt)
    assert rt.last_bubble_ms < 15.0
    # declared covered host work is not a bubble either
    t0 = time.perf_counter()
    time.sleep(0.02)
    rt.add_host(time.perf_counter() - t0)
    _mark_cycle(rt)
    assert rt.last_bubble_ms < 15.0
    snap = rt.snapshot()
    assert snap["ticks"] == 3
    assert set(snap["phases"]) == set(residency.PHASES)
    # raw count vectors ride the payload for exact merging
    assert len(snap["bubble_counts"]) == len(snap["edges_ms"]) + 1
    assert sum(snap["bubble_counts"]) == 3
    assert isinstance(snap["pass"], bool)


def test_snapshot_serve_gap_refs_are_honest():
    rt = residency.ResidencyTracker("t", sample_every=1 << 20)
    _mark_cycle(rt)
    time.sleep(0.004)
    _mark_cycle(rt)
    rt.observe_device_step(0.002)
    rt.observe_device_step(0.002)
    snap = rt.snapshot()
    # no pinned marginal: the tracker's own device-step p50 backs it
    assert snap["serve_gap_ref"] == "device_step_p50"
    assert snap["serve_gap"] > 0
    rt.set_scan_marginal_ms(2.0)
    snap = rt.snapshot()
    assert snap["serve_gap_ref"] == "scan_marginal"
    assert snap["serve_gap_ref_ms"] == 2.0
    assert snap["serve_ms_per_tick"] == snap["tick"]["p50_ms"]


def test_sample_every_validated_loudly():
    with pytest.raises(ValueError, match="residency_sample_every"):
        residency.ResidencyTracker("t", sample_every=0)
    # the World constructor propagates the knob OUTSIDE any try block:
    # a bad config fails loudly at construction, never silently off
    with pytest.raises(ValueError, match="residency_sample_every"):
        _world(residency_sample_every=-3)


def test_window_verdict_deltas():
    rt = residency.ResidencyTracker("t", sample_every=1 << 20)
    _mark_cycle(rt)
    time.sleep(0.01)
    _mark_cycle(rt)
    p99, n = rt.window_verdict()  # first call only sets the mark
    assert (p99, n) == (None, 0)
    time.sleep(0.01)
    _mark_cycle(rt)
    p99, n = rt.window_verdict()
    assert n == 1 and p99 is not None and p99 > 0
    # an empty window is honest, not a stale repeat
    assert rt.window_verdict() == (None, 0)


# =======================================================================
# instrumented tick: zero added syncs + census on the vmapped path
# =======================================================================
def test_instrumented_world_ticks_and_marks_are_transfer_free():
    import jax

    w = _world(n_spaces=1, residency_sample_every=1)
    rt = w.residency
    assert rt is not None
    sp = w.create_space("Arena")
    for i in range(4):
        sp.create_entity("Mob", pos=(40.0 + i, 0.0, 40.0))
    w.tick()  # compile outside the guard
    w.tick()
    assert rt.ticks >= 1
    # every residency operation — marks, census pointer reads, the
    # snapshot — is host-only: prove it under the strictest guard
    # (the tick itself legitimately fetches outputs; the PLANE adds
    # no transfer of its own)
    with jax.transfer_guard("disallow"):
        _mark_cycle(rt)
        rt.sample_census(w.state)
        rt.window_verdict()
        snap = rt.snapshot()
    assert snap["ticks"] >= 2


def test_census_stable_and_finds_realloc_on_vmapped_path():
    # resident=False: this test asserts the census FINDS the realloc
    # worklist a non-donating step leaves behind (the donated path's
    # 0-realloc verdict is tests/test_resident.py's job)
    w = _world(n_spaces=2, residency_sample_every=1, resident=False)
    rt = w.residency
    sp = w.create_space("Arena")
    for i in range(4):
        sp.create_entity("Mob", pos=(40.0 + i, 0.0, 40.0))
    for _ in range(9):
        w.tick()
    census = rt.snapshot()["census"]
    # sampled every tick: >= 8 pairwise samples over 9 ticks
    assert census["samples"] >= 8
    assert census["lanes"] > 0
    # the partition is exact: every fingerprinted lane is either
    # re-allocated (donation work) or aliased in place, never both
    realloc, aliased = set(census["realloc"]), set(census["aliased"])
    assert realloc.isdisjoint(aliased)
    assert realloc | aliased == set(census["changes"])
    # without donation the jitted step rewrites the carry: the census
    # must find at least one re-allocated lane (the donate_argnums
    # worklist is nonempty — the whole point of the plane)
    assert len(realloc) >= 1
    # lane names are stable pytree paths (the worklist is actionable)
    assert all(lane for lane in census["changes"])
    # alloc honesty on CPU: measured dict or an explicit absence
    alloc = rt.snapshot()["alloc"]
    assert isinstance(alloc, dict)
    assert ("bytes_in_use" in alloc) or ("unavailable" in alloc)


def test_residency_off_means_no_tracker():
    w = _world(residency=False)
    assert w.residency is None
    w.tick()
    assert "error" in residency.snapshot_all()


# =======================================================================
# gc-callback idempotency
# =======================================================================
def test_gc_callback_never_stacks_under_tracker_churn():
    import gc as _gc

    # earlier tests' worlds may have died with the shared callback
    # still installed (dead subscribers vanish from the WeakSet
    # silently; removal happens on the next unsubscribe) — flush via
    # one install/uninstall round-trip, then the contract is exact
    _gc.collect()
    flush = residency.GcPauseTracker("flush")
    flush.install()
    flush.uninstall()
    assert residency.gc_callback_count() == 0
    trackers = []
    for i in range(5):
        t = residency.GcPauseTracker(f"churn{i}")
        t.install()
        t.install()  # double-install must not double-subscribe
        trackers.append(t)
        assert residency.gc_callback_count() == 1
    for t in trackers:
        t.uninstall()
        t.uninstall()
    assert residency.gc_callback_count() == 0
    # a full tracker close detaches too (the World teardown path)
    rt = residency.ResidencyTracker("t", sample_every=1 << 20)
    rt.tick_begin()  # binds + installs on first tick
    assert residency.gc_callback_count() == 1
    rt.close()
    rt.close()
    assert residency.gc_callback_count() == 0


def test_gc_pauses_attributed_to_bound_thread_only():
    import gc as _gc

    t = residency.GcPauseTracker("gcme")
    t.bind_thread()
    t.install()
    try:
        _gc.collect()
        assert t.pauses >= 1
        seen = t.pauses
        # collections on OTHER threads never count against the tick
        import threading

        other = threading.Thread(target=_gc.collect)
        other.start()
        other.join()
        assert t.pauses == seen
    finally:
        t.uninstall()


# =======================================================================
# flight-recorder trigger (deterministic replay from frozen frames)
# =======================================================================
def test_residency_regression_trigger_fires_and_cools_down():
    clock = [0.0]
    rec = flightrec.FlightRecorder(ring=16, cooldown_secs=30.0,
                                   clock=lambda: clock[0])
    frame = {"tick": 16, "residency_bubble_p99_ms": 9.5,
             "residency_bubble_budget_ms": 4.0,
             "residency_window": 16}
    out = rec.record(dict(frame))
    assert len(out) == 1
    assert out[0]["trigger"] == "residency_regression"
    assert "9.5" in out[0]["detail"] and "4" in out[0]["detail"]
    # deterministic replay: the frozen frames carry the exact verdict
    assert out[0]["frames"][-1]["residency_bubble_p99_ms"] == 9.5
    assert out[0]["frames"][-1]["residency_window"] == 16
    # cooldown dedups, then re-arms
    clock[0] = 5.0
    assert rec.record(dict(frame, tick=32)) == []
    clock[0] = 35.0
    assert len(rec.record(dict(frame, tick=48))) == 1
    # the "inf" overflow convention is the strongest breach
    clock[0] = 99.0
    out = rec.record({"tick": 64, "residency_bubble_p99_ms": "inf",
                      "residency_bubble_budget_ms": 4.0})
    assert len(out) == 1 and out[0]["trigger"] == "residency_regression"
    # under budget: silent
    clock[0] = 199.0
    assert rec.record({"tick": 80, "residency_bubble_p99_ms": 1.0,
                       "residency_bubble_budget_ms": 4.0}) == []


# =======================================================================
# endpoint + scrape + deployment merge
# =======================================================================
def test_residency_endpoint_serves_registered_trackers():
    rt = residency.register(
        "game7", residency.ResidencyTracker("game7",
                                            sample_every=1 << 20))
    _mark_cycle(rt)
    time.sleep(0.002)
    _mark_cycle(rt)
    srv = debug_http.start(0, process_name="game7")
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/residency", timeout=5) as r:
            payload = json.loads(r.read())
        assert "game7" in payload
        snap = payload["game7"]
        for key in ("bubble", "bubble_counts", "edges_ms", "tick",
                    "phases", "census", "alloc", "gc"):
            assert key in snap
        # weakref registry: a dropped world leaves an honest error
        residency.unregister("game7")
        del rt
        import gc as _gc

        _gc.collect()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/residency", timeout=5) as r:
            assert "error" in json.loads(r.read())
    finally:
        srv.shutdown()


_SNAP_SEQ = [0]


def _snap_with(bubbles_ms, gap, budget=4.0):
    # unique tracker label per call: histogram families are
    # process-global, a reused label would merge the fixtures
    _SNAP_SEQ[0] += 1
    rt = residency.ResidencyTracker(f"mock{_SNAP_SEQ[0]}",
                                    sample_every=1 << 20)
    for b in bubbles_ms:
        rt._h_tick.observe(max(b, 0.001) * 2)
        rt._h_bubble.observe(b)
        rt.ticks += 1
    rt.set_scan_marginal_ms(1.0)
    snap = rt.snapshot()
    snap["serve_gap"] = gap
    snap["bubble_budget_ms"] = budget
    rt.close()
    return snap


def test_aggregator_merges_bubble_counts_and_worst_gap(monkeypatch):
    import obs_aggregate

    snap_fast = _snap_with([0.1] * 100, gap=1.2)
    snap_slow = _snap_with([9.0] * 50, gap=2.8)

    def fake_fetch(url, timeout=2.0):
        if url.startswith("http://g1") and url.endswith("/residency"):
            return {"game1": snap_fast}
        if url.startswith("http://g2") and url.endswith("/residency"):
            return {"game2": snap_slow}
        raise OSError("down")

    monkeypatch.setattr(obs_aggregate, "_fetch_json", fake_fetch)
    res = obs_aggregate.aggregate_residency(
        [("g1", "http://g1"), ("g2", "http://g2"),
         ("dead", "http://dead")])
    assert res["worlds"] == ["g1:game1", "g2:game2"]
    # exact vector merge: every tick from both worlds is in the mass
    assert res["bubble"]["samples"] == 150
    # the slow world's 9 ms mass dominates the merged p99
    assert res["bubble"]["p99_ms"] == "inf" or \
        res["bubble"]["p99_ms"] > 4.0
    assert res["pass"] is False
    assert res["serve_gap_worst"] == 2.8
    line = obs_aggregate.residency_line({"residency": res})
    assert "FAIL" in line and "2.8" in line
    # no contributors -> no line (status stays quiet, never "0 worlds")
    assert obs_aggregate.residency_line(
        {"residency": {"worlds": []}}) == ""


def test_scrape_residency_lines_render_verdicts(monkeypatch):
    import scrape_metrics

    snap = _snap_with([0.2] * 40, gap=1.5)
    lines = scrape_metrics.residency_lines({"game1": {"game1": snap}})
    assert len(lines) == 1
    assert "residency bubble p99" in lines[0]
    assert "serve_gap 1.5" in lines[0]
    assert "PASS" in lines[0]
    bad = _snap_with([40.0] * 40, gap=6.0)
    lines = scrape_metrics.residency_lines({"game2": {"game2": bad}})
    assert "FAIL" in lines[0]


# =======================================================================
# overhead: the plane must cost <1% of the 60 Hz frame
# =======================================================================
def test_mark_overhead_under_one_percent_of_frame():
    rt = residency.ResidencyTracker("ovh", sample_every=1 << 30)
    reps = 2000
    _mark_cycle(rt)  # open the first gap outside the timer
    t0 = time.perf_counter()
    for _ in range(reps):
        rt.tick_begin()
        rt.mark_dispatch()
        rt.mark_fetch()
        rt.mark_visible()
        rt.add_host(1e-4)
        rt.observe_device_step(1e-3)
        rt.mark_decode_done()
        rt.add_idle(1e-4)
    per_tick_us = (time.perf_counter() - t0) / reps * 1e6
    rt.close()
    budget_us = 1e6 / 60.0  # 16.7 ms frame
    assert per_tick_us < 0.01 * budget_us, (
        f"residency marks cost {per_tick_us:.1f} us/tick "
        f"(>1% of the 60 Hz frame)")
