"""Chaos: deterministic fault injection + supervised cluster recovery.

Unit tier: schedule grammar, seeded-decision determinism, wire-fault
application at the PacketConnection seam, the bounded reconnect pend
queue, and the kvdb/storage op-fault + retry wrappers.

Live tier (``chaos`` marker): a real 1-dispatcher/1-game/1-gate cluster
(OS processes via the ops CLI, the test_cli.py pattern) runs under a
seeded schedule with ≥3 wire-fault kinds plus a deterministic game kill
(``crash:game.tick@n=...``); `supervise` restarts the game from its
crash-recovery checkpoint, the census re-handshake completes (a fresh
client logs in and audits), the persistent Vault entity survives with
its exact pre-kill value, and the gate's ``/faults`` log equals the log
computed locally from (seed, spec, trial count) — the seeded-replay
guarantee. The full double-run soak lives behind ``-m slow``
(tools/chaos_soak.py).
"""

import asyncio
import json
import os
import threading
import time
import urllib.request

import pytest

from goworld_tpu import cli
from goworld_tpu.net import proto
from goworld_tpu.net.packet import Packet, PacketConnection, new_packet
from goworld_tpu.utils import faults


def _chaos_soak_mod():
    """tools/chaos_soak.py is the ONE copy of the chaos harness (game
    script, cluster ini, fault spec); the live smoke below reuses it so
    the smoke and the slow double-run soak can never drift apart."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "chaos_soak.py",
    )
    spec = importlib.util.spec_from_file_location("gw_chaos_soak", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    faults.uninstall()


def _install(spec: str, seed: int = 7, process: str = "test") -> faults.FaultPlane:
    """Install a plane directly (bypassing env)."""
    faults.plane = faults.FaultPlane(
        faults.parse_schedule(spec), seed, process=process
    )
    faults.active = True
    return faults.plane


# =======================================================================
# grammar + determinism
# =======================================================================
def test_parse_schedule_kinds():
    rules = faults.parse_schedule(
        "drop:game->dispatcher:0.05,"
        "delay:gate->dispatcher:mt=13:0.5:20ms,"
        "truncate:*->dispatcher:0.1,"
        "disconnect:game->*:0.01,"
        "dup:gate->dispatcher:1.0,"
        "kill:game1@t+10s,"
        "err:kvdb.put:0.2,"
        "err:storage.*:0.1,"
        "crash:freeze.write:1.0,"
        "crash:game.tick@n=600"
    )
    kinds = [r.kind for r in rules]
    assert kinds == ["drop", "delay", "truncate", "disconnect", "dup",
                     "kill", "err", "err", "crash", "crash"]
    assert rules[1].msgtype == 13 and rules[1].delay_s == 0.02
    assert rules[2].src == "*" and rules[2].dst == "dispatcher"
    assert rules[5].target == "game1" and rules[5].at_s == 10.0
    assert rules[7].op == "*"
    assert rules[9].at_n == 600


@pytest.mark.parametrize("bad", [
    "explode:game->dispatcher:0.5",      # unknown kind
    "drop:nodirection:0.5",              # missing ->
    "drop:game->dispatcher",             # missing probability
    "kill:game1",                        # missing @t+...s
    "err:frobnicator.put:0.5",           # unknown subsystem
    "delay:game->dispatcher:0.5:20",     # delay without ms
])
def test_parse_schedule_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_schedule(bad)


def test_seeded_decisions_are_reproducible():
    spec = "drop:gate->dispatcher:0.3,dup:gate->dispatcher:0.3"
    p1 = faults.FaultPlane(faults.parse_schedule(spec), 42)
    p2 = faults.FaultPlane(faults.parse_schedule(spec), 42)
    p3 = faults.FaultPlane(faults.parse_schedule(spec), 43)
    for _ in range(300):
        p1.wire_fault("gate->dispatcher", 13)
        p2.wire_fault("gate->dispatcher", 13)
        p3.wire_fault("gate->dispatcher", 13)
    assert p1.log_lines() == p2.log_lines()      # byte-identical replay
    assert p1.log_lines() != p3.log_lines()      # the seed is the input
    assert p1.injected_total > 0


def test_deterministic_tick_crash_rule():
    p = faults.FaultPlane(
        faults.parse_schedule("crash:game.tick@n=3"), 1)
    died = []
    p.exit_hook = lambda: died.append(True)
    p.crash("game.tick")
    p.crash("game.tick")
    assert not died
    p.crash("game.tick")
    assert died


# =======================================================================
# wire faults at the PacketConnection seam
# =======================================================================
class _StubTransport:
    def __init__(self):
        self.aborted = False

    def abort(self):
        self.aborted = True


class _StubWriter:
    def __init__(self):
        self.chunks: list[bytes] = []
        self.transport = _StubTransport()

    def write(self, data: bytes) -> None:
        self.chunks.append(bytes(data))

    def close(self):
        pass


def _conn(edge="game->dispatcher"):
    w = _StubWriter()
    return PacketConnection(None, w, edge=edge), w


def _pkt(mt=proto.MT_CALL_ENTITY_METHOD):
    p = new_packet(mt)
    p.append_var_str("payload")
    return p


def test_wire_drop_dup_truncate_disconnect():
    # p=1 rules fire on every trial: each kind observable via the writer
    _install("drop:game->dispatcher:1.0")
    c, w = _conn()
    c.send(_pkt())
    assert w.chunks == []                        # dropped

    _install("dup:game->dispatcher:1.0")
    c, w = _conn()
    c.send(_pkt())
    assert len(w.chunks) == 2 and w.chunks[0] == w.chunks[1]

    _install("truncate:game->dispatcher:1.0")
    c, w = _conn()
    c.send(_pkt())
    (data,) = w.chunks
    body = _pkt()
    import struct
    full = struct.pack("<I", len(body.buf)) + bytes(body.buf)
    assert len(data) < len(full)                 # cut short...
    (size,) = struct.unpack_from("<I", data)
    assert size == len(data) - 4                 # ...but framed

    _install("disconnect:game->dispatcher:1.0")
    c, w = _conn()
    c.send(_pkt())
    assert w.transport.aborted and c.closed

    # wrong edge: untouched
    _install("drop:gate->dispatcher:1.0")
    c, w = _conn(edge="game->dispatcher")
    c.send(_pkt())
    assert len(w.chunks) == 1

    # msgtype filter: only the named type is injected
    _install("drop:game->dispatcher:mt=9999:1.0")
    c, w = _conn()
    c.send(_pkt())
    assert len(w.chunks) == 1


def test_injected_faults_are_counted_and_logged():
    from goworld_tpu.utils import metrics

    plane = _install("drop:game->dispatcher:1.0")
    c, _w = _conn()
    for _ in range(5):
        c.send(_pkt())
    assert plane.injected_total == 5
    assert plane.log_lines() == [
        "drop:game->dispatcher:1.0 -> 0,1,2,3,4"
    ]
    snap = faults.snapshot()
    assert snap["active"] and snap["rules"][0]["trials"] == 5
    assert "faults_injected_total" in metrics.REGISTRY.expose_text()


# =======================================================================
# bounded reconnect pend queue (drop-oldest + counter)
# =======================================================================
def test_cluster_pend_queue_drop_oldest():
    from goworld_tpu.net.cluster import DispatcherConn

    conn = DispatcherConn(
        0, ("127.0.0.1", 1), lambda *a: None, None,
        pend_max_packets=4, pend_max_bytes=1 << 20,
    )
    drop0 = conn._m_pend_dropped.value  # registry counters are global
    for i in range(10):   # disconnected: everything pends
        p = new_packet(proto.MT_CALL_ENTITY_METHOD)
        p.append_u32(i)
        conn.send(p)
    assert len(conn._pending) == 4
    # drop-OLDEST: the survivors are the newest four (ids 6..9)
    kept = [Packet(raw) for raw in conn._pending]
    ids_ = [(p.read_u16(), p.read_u32())[1] for p in kept]
    assert ids_ == [6, 7, 8, 9]
    assert conn._m_pend_dropped.value == drop0 + 6

    # byte budget binds independently of the packet budget
    conn2 = DispatcherConn(
        1, ("127.0.0.1", 1), lambda *a: None, None,
        pend_max_packets=1000, pend_max_bytes=100,
    )
    for _ in range(10):
        p = new_packet(proto.MT_CALL_ENTITY_METHOD)
        p.append_bytes(b"x" * 30)
        conn2.send(p)
    assert conn2._pending_bytes <= 100
    assert conn2._m_pend_dropped.value > 0


# =======================================================================
# boot requests during a zero-game outage (the mid-restart window)
# =======================================================================
def test_boot_request_queued_during_game_outage():
    """A client connecting while NO game is live (between a crash and
    its supervised restart) must have its boot request parked and
    flushed to the next game that handshakes — not silently dropped
    (which left the client hanging forever)."""
    from goworld_tpu.net.dispatcher import DispatcherService

    svc = DispatcherService(1, "127.0.0.1", 0,
                            desired_games=1, desired_gates=0)

    class _Conn:
        edge = ""

        def __init__(self):
            self.sent = []

        def send(self, p, release=True):
            mt = int.from_bytes(bytes(p.buf[:2]), "little") & 0x7FFF
            self.sent.append(mt)

    boot = proto.pack_notify_client_connected("b" * 16, "c" * 16, 1)
    pkt = Packet(bytes(boot.buf))
    pkt.rpos = 2
    svc._h_client_connected(None, None,
                            proto.MT_NOTIFY_CLIENT_CONNECTED, pkt)
    assert len(svc._boot_pending) == 1          # parked, not dropped

    conn = _Conn()
    hs = proto.pack_set_game_id(1, False, True, False, [])
    hp = Packet(bytes(hs.buf))
    hp.rpos = 2
    svc._handle_set_game_id(conn, hp)
    assert not svc._boot_pending                # flushed on handshake
    assert proto.MT_NOTIFY_CLIENT_CONNECTED in conn.sent
    assert svc.entities["b" * 16].game_id == 1  # routed to the new game


# =======================================================================
# op faults + retry wrappers (kvdb / storage)
# =======================================================================
def test_kvdb_op_fault_exhausts_bounded_retries():
    import queue

    from goworld_tpu.kvdb import KVDB, MemoryKVDB
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    _install("err:kvdb.get:1.0")      # every attempt fails
    posted = queue.Queue()
    kv = KVDB(MemoryKVDB(), AsyncWorkers(posted.put))
    err0 = kv._m_err.value            # registry counters are global
    out = []
    kv.get("k", lambda v, e: out.append((v, e)))
    deadline = time.time() + 10
    while not out and time.time() < deadline:
        try:
            posted.get(timeout=0.1)()
        except queue.Empty:
            pass
    assert out, "kvdb get callback never fired"
    v, err = out[0]
    assert isinstance(err, faults.InjectedFaultError)   # bounded: failed
    assert kv._m_err.value == err0 + 1


def test_kvdb_recovers_when_fault_is_transient():
    import queue

    from goworld_tpu.kvdb import KVDB, MemoryKVDB
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    # fires on trial 0 only -> first attempt raises, retry succeeds
    plane = _install("err:kvdb.get:0.5")
    plane.rules[0].at_n = 1    # deterministic: exactly the first trial
    posted = queue.Queue()
    kv = KVDB(MemoryKVDB(), AsyncWorkers(posted.put))
    retry0 = kv._m_retry["get"].value  # registry counters are global
    kv.backend.put("k", "v")
    out = []
    kv.get("k", lambda v, e: out.append((v, e)))
    deadline = time.time() + 10
    while not out and time.time() < deadline:
        try:
            posted.get(timeout=0.1)()
        except queue.Empty:
            pass
    assert out == [("v", None)]
    assert kv._m_retry["get"].value == retry0 + 1


def test_storage_save_retries_through_injected_faults():
    import queue

    from goworld_tpu.storage import Storage, MemoryStorage

    plane = _install("err:storage.save:0.9")
    plane.rules[0].prob = 0.0          # arm per-trial below
    plane.rules[0].at_n = 1            # first attempt fails, then clean
    post_q = queue.Queue()
    st = Storage(MemoryStorage(), post_q.put)
    done = []
    st.save("T", "e" * 16, {"x": 1}, cb=lambda: done.append(True))
    deadline = time.time() + 15
    while not done and time.time() < deadline:
        try:
            post_q.get(timeout=0.1)()
        except queue.Empty:
            pass
    assert done, "save never completed"
    assert st.backend.read("T", "e" * 16) == {"x": 1}
    assert st._m_retry.value >= 1
    st.shutdown()


# =======================================================================
# live cluster: seeded chaos smoke (the acceptance scenario)
# =======================================================================
N_DEPOSITS = 30
CHAOS_SEED = 1234


def _scrape_faults(hport: int) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{hport}/faults", timeout=5
    ) as r:
        return json.loads(r.read())


async def _session(gport: int, actions):
    """One bot session; ``actions(bot)`` is an async callable."""
    from goworld_tpu.net.botclient import BotClient

    bot = BotClient("127.0.0.1", gport)
    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 90)
        for _ in range(200):
            if bot.player.attrs.get("status") == "online":
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("status") == "online"
        return await actions(bot)
    finally:
        recv.cancel()
        await bot.conn.close()


@pytest.mark.chaos
def test_chaos_smoke_kill_recovery_and_seeded_replay(tmp_path,
                                                     monkeypatch):
    soak = _chaos_soak_mod()
    dst, gport, hport = soak.build_server_dir(
        str(tmp_path / "chaos_game"))
    chaos_spec = soak.spec_for()
    monkeypatch.setenv("GOWORLD_FAULTS", chaos_spec)
    monkeypatch.setenv("GOWORLD_FAULTS_SEED", str(CHAOS_SEED))
    stop = threading.Event()
    sup = None
    try:
        assert cli.cmd_start(dst) == 0, _logs(dst)
        # the spawned processes inherited the schedule; respawns must
        # not (one deterministic kill, then a clean recovery)
        monkeypatch.delenv("GOWORLD_FAULTS")
        monkeypatch.delenv("GOWORLD_FAULTS_SEED")
        game_pid = cli._read_pid(dst, "game", 1)

        # -- deposit phase: RPCs through the faulted gate->dispatcher
        # edge; drops are allowed (that is the fault), but SOME deposits
        # must land and the audit attr reports the applied total
        async def deposit(bot):
            for _ in range(N_DEPOSITS):
                bot.call_server("Deposit_Client", 1)
                await asyncio.sleep(0.02)
            deadline = time.time() + 20
            while time.time() < deadline:
                a = bot.player.attrs.get("audit")
                if a is not None:
                    await asyncio.sleep(1.0)  # let stragglers apply
                    return bot.player.attrs.get("audit")
                await asyncio.sleep(0.1)
            return None

        gold = asyncio.run(asyncio.wait_for(_session(gport, deposit),
                                            120))
        t_gold = time.time()
        assert gold and 0 < gold <= 2 * N_DEPOSITS, \
            f"no deposit survived the faults (audit={gold})"

        # wait until ALL 30 RPCs have passed the gate's decision point
        # (poll the trial counter instead of sleeping a fixed margin —
        # the client->gate stream is ordered, so trials only grow to
        # exactly N_DEPOSITS), then check the deterministic fault log
        # equals the pure function of (seed, spec, trials) — which is
        # exactly what a re-run with the same seed replays
        deadline = time.time() + 30
        live = _scrape_faults(hport)
        while time.time() < deadline and \
                live["rules"][0]["trials"] < N_DEPOSITS:
            time.sleep(0.2)
            live = _scrape_faults(hport)
        assert live["rules"][0]["trials"] == N_DEPOSITS, live["rules"]
        assert live["active"] and live["seed"] == CHAOS_SEED
        expected = faults.FaultPlane(
            faults.parse_schedule(chaos_spec), CHAOS_SEED)
        for _ in range(N_DEPOSITS):
            expected.wire_fault(
                "gate->dispatcher",
                proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT)
        assert live["log"] == expected.log_lines(), \
            "live fault log diverged from the seeded replay"
        assert live["injected_total"] > 0

        # a checkpoint newer than the last applied deposit must exist
        # before the kill (1 s cadence; the kill tick is ~15 s in), so
        # the restore carries the audited vault value exactly
        ckpt = os.path.join(dst, "game1_checkpoint.dat")
        deadline = time.time() + 30
        while time.time() < deadline and (
            not os.path.exists(ckpt)
            or os.path.getmtime(ckpt) < t_gold + 0.5
        ):
            time.sleep(0.2)
        assert os.path.exists(ckpt) \
            and os.path.getmtime(ckpt) >= t_gold + 0.5, \
            "no post-deposit crash-recovery checkpoint\n" + _logs(dst)

        # -- the deterministic kill: crash:game.tick@n fires, the game
        # process dies hard (exit code 86, no freeze, no goodbye)
        deadline = time.time() + 60
        while time.time() < deadline and cli._alive(game_pid):
            time.sleep(0.2)
        assert not cli._alive(game_pid), "kill rule never fired"

        # -- supervised recovery: `supervise` notices the crash
        # signature (dead pid, pidfile present) and restarts the game
        # with -restore from the checkpoint, with backoff bookkeeping
        sup = threading.Thread(
            target=cli.cmd_supervise,
            args=(dst,), kwargs=dict(interval=0.5, stop=stop),
            daemon=True,
        )
        sup.start()
        deadline = time.time() + 180
        new_pid = None
        while time.time() < deadline:
            new_pid = cli._read_pid(dst, "game", 1)
            if new_pid != game_pid and cli._alive(new_pid):
                break
            time.sleep(0.3)
        assert new_pid != game_pid and cli._alive(new_pid), \
            "supervisor never restarted the game\n" + _logs(dst)

        # -- convergence: census re-handshake done (a FRESH client boots
        # and is routed to the restarted game) and ZERO persistent-
        # entity loss (the Vault restored with its exact audited value)
        async def audit(bot):
            bot.call_server("Audit_Client")
            deadline = time.time() + 30
            while time.time() < deadline:
                a = bot.player.attrs.get("audit")
                if a is not None:
                    return a
                await asyncio.sleep(0.1)
            return None

        seen = asyncio.run(asyncio.wait_for(_session(gport, audit), 240))
        assert seen == gold, (
            f"persistent entity lost or stale: audited {seen}, "
            f"expected {gold}\n" + _logs(dst)
        )
        # the vault also reached durable storage (explicit save path)
        vault_file = os.path.join(
            dst, "entity_storage", "Vault", "Vault00000000001.mp")
        assert os.path.exists(vault_file)
    finally:
        stop.set()
        if sup is not None:
            sup.join(timeout=60)
        cli.cmd_stop(dst)


def _logs(server_dir: str) -> str:
    out = []
    rd = os.path.join(server_dir, "run")
    if os.path.isdir(rd):
        for name in sorted(os.listdir(rd)):
            if name.endswith(".log"):
                with open(os.path.join(rd, name), errors="replace") as f:
                    out.append(f"==== {name} ====\n" + f.read()[-3000:])
    return "\n".join(out)


# =======================================================================
# full soak: double run, byte-identical fault logs (slow tier)
# =======================================================================
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_soak_same_seed_replays_identical_log(tmp_path):
    """Run tools/chaos_soak.py twice with the same seed against two
    fresh clusters and require byte-identical fault logs plus converged
    recovery in both runs."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    outs = []
    for run in (1, 2):
        out = str(tmp_path / f"soak{run}.json")
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "chaos_soak.py"),
             "--dir", str(tmp_path / f"cluster{run}"),
             "--seed", "77", "--deposits", "25", "--out", out],
            env=env, capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
        with open(out) as f:
            outs.append(json.load(f))
    assert outs[0]["converged"] and outs[1]["converged"]
    assert outs[0]["fault_log"] == outs[1]["fault_log"], \
        "same seed, different fault sequence"
    assert outs[0]["injected_total"] > 0
