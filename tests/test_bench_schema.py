"""tools/bench_schema.py — artifact schema validation, in tier-1.

Every CHECKED-IN BENCH_r*/MULTICHIP_r* artifact must validate (so a
malformed stamp can never land again), and the checker must actually
catch malformation (required keys, device-plane blocks since r8,
multichip invariants).
"""

import importlib.util
import glob
import json
import os

import pytest

pytestmark = pytest.mark.devprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "bench_schema_under_test",
    os.path.join(REPO, "tools", "bench_schema.py"))
SCHEMA = importlib.util.module_from_spec(spec)
spec.loader.exec_module(SCHEMA)


def test_every_checked_in_artifact_validates():
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))
                   + glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    assert files, "no artifacts in the repo root?"
    problems = {os.path.basename(f): SCHEMA.validate_file(f)
                for f in files}
    assert all(not errs for errs in problems.values()), problems


def test_cli_passes_on_repo(capsys):
    assert SCHEMA.main(["--dir", REPO]) == 0


def _full_rec(rno=8, **extra):
    rec = {
        "metric": "entity_ticks_per_sec_per_chip", "value": 100.0,
        "unit": "entity-ticks/s/chip", "vs_baseline": 0.0,
        "entities": 1024, "tick_ms": 5.0, "platform": "cpu",
        "attempts": [],
        "sweep_impl": "ranges", "topk_impl": "sort",
        "sort_impl": "argsort", "skin": 0.0,
        "slo": {"target_ms": 16.0, "p50_ms": 1.0, "p90_ms": 2.0,
                "p99_ms": 3.0, "pass": True, "source": "x"},
        "op_stats": {"tick_ms": {"edges": [], "counts": []}},
        "roofline_audit": {"phases": {}},
    }
    rec.update(extra)
    return rec


def _validate(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return SCHEMA.validate_file(str(p))


def test_valid_r8_record_passes(tmp_path):
    assert _validate(tmp_path, "BENCH_r08.json", _full_rec()) == []


def test_missing_kernel_stamp_caught(tmp_path):
    rec = _full_rec()
    del rec["sweep_impl"]
    errs = _validate(tmp_path, "BENCH_r08.json", rec)
    assert any("sweep_impl" in e for e in errs)


def test_missing_device_plane_blocks_caught_since_r8(tmp_path):
    rec = _full_rec()
    del rec["slo"], rec["roofline_audit"], rec["op_stats"]
    errs = _validate(tmp_path, "BENCH_r08.json", rec)
    assert any("slo" in e for e in errs)
    assert any("roofline_audit" in e for e in errs)
    assert any("op_stats" in e for e in errs)
    # the same record is a VALID r7 artifact (grandfathered)
    assert _validate(tmp_path, "BENCH_r07.json", rec) == []


def test_honest_error_blocks_accepted(tmp_path):
    rec = _full_rec(slo={"error": "telemetry scan failed"},
                    roofline_audit={"error": "no phases"},
                    op_stats={"error": "x"})
    assert _validate(tmp_path, "BENCH_r08.json", rec) == []


def test_deliberate_skip_blocks_accepted(tmp_path):
    """BENCH_DEVPROF=0 / BENCH_SLO=0 / BENCH_PHASES=0 runs stamp
    {"skipped": ...} records — a documented thinner run (e.g. a relay
    window avoiding the extra compiles) must stay schema-valid."""
    rec = _full_rec(slo={"skipped": "BENCH_SLO=0"},
                    roofline_audit={"skipped": "BENCH_DEVPROF=0"},
                    op_stats={"skipped": "BENCH_SLO=0"})
    assert _validate(tmp_path, "BENCH_r08.json", rec) == []


def test_value_zero_error_record_is_a_failed_round(tmp_path):
    """compose()'s "no stage completed" artifact (value 0.0 + error)
    is a FAILED round, not a headline held to the headline contract —
    the same definition bench_trend/roofline_audit use
    (devprof.artifact_headline)."""
    failed = {"metric": "entity_ticks_per_sec_per_chip", "value": 0.0,
              "unit": "entity-ticks/s/chip", "vs_baseline": 0.0,
              "error": "no stage completed on any backend",
              "attempts": []}
    doc = {"cmd": "x", "rc": 1, "parsed": failed, "tail": ""}
    assert _validate(tmp_path, "BENCH_r09.json", doc) == []
    # ...but an rc that claims success next to no headline is a lie
    doc_lie = dict(doc, rc=0)
    errs = _validate(tmp_path, "BENCH_r09.json", doc_lie)
    assert any("rc == 0" in e for e in errs)


def test_malformed_slo_shape_caught(tmp_path):
    rec = _full_rec(slo={"target_ms": 16.0})  # percentiles missing
    errs = _validate(tmp_path, "BENCH_r08.json", rec)
    assert any("slo" in e and "p99_ms" in e for e in errs)


def test_non_numeric_value_caught(tmp_path):
    errs = _validate(tmp_path, "BENCH_r08.json",
                     _full_rec(value="fast"))
    assert any("not a number" in e for e in errs)


def test_failed_round_requires_nonzero_rc(tmp_path):
    ok = {"cmd": "x", "rc": 1, "parsed": None, "tail": ""}
    assert _validate(tmp_path, "BENCH_r09.json", ok) == []
    lie = {"cmd": "x", "rc": 0, "parsed": None, "tail": ""}
    errs = _validate(tmp_path, "BENCH_r09.json", lie)
    assert any("rc == 0" in e for e in errs)


def test_scenario_blocks_validated(tmp_path):
    rec = _full_rec(scenarios={"hotspot": {"tick_ms": 1.0}})
    errs = _validate(tmp_path, "BENCH_r08.json", rec)
    assert any("hotspot" in e and "value" in e for e in errs)
    rec2 = _full_rec(scenarios={
        "hotspot": {"value": 1.0, "tick_ms": 1.0, "entities": 10},
        "shrink": {"error": "boom"},
    })
    assert _validate(tmp_path, "BENCH_r08.json", rec2) == []


def test_multichip_invariants(tmp_path):
    good = {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}
    assert _validate(tmp_path, "MULTICHIP_r08.json", good) == []
    bad = {"n_devices": 8, "rc": 3, "ok": True, "tail": ""}
    errs = _validate(tmp_path, "MULTICHIP_r08.json", bad)
    assert any("rc=3" in e for e in errs)
    errs = _validate(tmp_path, "MULTICHIP_r08.json",
                     {"rc": 0, "ok": False})
    assert any("n_devices" in e for e in errs)
    assert any("tail" in e for e in errs)


def _multi_rec(**extra):
    """A valid r>=10 MULTICHIP record (the measured-mesh contract)."""
    rec = {
        "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
        "tail": "multichip(8): ...",
        "headline": {
            "entity_ticks_per_sec_mesh": 159907.2,
            "per_chip_efficiency": 0.19,
            "n_entities": 65536, "platform": "cpu",
        },
        "gauges": {"halo_demand_max": 252, "migrate_demand_max": 2,
                   "migrate_dropped_total": 0},
        "cost_report": {"name": "mega_tick_scan"},
        "roofline_audit": {"phases": {"ici_halo": {"model_mb": 0.1}}},
        "phases": {"border_churn": {"tick_ms": 905.0}},
    }
    rec.update(extra)
    return rec


def test_multichip_r10_contract(tmp_path):
    assert _validate(tmp_path, "MULTICHIP_r10.json", _multi_rec()) == []
    # old dryrun-only records stay grandfathered below r10
    old = {"n_devices": 8, "rc": 0, "ok": True, "tail": ""}
    assert _validate(tmp_path, "MULTICHIP_r09.json", old) == []
    # ... but r10+ requires the measured blocks
    errs = _validate(tmp_path, "MULTICHIP_r10.json", old)
    assert any("headline" in e for e in errs)
    assert any("border_churn" in e for e in errs)
    # missing headline keys caught
    rec = _multi_rec()
    del rec["headline"]["per_chip_efficiency"]
    errs = _validate(tmp_path, "MULTICHIP_r10.json", rec)
    assert any("per_chip_efficiency" in e for e in errs)
    # honest error blocks accepted for the device-plane stamps
    rec = _multi_rec(cost_report={"error": "boom"},
                     roofline_audit={"error": "boom"})
    assert _validate(tmp_path, "MULTICHIP_r10.json", rec) == []
    # ok with no mesh number is a lie
    rec = _multi_rec()
    rec["headline"]["entity_ticks_per_sec_mesh"] = 0
    errs = _validate(tmp_path, "MULTICHIP_r10.json", rec)
    assert any("no mesh number" in e for e in errs)
    # failed rounds and skips stay exempt
    failed = {"n_devices": 8, "rc": 2, "ok": False, "tail": "died"}
    assert _validate(tmp_path, "MULTICHIP_r11.json", failed) == []
    skipped = {"n_devices": 8, "rc": 0, "ok": True, "skipped": True,
               "tail": ""}
    assert _validate(tmp_path, "MULTICHIP_r11.json", skipped) == []


def _sig_block():
    return {"sig": "churn=skinless|density=exact|events=quiet",
            "churn": "skinless", "density": "exact",
            "events": "quiet", "recommendation": {}}


def test_workload_signature_required_since_r11(tmp_path):
    # r10 and older: grandfathered without the block
    assert _validate(tmp_path, "BENCH_r10.json", _full_rec()) == []
    # r11+: the block is part of the contract
    errs = _validate(tmp_path, "BENCH_r11.json", _full_rec())
    assert any("workload_signature" in e for e in errs)
    rec = _full_rec(workload_signature=_sig_block())
    assert _validate(tmp_path, "BENCH_r11.json", rec) == []
    # honest error/skip records accepted (device-plane convention)
    for blk in ({"error": "no op_stats"}, {"skipped": "BENCH_SLO=0"}):
        rec = _full_rec(workload_signature=blk)
        assert _validate(tmp_path, "BENCH_r11.json", rec) == []
    # partial signature shapes caught
    rec = _full_rec(workload_signature={"sig": "x"})
    errs = _validate(tmp_path, "BENCH_r11.json", rec)
    assert any("workload_signature missing key" in e for e in errs)
    # MULTICHIP r11+: same rule at the document level
    mc = _multi_rec()
    errs = _validate(tmp_path, "MULTICHIP_r11.json", mc)
    assert any("workload_signature" in e for e in errs)
    mc = _multi_rec(workload_signature=_sig_block())
    assert _validate(tmp_path, "MULTICHIP_r11.json", mc) == []
    assert _validate(tmp_path, "MULTICHIP_r10.json",
                     _multi_rec()) == []


def _prec_blocks():
    return {
        "precision": {"plane": "off", "pos_scale_bits": 0,
                      "quant_step": 0.03125, "sync_delta": False,
                      "sync_keyframe_every": 16},
        "precision_ab": {"n": 131072, "off_ms": 10.0, "q16_ms": 9.0,
                         "model_off_gb_1m": 1.09,
                         "model_q16_gb_1m": 0.61},
    }


def test_precision_stamp_required_since_r12(tmp_path):
    """ISSUE 12 satellite: r>=12 headlines must stamp the resolved
    precision config + the on/off A/B next to the kernel stamps;
    honest error/skip records accepted; r11 grandfathered."""
    rec = _full_rec(workload_signature=_sig_block())
    # r11: grandfathered without the blocks
    assert _validate(tmp_path, "BENCH_r11.json", rec) == []
    # r12: both blocks required
    errs = _validate(tmp_path, "BENCH_r12.json", rec)
    assert any("precision block" in e or "precision" in e
               for e in errs)
    assert any("precision_ab" in e for e in errs)
    rec = _full_rec(workload_signature=_sig_block(), **_prec_blocks())
    assert _validate(tmp_path, "BENCH_r12.json", rec) == []
    # partial precision shapes caught
    bad = _full_rec(workload_signature=_sig_block(), **_prec_blocks())
    del bad["precision"]["pos_scale_bits"]
    errs = _validate(tmp_path, "BENCH_r12.json", bad)
    assert any("precision missing key 'pos_scale_bits'" in e
               for e in errs)
    bad = _full_rec(workload_signature=_sig_block(), **_prec_blocks())
    del bad["precision_ab"]["model_q16_gb_1m"]
    errs = _validate(tmp_path, "BENCH_r12.json", bad)
    assert any("precision_ab missing key" in e for e in errs)
    # honest error/skip records accepted (device-plane convention)
    rec = _full_rec(workload_signature=_sig_block(),
                    precision={"error": "stamp failed"},
                    precision_ab={"skipped": "BENCH_PRECISION_AB=0"})
    assert _validate(tmp_path, "BENCH_r12.json", rec) == []


def _r13_rec(**extra):
    """A valid r13 record: r12's contract + the governor block."""
    rec = _full_rec(
        workload_signature={"sig": "x", "churn": "flock_like",
                            "density": "exact", "events": "quiet",
                            "recommendation": {}},
        precision={"plane": "off", "pos_scale_bits": 0,
                   "sync_keyframe_every": 16},
        precision_ab={"off_ms": 1.0, "q16_ms": 0.9,
                      "model_off_gb_1m": 1.0, "model_q16_gb_1m": 0.6},
        governor={"schedule": ["flock", "teleport", "hotspot"],
                  "phases": [{"scenario": "flock", "chosen": "default",
                              "expected": "default",
                              "swap_latency_ticks": 8}],
                  "throughput": 1000.0,
                  "static_wall_s": {"default": 1.0}},
    )
    rec.update(extra)
    return rec


def test_governor_block_required_since_r13(tmp_path):
    rec = _r13_rec()
    assert _validate(tmp_path, "BENCH_r13.json", rec) == []
    # missing entirely -> caught at r13, grandfathered at r12
    rec2 = _r13_rec()
    del rec2["governor"]
    errs = _validate(tmp_path, "BENCH_r13.json", rec2)
    assert any("governor" in e for e in errs)
    assert _validate(tmp_path, "BENCH_r12.json", rec2) == []
    # honest skip/error records accepted (the --governor-not-requested
    # round and the stage-failed round are both valid artifacts)
    for blk in ({"skipped": "--governor not requested"},
                {"error": "governor stage never completed"}):
        rec3 = _r13_rec(governor=blk)
        assert _validate(tmp_path, "BENCH_r13.json", rec3) == []


def test_governor_block_shape_caught(tmp_path):
    # a present-but-gutted block is malformation, not an honest skip
    rec = _r13_rec(governor={"schedule": ["flock"]})
    errs = _validate(tmp_path, "BENCH_r13.json", rec)
    assert any("governor" in e and "phases" in e for e in errs)
    # malformed phase records inside an otherwise-complete block
    rec2 = _r13_rec()
    rec2["governor"]["phases"] = [{"scenario": "flock"}]
    errs = _validate(tmp_path, "BENCH_r13.json", rec2)
    assert any("governor phase" in e for e in errs)


def test_unreadable_file_reported(tmp_path):
    p = tmp_path / "BENCH_r08.json"
    p.write_text("{not json")
    errs = SCHEMA.validate_file(str(p))
    assert errs and "unreadable" in errs[0]


# =======================================================================
# r>=15: the sync-age block (ISSUE 15)
# =======================================================================
def _sync_age_block(**extra):
    hops = {h: {"samples": 100, "p50_ms": 1.0, "p90_ms": 2.0,
                "p99_ms": 3.0}
            for h in ("device_tick", "drain_decode", "encode",
                      "dispatcher", "gate_flush")}
    blk = {
        "target_ms": 16.0,
        "e2e": {"samples": 100, "p50_ms": 4.0, "p90_ms": 8.0,
                "p99_ms": 12.0},
        "hops": hops,
        "records_per_tick": 2048,
        "clients": 4,
        "pass": True,
        "stamp_overhead_pct_of_budget": 0.05,
    }
    blk.update(extra)
    return blk


def _r15_rec(**extra):
    """A valid r15 record: r13's contract + the sync_age block."""
    rec = _r13_rec(sync_age=_sync_age_block())
    rec.update(extra)
    return rec


def test_sync_age_block_required_since_r15(tmp_path):
    rec = _r15_rec()
    assert _validate(tmp_path, "BENCH_r15.json", rec) == []
    # missing entirely -> caught at r15, grandfathered at r13
    rec2 = _r15_rec()
    del rec2["sync_age"]
    errs = _validate(tmp_path, "BENCH_r15.json", rec2)
    assert any("sync_age" in e for e in errs)
    assert _validate(tmp_path, "BENCH_r13.json", rec2) == []
    # honest skip/error records accepted (the BENCH_SYNC_AGE=0 round
    # and the stage-failed round are both valid artifacts)
    for blk in ({"skipped": "BENCH_SYNC_AGE=0"},
                {"error": "sync_age stage never completed"}):
        rec3 = _r15_rec(sync_age=blk)
        assert _validate(tmp_path, "BENCH_r15.json", rec3) == []


def test_sync_age_block_shape_caught(tmp_path):
    # a present-but-gutted block is malformation, not an honest skip
    rec = _r15_rec(sync_age={"target_ms": 16.0})
    errs = _validate(tmp_path, "BENCH_r15.json", rec)
    assert any("sync_age" in e for e in errs)
    # a missing hop lane inside an otherwise-complete block
    rec2 = _r15_rec()
    del rec2["sync_age"]["hops"]["dispatcher"]
    errs = _validate(tmp_path, "BENCH_r15.json", rec2)
    assert any("dispatcher" in e for e in errs)
    # e2e percentiles must be the full p50/p90/p99 + samples shape
    rec3 = _r15_rec()
    rec3["sync_age"]["e2e"] = {"p99_ms": 3.0}
    errs = _validate(tmp_path, "BENCH_r15.json", rec3)
    assert any("e2e" in e for e in errs)


# =======================================================================
# r>=16: the serve-loop residency block (ISSUE 16)
# =======================================================================
def _residency_block(**extra):
    pt = {"samples": 90, "p50_ms": 0.5, "p90_ms": 1.0, "p99_ms": 2.0}
    blk = {
        "entities": 64,
        "ticks": 90,
        "bubble": dict(pt),
        "tick": {"samples": 90, "p50_ms": 17.0, "p90_ms": 18.0,
                 "p99_ms": 20.0},
        "bubble_budget_ms": 4.0,
        "phases": {p: dict(pt) for p in
                   ("pre_dispatch", "device_wait", "decode_fanout",
                    "host_other", "idle", "bubble")},
        "gc": {"pauses": 2, "total_ms": 1.0, "max_ms": 0.8},
        "alloc": {"unavailable": "memory_stats unavailable"},
        "census": {"samples": 5, "lanes": 19, "realloc": ["pos"],
                   "aliased": [], "opaque": [], "changes": {"pos": 5}},
        "serve_ms_per_tick": 17.0,
        "serve_gap": 1.4,
        "serve_gap_ref": "scan_marginal",
        "serve_gap_ref_ms": 12.1,
        "scan_marginal_ms": 12.1,
        "pass": True,
        "mark_overhead_us_per_tick": 8.0,
        "mark_overhead_pct_of_budget": 0.05,
    }
    blk.update(extra)
    return blk


def _r16_rec(**extra):
    """A valid r16 record: r15's contract + the residency block."""
    rec = _r15_rec(residency=_residency_block())
    rec.update(extra)
    return rec


def test_residency_block_required_since_r16(tmp_path):
    rec = _r16_rec()
    assert _validate(tmp_path, "BENCH_r16.json", rec) == []
    # missing entirely -> caught at r16, grandfathered at r15
    rec2 = _r16_rec()
    del rec2["residency"]
    errs = _validate(tmp_path, "BENCH_r16.json", rec2)
    assert any("residency" in e for e in errs)
    assert _validate(tmp_path, "BENCH_r15.json", rec2) == []
    # honest skip/error records accepted (the BENCH_RESIDENCY=0 round
    # and the stage-failed round are both valid artifacts)
    for blk in ({"skipped": "BENCH_RESIDENCY=0"},
                {"error": "residency stage never completed"}):
        rec3 = _r16_rec(residency=blk)
        assert _validate(tmp_path, "BENCH_r16.json", rec3) == []


def test_residency_block_shape_caught(tmp_path):
    # a present-but-gutted block is malformation, not an honest skip
    rec = _r16_rec(residency={"bubble": {"p99_ms": 1.0}})
    errs = _validate(tmp_path, "BENCH_r16.json", rec)
    assert any("residency" in e for e in errs)
    # bubble percentiles must be the full p50/p90/p99 + samples shape
    rec2 = _r16_rec()
    rec2["residency"]["bubble"] = {"p99_ms": 1.0}
    errs = _validate(tmp_path, "BENCH_r16.json", rec2)
    assert any("bubble" in e for e in errs)
    # the census must carry the donation worklist shape
    rec3 = _r16_rec()
    rec3["residency"]["census"] = {"samples": 5}
    errs = _validate(tmp_path, "BENCH_r16.json", rec3)
    assert any("census" in e for e in errs)
    # alloc must be a dict — measured stats or {"unavailable": ...},
    # never a bare null pretending nothing was supposed to be there
    rec4 = _r16_rec()
    rec4["residency"]["alloc"] = None
    errs = _validate(tmp_path, "BENCH_r16.json", rec4)
    assert any("alloc" in e for e in errs)


# =======================================================================
# r>=18: the hot-standby failover block (ISSUE 18)
# =======================================================================
def _audit_block(**extra):
    blk = {
        "entities": 64,
        "ledger": {"entities": 64, "crc": 1, "created": 70,
                   "destroyed": 6, "migrated_out": 0, "migrated_in": 0},
        "oracle": {"samples": 12, "entities_checked": 700,
                   "mismatches": 0},
        "violations_total": {},
        "conservation": {"ok": True, "live": 64, "in_flight": 0,
                         "created": 70, "destroyed": 6, "problems": []},
        "overhead_pct_of_budget": 0.2,
        "pass": True,
    }
    blk.update(extra)
    return blk


def _failover_block(**extra):
    blk = {
        "entities": 48,
        "ticks": 20,
        "keyframe_every": 8,
        "replication_bytes_per_tick": 5163.3,
        "client_sync_bytes_per_tick": 1214.4,
        "standby_apply_ms_per_tick": 0.9,
        "promotion_latency_ticks": 1,
        "lag_budget_ticks": 16,
        "entities_lost": 0,
        "entities_duplicated": 0,
        "frames_applied": 20,
        "frames_rejected": 0,
        "decision_log_replay_ok": True,
        "pass": True,
    }
    blk.update(extra)
    return blk


def _r18_rec(**extra):
    """A valid r18 record: r17's contract (the audit block) + the
    hot-standby failover block."""
    rec = _r16_rec(audit=_audit_block(), failover=_failover_block())
    rec.update(extra)
    return rec


def test_failover_block_required_since_r18(tmp_path):
    rec = _r18_rec()
    assert _validate(tmp_path, "BENCH_r18.json", rec) == []
    # missing entirely -> caught at r18, grandfathered at r17
    rec2 = _r18_rec()
    del rec2["failover"]
    errs = _validate(tmp_path, "BENCH_r18.json", rec2)
    assert any("failover" in e for e in errs)
    assert _validate(tmp_path, "BENCH_r17.json", rec2) == []
    # honest skip/error records accepted (the BENCH_FAILOVER=0 round
    # and the stage-failed round are both valid artifacts)
    for blk in ({"skipped": "BENCH_FAILOVER=0"},
                {"error": "failover stage never completed"}):
        rec3 = _r18_rec(failover=blk)
        assert _validate(tmp_path, "BENCH_r18.json", rec3) == []


def test_failover_block_shape_caught(tmp_path):
    # a present-but-gutted block is malformation, not an honest skip
    rec = _r18_rec(failover={"promotion_latency_ticks": 1})
    errs = _validate(tmp_path, "BENCH_r18.json", rec)
    assert any("failover missing key" in e for e in errs)
    assert any("entities_lost" in e for e in errs)
    # a non-numeric conservation count is malformation (a bool True
    # would make `if lost` lie, a string would break the trend gate)
    rec2 = _r18_rec()
    rec2["failover"]["entities_lost"] = "none"
    errs = _validate(tmp_path, "BENCH_r18.json", rec2)
    assert any("entities_lost malformed" in e for e in errs)


# =======================================================================
# r>=19: the self-healing rebalance block (ISSUE 19)
# =======================================================================
def _rebalance_block(**extra):
    blk = {
        "donor_p99_before_ms": 12.1,
        "donor_p99_after_ms": 10.4,
        "entities_moved": 24,
        "batch": 24,
        "aborts": 0,
        "donor_recovery_windows": 2,
        "entities_lost": 0,
        "entities_duplicated": 0,
        "decision_log_replay_ok": True,
        "pass": True,
    }
    blk.update(extra)
    return blk


def _r19_rec(**extra):
    """A valid r19 record: r18's contract + the rebalance block."""
    rec = _r18_rec(rebalance=_rebalance_block())
    rec.update(extra)
    return rec


def test_rebalance_block_required_since_r19(tmp_path):
    rec = _r19_rec()
    assert _validate(tmp_path, "BENCH_r19.json", rec) == []
    # missing entirely -> caught at r19, grandfathered at r18
    rec2 = _r19_rec()
    del rec2["rebalance"]
    errs = _validate(tmp_path, "BENCH_r19.json", rec2)
    assert any("rebalance" in e for e in errs)
    assert _validate(tmp_path, "BENCH_r18.json", rec2) == []
    # honest skip/error records accepted
    for blk in ({"skipped": "BENCH_REBALANCE=0"},
                {"error": "rebalance stage never completed"}):
        rec3 = _r19_rec(rebalance=blk)
        assert _validate(tmp_path, "BENCH_r19.json", rec3) == []


def test_rebalance_block_shape_caught(tmp_path):
    # a present-but-gutted block is malformation, not an honest skip
    rec = _r19_rec(rebalance={"entities_moved": 24})
    errs = _validate(tmp_path, "BENCH_r19.json", rec)
    assert any("rebalance missing key" in e for e in errs)
    assert any("entities_lost" in e for e in errs)
    # non-numeric conservation counts are malformation
    rec2 = _r19_rec()
    rec2["rebalance"]["entities_duplicated"] = "zero"
    errs = _validate(tmp_path, "BENCH_r19.json", rec2)
    assert any("entities_duplicated malformed" in e for e in errs)
    # an aborted round's recovery latency is honestly None — accepted
    rec3 = _r19_rec()
    rec3["rebalance"]["donor_recovery_windows"] = None
    rec3["rebalance"]["pass"] = False
    assert _validate(tmp_path, "BENCH_r19.json", rec3) == []
