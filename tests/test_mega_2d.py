"""2D megaspace tiling (VERDICT #9): the XZ plane tiled over a (4, 2)
device grid with 8-neighbor halo exchange — corners included via the
two-phase x-then-z ghost shipment. At 64 devices over a square world,
1D x-strips get thinner than the AOI radius; 2D tiles are the realistic
BASELINE config-4 layout."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.parallel.mesh import make_mesh

TX, TZ = 4, 2
TILE_W, TILE_D = 60.0, 60.0
RADIUS = 10.0


class Walker(Entity):
    pass


class MegaArena(Space):
    pass


def _world_2d(capacity=96):
    cfg = WorldConfig(
        capacity=capacity,
        grid=GridSpec(
            radius=RADIUS,
            extent_x=TILE_W + 2 * RADIUS,
            extent_z=TILE_D + 2 * RADIUS,
            k=32, cell_cap=64, row_block=capacity,
        ),
        npc_speed=30.0, turn_prob=0.2,
        enter_cap=8192, leave_cap=8192, sync_cap=8192,
    )
    mesh = make_mesh(TX * TZ)
    w = World(cfg, n_spaces=TX * TZ, mesh=mesh, megaspace=True,
              halo_cap=64, migrate_cap=32, mega_shape=(TX, TZ))
    w.register_space("MegaArena", MegaArena, megaspace=True)
    w.register_entity("Walker", Walker)
    w.create_nil_space()
    return w


def _oracle_check(w: World, arena):
    ents = [
        w.entities[eid] for eid in arena.members
        if w.entities[eid].slot is not None
    ]
    pos = np.asarray(w.state.pos)
    coords = {
        e.id: (float(pos[e.shard, e.slot][0]),
               float(pos[e.shard, e.slot][2]))
        for e in ents
    }
    for e in ents:
        ex, ez = coords[e.id]
        want = {
            o.id for o in ents
            if o.id != e.id
            and max(abs(coords[o.id][0] - ex), abs(coords[o.id][1] - ez))
            <= RADIUS
        }
        assert e.interested_in == want, (
            f"{e.id} tile {e.shard} at ({ex:.1f},{ez:.1f}): "
            f"{len(e.interested_in)} vs {len(want)} expected"
        )


def test_2d_corner_visibility():
    """Four entities around a 4-tile corner point — every pair crosses a
    tile boundary, the diagonal pair ONLY via the corner exchange."""
    w = _world_2d()
    arena = w.create_space("MegaArena")
    cx, cz = TILE_W, TILE_D  # the (0,0)/(1,0)/(0,1)/(1,1) corner point
    quad = [
        w.create_entity("Walker", space=arena, pos=(cx - 3, 0, cz - 3)),
        w.create_entity("Walker", space=arena, pos=(cx + 3, 0, cz - 3)),
        w.create_entity("Walker", space=arena, pos=(cx - 3, 0, cz + 3)),
        w.create_entity("Walker", space=arena, pos=(cx + 3, 0, cz + 3)),
    ]
    for _ in range(2):
        w.tick()
    tiles = {e.shard for e in quad}
    assert len(tiles) == 4, f"quad not spread over 4 tiles: {tiles}"
    ids = {e.id for e in quad}
    for e in quad:
        assert e.interested_in == ids - {e.id}, (
            f"corner entity on tile {e.shard} sees "
            f"{len(e.interested_in)}/3 of its diagonal quad"
        )
    _oracle_check(w, arena)


def test_2d_border_churn_matches_oracle():
    w = _world_2d()
    arena = w.create_space("MegaArena")
    rng = np.random.default_rng(7)
    ents = []
    spawn_tile = {}
    for _ in range(TX * TZ * 30):
        x = float(rng.uniform(0, TILE_W * TX))
        z = float(rng.uniform(0, TILE_D * TZ))
        e = w.create_entity("Walker", space=arena, pos=(x, 0, z),
                            moving=True)
        ents.append(e)
        spawn_tile[e.id] = e.shard
    for _ in range(10):
        w.tick()
        outs = w.last_outputs
        assert int(np.asarray(outs.migrate_dropped).sum()) == 0
        assert (np.asarray(outs.halo_demand) <= 64).all()
        _oracle_check(w, arena)
    # host tiles track device positions in BOTH axes
    pos = np.asarray(w.state.pos)
    for e in ents:
        x, z = float(pos[e.shard, e.slot][0]), float(pos[e.shard, e.slot][2])
        ix = max(0, min(TX - 1, int(x // TILE_W)))
        iz = max(0, min(TZ - 1, int(z // TILE_D)))
        assert e.shard == ix * TZ + iz, \
            f"{e.id}: host tile {e.shard} != ({ix},{iz}) for ({x},{z})"
    crossings = sum(1 for e in ents if e.shard != spawn_tile[e.id])
    assert crossings > 0, "no tile border was ever crossed"
    assert sum(len(o) for o in w._slot_owner) == len(ents)


def test_2d_z_crossing_keeps_identity():
    """Teleport across a Z border (the new axis): identity, attrs and
    interest survive exactly like the 1D x-crossing."""
    w = _world_2d()
    arena = w.create_space("MegaArena")
    a = w.create_entity("Walker", space=arena, pos=(30.0, 0, 57.0))
    b = w.create_entity("Walker", space=arena, pos=(30.0, 0, 55.0))
    a.attrs["hp"] = 5
    w.tick()
    assert a.shard == 0 and b.shard == 0
    assert a.interested_in == {b.id}
    a.set_position((30.0, 0, 63.0))  # z crosses into tile (0,1)
    w.tick()
    assert a.shard == 1, f"z-crossing did not hop tiles (shard={a.shard})"
    assert a.attrs["hp"] == 5
    assert a.interested_in == {b.id}, "interest lost across the z border"
    assert b.interested_in == {a.id}


def test_mega_config_validates_2d():
    from goworld_tpu.parallel.megaspace import MegaConfig

    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=10.0, extent_x=80.0, extent_z=80.0,
                      k=8, cell_cap=16, row_block=16),
    )
    with pytest.raises(ValueError, match="mesh_shape"):
        MegaConfig(cfg=cfg, n_dev=8, tile_w=60.0, mesh_shape=(3, 2),
                   tile_d=60.0)
    with pytest.raises(ValueError, match="tile_d"):
        MegaConfig(cfg=cfg, n_dev=8, tile_w=60.0, mesh_shape=(4, 2))
    with pytest.raises(ValueError, match="extent_z"):
        MegaConfig(cfg=cfg, n_dev=8, tile_w=60.0, mesh_shape=(4, 2),
                   tile_d=99.0)
    MegaConfig(cfg=cfg, n_dev=8, tile_w=60.0, mesh_shape=(4, 2),
               tile_d=60.0)  # valid
