"""Multi-space and megaspace sharding tests on the 8-device CPU mesh.

Covers the TPU replacements for the reference's distributed machinery:
all_to_all entity migration (vs the dispatcher's block-and-queue protocol,
DispatcherService.go:850-891), ring-halo cross-tile AOI (SURVEY.md#5.7),
and psum global stats."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from goworld_tpu.core import TickInputs, WorldConfig
from goworld_tpu.core.state import spawn
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.parallel import (
    MegaConfig,
    MultiTickInputs,
    create_multi_state,
    make_mesh,
    make_multi_tick,
    make_mega_tick,
)
from goworld_tpu.parallel.megaspace import create_mega_state

D = 8


def small_cfg(**kw):
    base = dict(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=32, row_block=32),
    )
    base.update(kw)
    return WorldConfig(**base)


from tests.conftest import spawn_on  # noqa: E402


class TestMultiSpace:
    def test_independent_spaces_tick(self):
        cfg = small_cfg()
        mesh = make_mesh(D)
        step = make_multi_tick(cfg, mesh, migrate_cap=4)
        st = create_multi_state(cfg, D)
        st = spawn_on(st, 0, 0, pos=(50.0, 0, 50.0))
        st = spawn_on(st, 0, 1, pos=(52.0, 0, 50.0))
        st = spawn_on(st, 3, 0, pos=(50.0, 0, 50.0))
        st, out = step(st, MultiTickInputs.empty(cfg, D), None)
        assert int(out.global_alive[0]) == 3
        assert (np.asarray(out.global_alive) == 3).all()
        # AOI is per-space: shard 0 sees a pair, shard 3 sees nobody
        assert int(out.base.enter_n[0]) == 2
        assert int(out.base.enter_n[3]) == 0

    def test_migration_moves_entity_and_reports_mapping(self):
        cfg = small_cfg()
        mesh = make_mesh(D)
        step = make_multi_tick(cfg, mesh, migrate_cap=4)
        st = create_multi_state(cfg, D)
        st = spawn_on(st, 1, 5, pos=(20.0, 0, 30.0), type_id=7,
                      has_client=True, client_gate=2,
                      hot_attrs=[9.0] * cfg.attr_width)
        inp = MultiTickInputs.empty(cfg, D)
        inp = inp.replace(
            migrate_target=inp.migrate_target.at[1, 5].set(6),
            migrate_tag=inp.migrate_tag.at[1, 5].set(12345),
        )
        st, out = step(st, inp, None)
        # departed from shard 1
        assert not bool(st.alive[1, 5])
        # arrived on shard 6 with mapping record
        assert int(out.arr_n[6]) == 1
        tag = int(np.asarray(out.arr_tag[6])[0])
        slot = int(np.asarray(out.arr_slot[6])[0])
        assert tag == 12345 and slot >= 0
        assert bool(st.alive[6, slot])
        assert int(st.type_id[6, slot]) == 7
        assert bool(st.has_client[6, slot])
        assert int(st.client_gate[6, slot]) == 2
        assert np.allclose(np.asarray(st.hot_attrs[6, slot]), 9.0)
        assert np.allclose(np.asarray(st.pos[6, slot]), [20.0, 0, 30.0])
        assert (np.asarray(out.global_alive) == 1).all()
        assert int(out.migrate_dropped.sum()) == 0
        # nothing arrived anywhere else
        for dd in range(D):
            if dd != 6:
                assert int(out.arr_n[dd]) == 0

    def test_migration_capacity_backpressure(self):
        cfg = small_cfg()
        mesh = make_mesh(D)
        step = make_multi_tick(cfg, mesh, migrate_cap=2)
        st = create_multi_state(cfg, D)
        for s in range(5):  # 5 emigrants, cap 2 -> 3 stay behind
            st = spawn_on(st, 0, s, pos=(10.0 + s, 0, 10.0))
        inp = MultiTickInputs.empty(cfg, D)
        for s in range(5):
            inp = inp.replace(
                migrate_target=inp.migrate_target.at[0, s].set(2),
                migrate_tag=inp.migrate_tag.at[0, s].set(100 + s),
            )
        st, out = step(st, inp, None)
        assert int(out.arr_n[2]) == 2
        assert int(np.asarray(out.migrate_demand)[0, 2]) == 5
        assert int(np.asarray(st.alive[0]).sum()) == 3  # surplus stayed
        assert (np.asarray(out.global_alive) == 5).all()


class TestMegaspace:
    def mega(self, **kw):
        cfg = small_cfg(
            capacity=32,
            grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                          k=8, cell_cap=32, row_block=96),
            **kw,
        )
        return MegaConfig(cfg=cfg, n_dev=D, tile_w=100.0, halo_cap=16,
                          migrate_cap=4)

    def test_cross_tile_aoi_enters(self):
        mc = self.mega()
        mesh = make_mesh(D)
        step = make_mega_tick(mc, mesh)
        st = create_mega_state(mc)
        # entity A on tile 2 at x=295 (5 from border), B on tile 3 at x=302
        st = spawn_on(st, 2, 0, pos=(295.0, 0, 50.0))
        st = spawn_on(st, 3, 0, pos=(302.0, 0, 50.0))
        st, out = step(st, MultiTickInputs.empty(mc.cfg, D), None)
        gid_a = 2 * mc.cfg.capacity + 0
        gid_b = 3 * mc.cfg.capacity + 0
        enters2 = {(int(w), int(j)) for w, j in
                   zip(np.asarray(out.base.enter_w[2])[: int(out.base.enter_n[2])],
                       np.asarray(out.base.enter_j[2])[: int(out.base.enter_n[2])])}
        enters3 = {(int(w), int(j)) for w, j in
                   zip(np.asarray(out.base.enter_w[3])[: int(out.base.enter_n[3])],
                       np.asarray(out.base.enter_j[3])[: int(out.base.enter_n[3])])}
        assert (0, gid_b) in enters2          # A sees B across the border
        assert (0, gid_a) in enters3          # B sees A across the border
        assert int(out.halo_demand[2]) == 1 and int(out.halo_demand[3]) == 1

    def test_cross_tile_sync_records(self):
        mc = self.mega()
        mesh = make_mesh(D)
        step = make_mega_tick(mc, mesh)
        st = create_mega_state(mc)
        st = spawn_on(st, 2, 0, pos=(295.0, 0, 50.0), has_client=True)
        st = spawn_on(st, 3, 1, pos=(302.0, 0, 50.0), npc_moving=True)
        inp = MultiTickInputs.empty(mc.cfg, D)
        st, out = step(st, inp, None)
        st, out = step(st, inp, None)  # mover moves -> dirty ghost
        gid_mover = 3 * mc.cfg.capacity + 1
        w = np.asarray(out.base.sync_w[2])[: int(out.base.sync_n[2])]
        j = np.asarray(out.base.sync_j[2])[: int(out.base.sync_n[2])]
        assert int(out.base.sync_n[2]) >= 1
        assert set(w.tolist()) == {0}
        assert gid_mover in set(j.tolist())
        # record position matches the mover's true state on its own shard
        row = list(j.tolist()).index(gid_mover)
        vals = np.asarray(out.base.sync_vals[2])[row]
        assert np.allclose(vals[:3], np.asarray(st.pos[3, 1]), atol=1e-5)

    def test_border_crossing_auto_migrates(self):
        mc = self.mega()
        mesh = make_mesh(D)
        step = make_mega_tick(mc, mesh)
        st = create_mega_state(mc)
        st = spawn_on(st, 4, 3, pos=(401.0, 0, 50.0), type_id=9)
        # teleport it across the border into tile 3 via client input
        inp = MultiTickInputs.empty(mc.cfg, D)
        base = inp.base
        base = base.replace(
            pos_sync_idx=base.pos_sync_idx.at[4, 0].set(3),
            pos_sync_vals=base.pos_sync_vals.at[4, 0].set(
                jnp.array([399.0, 0.0, 50.0, 0.0])),
            pos_sync_n=base.pos_sync_n.at[4].set(1),
        )
        st, out = step(st, inp.replace(base=base), None)
        assert not bool(st.alive[4, 3])
        assert int(out.arr_n[3]) == 1
        old_gid = 4 * mc.cfg.capacity + 3
        assert int(np.asarray(out.arr_tag[3])[0]) == old_gid
        new_slot = int(np.asarray(out.arr_slot[3])[0])
        assert bool(st.alive[3, new_slot])
        assert int(st.type_id[3, new_slot]) == 9
        assert np.allclose(np.asarray(st.pos[3, new_slot]),
                           [399.0, 0, 50.0])
        assert (np.asarray(out.global_alive) == 1).all()

    def test_mega_matches_oracle_at_density(self):
        """Random world over all 8 tiles: cross-check the full neighbor
        graph (via enter events on tick 1) against the NumPy oracle."""
        mc = self.mega()
        mesh = make_mesh(D)
        step = make_mega_tick(mc, mesh)
        st = create_mega_state(mc)
        rng = np.random.default_rng(0)
        gids, all_pos = [], {}
        for i in range(40):
            x = rng.uniform(0, 800.0)
            z = rng.uniform(0, 100.0)
            dev = min(int(x // 100.0), D - 1)  # spawn on the owning tile so
            slot = int(np.asarray(st.alive[dev]).argmin())  # gids are stable
            st = spawn_on(st, dev, slot, pos=(x, 0.0, z))
            gid = dev * mc.cfg.capacity + slot
            gids.append(gid)
            all_pos[gid] = (x, z)
        st, out = step(st, MultiTickInputs.empty(mc.cfg, D), None)
        got = set()
        for dev in range(D):
            en = int(out.base.enter_n[dev])
            for w, j in zip(np.asarray(out.base.enter_w[dev])[:en],
                            np.asarray(out.base.enter_j[dev])[:en]):
                got.add((dev * mc.cfg.capacity + int(w), int(j)))
        expect = set()
        for a in gids:
            for b in gids:
                if a == b:
                    continue
                dx = abs(all_pos[a][0] - all_pos[b][0])
                dz = abs(all_pos[a][1] - all_pos[b][1])
                if max(dx, dz) <= 10.0:
                    expect.add((a, b))
        assert got == expect


class TestMigrationQuarantine:
    def test_same_tick_slot_reuse_blocked(self):
        """A slot freed by emigration this tick must NOT be handed to an
        arrival in the same tick — its stale interest list still owes the
        previous occupant's leave events (insert_arrivals quarantine)."""
        cfg = small_cfg(capacity=4)  # tiny shard: slots 0-3
        mesh = make_mesh(D)
        step = make_multi_tick(cfg, mesh, migrate_cap=2)
        st = create_multi_state(cfg, D)
        # shard 1: fill slots 0,1,2 -> only slot 3 free
        for s in range(3):
            st = spawn_on(st, 1, s, pos=(10.0 + s, 0, 10.0))
        # shard 0: one entity that will migrate INTO shard 1
        st = spawn_on(st, 0, 0, pos=(5.0, 0, 5.0))
        inp = MultiTickInputs.empty(cfg, D)
        # same tick: shard1/slot1 leaves for shard 2; shard0/slot0 -> shard 1
        inp = inp.replace(
            migrate_target=inp.migrate_target.at[1, 1].set(2)
                                             .at[0, 0].set(1),
            migrate_tag=inp.migrate_tag.at[1, 1].set(11).at[0, 0].set(22),
        )
        st, out = step(st, inp, None)
        assert int(out.arr_n[1]) == 1
        slot = int(np.asarray(out.arr_slot[1])[0])
        assert slot == 3, f"arrival must use the pre-existing free slot, got {slot}"
        assert not bool(st.alive[1, 1])   # departed slot stays empty
        # next tick: the departed entity's leave events fire on shard 1
        st, out = step(st, MultiTickInputs.empty(cfg, D), None)
        leaves = {(int(w), int(j)) for w, j in
                  zip(np.asarray(out.base.leave_w[1])[: int(out.base.leave_n[1])],
                      np.asarray(out.base.leave_j[1])[: int(out.base.leave_n[1])])}
        assert (0, 1) in leaves and (2, 1) in leaves
