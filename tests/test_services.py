"""Service layer, pubsub, storage and kvdb unit tests (reference test
strategy: kvdb_test.go, service reconcile semantics, storage roundtrips)."""

import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.service import ServiceManager, hash_string
from goworld_tpu.ext.pubsub import PublishSubscribeService
from goworld_tpu.kvdb import KVDB, MemoryKVDB, next_larger_key
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.storage import FilesystemStorage, MemoryStorage, Storage
from goworld_tpu.utils.asyncwork import AsyncWorkers


def make_world():
    cfg = WorldConfig(
        capacity=64, grid=GridSpec(radius=10.0, extent_x=100.0,
                                   extent_z=100.0)
    )
    w = World(cfg, n_spaces=1)
    w.create_nil_space()
    return w


class CounterService(Entity):
    def OnInit(self):
        self.counts = {}

    def Bump(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1


class Listener(Entity):
    def OnInit(self):
        self.got = []

    def OnPublish(self, subject, *args):
        self.got.append((subject, args))


# ---------------------------------------------------------------------
# services
# ---------------------------------------------------------------------
def test_service_reconcile_creates_shards_and_routes():
    w = make_world()
    sm = ServiceManager(w, game_id=1)
    sm.register("CounterService", CounterService, shard_count=3)
    sm.start()
    w.tick()
    shards = [e for e in w.entities.values()
              if e.type_name == "CounterService"]
    assert len(shards) == 3

    # shard-by-key routing is stable
    sm.call("CounterService", "Bump", ("alpha",), shard_key="alpha")
    sm.call("CounterService", "Bump", ("alpha",), shard_key="alpha")
    sm.call("CounterService", "Bump", ("beta",), shard_key="beta")
    w.tick()
    idx_a = hash_string("alpha") % 3
    ea = w.entities[sm.entity_id_of("CounterService", idx_a)]
    assert ea.counts.get("alpha") == 2
    total = sum(e.counts.get("beta", 0) for e in shards)
    assert total == 1

    # call_all reaches every shard
    sm.call_all("CounterService", "Bump", "everyone")
    w.tick()
    assert all(e.counts.get("everyone") == 1 for e in shards)


def test_service_second_game_does_not_duplicate():
    """Two worlds sharing one kvreg map: only the first claims shards."""
    w1, w2 = make_world(), make_world()
    shared: dict[str, str] = {}

    def writer(gid):
        def w(key, val):
            shared.setdefault(key, val)
        return w

    sm1 = ServiceManager(w1, game_id=1, kv_write=writer(1),
                         kv_get=shared.get)
    sm2 = ServiceManager(w2, game_id=2, kv_write=writer(2),
                         kv_get=shared.get)
    sm1.register("CounterService", CounterService, shard_count=2)
    sm2.register("CounterService", CounterService, shard_count=2)
    sm1.check_services()
    sm2.check_services()
    n1 = sum(1 for e in w1.entities.values()
             if e.type_name == "CounterService")
    n2 = sum(1 for e in w2.entities.values()
             if e.type_name == "CounterService")
    assert n1 == 2 and n2 == 0  # first writer won everything


# ---------------------------------------------------------------------
# pubsub
# ---------------------------------------------------------------------
def test_pubsub_exact_and_wildcard():
    w = make_world()
    sm = ServiceManager(w, game_id=1)
    sm.register("PublishSubscribeService", PublishSubscribeService,
                shard_count=1)
    w.register_entity("Listener", Listener)
    sm.start()
    w.tick()
    exact = w.create_entity("Listener")
    wild = w.create_entity("Listener")
    other = w.create_entity("Listener")

    sm.call("PublishSubscribeService", "Subscribe",
            (exact.id, "chat.room1"), shard_key="chat.room1")
    sm.call("PublishSubscribeService", "Subscribe",
            (wild.id, "chat.*"), shard_key="chat.room1")
    sm.call("PublishSubscribeService", "Subscribe",
            (other.id, "mail.inbox"), shard_key="chat.room1")
    w.tick()
    sm.call("PublishSubscribeService", "Publish",
            ("chat.room1", "hi"), shard_key="chat.room1")
    w.tick()
    w.tick()
    assert exact.got == [("chat.room1", ("hi",))]
    assert wild.got == [("chat.room1", ("hi",))]
    assert other.got == []

    # unsubscribe stops delivery
    sm.call("PublishSubscribeService", "Unsubscribe",
            (exact.id, "chat.room1"), shard_key="chat.room1")
    w.tick()
    sm.call("PublishSubscribeService", "Publish",
            ("chat.room1", "again"), shard_key="chat.room1")
    w.tick()
    w.tick()
    assert len(exact.got) == 1
    assert len(wild.got) == 2


# ---------------------------------------------------------------------
# storage
# ---------------------------------------------------------------------
def test_storage_roundtrip_and_callbacks(tmp_path):
    posted = []
    st = Storage(FilesystemStorage(str(tmp_path / "es")), posted.append)
    st.save("Avatar", "A" * 16, {"name": "bob", "lv": 3})
    done = {}
    st.load("Avatar", "A" * 16, lambda d: done.update(got=d))
    st.exists("Avatar", "B" * 16, lambda b: done.update(exists=b))
    st.list_entity_ids("Avatar", lambda xs: done.update(ids=xs))
    deadline = time.time() + 5
    while len(posted) < 3 and time.time() < deadline:
        time.sleep(0.01)
    for cb in posted:  # drain the "post queue"
        cb()
    assert done["got"] == {"name": "bob", "lv": 3}
    assert done["exists"] is False
    assert done["ids"] == ["A" * 16]
    st.shutdown()


def test_world_persistence_save_load(tmp_path):
    class Hero(Entity):
        ATTRS = {"name": "client persistent", "secret": "persistent",
                 "transient": "client"}

    w = make_world()
    w.register_entity("Hero", Hero, persistent=True)
    posted = w.post_q.post
    w.storage = Storage(MemoryStorage(), posted)
    h = w.create_entity("Hero")
    h.attrs["name"] = "x"
    h.attrs["secret"] = 42
    h.attrs["transient"] = "no"
    hid = h.id
    h.destroy()  # persistent entities save on destroy
    deadline = time.time() + 5
    while w.storage.op_count < 1 and time.time() < deadline:
        time.sleep(0.01)
    loaded = {}
    w.load_entity("Hero", hid, cb=lambda e: loaded.update(e=e))
    deadline = time.time() + 5
    while "e" not in loaded and time.time() < deadline:
        w.tick()
    e = loaded["e"]
    assert e is not None and e.id == hid
    assert e.attrs["name"] == "x" and e.attrs["secret"] == 42
    # non-persistent attrs do not survive
    assert e.attrs.get("transient") is None
    w.storage.shutdown()


# ---------------------------------------------------------------------
# kvdb
# ---------------------------------------------------------------------
def test_kvdb_ops():
    posted = []
    workers = AsyncWorkers(posted.append)
    kv = KVDB(MemoryKVDB(), workers)
    out = {}
    kv.put("k1", "v1")
    kv.get("k1", lambda v, err: out.update(get=v))
    kv.get_or_put("k1", "OTHER", lambda v, err: out.update(gop_old=v))
    kv.get_or_put("k2", "v2", lambda v, err: out.update(gop_new=v))
    kv.get_range("k0", "k2", lambda items, err: out.update(rng=items))
    deadline = time.time() + 5
    while len(posted) < 4 and time.time() < deadline:
        time.sleep(0.01)
    for cb in posted:
        cb()
    assert out["get"] == "v1"
    assert out["gop_old"] == "v1"   # existing value returned, not replaced
    assert out["gop_new"] is None   # fresh write
    assert out["rng"] == [("k1", "v1")]
    assert next_larger_key("abc") == "abc\x00"


def test_restored_service_shards_are_adopted_not_duplicated():
    """Hot-reload semantics: the -restore snapshot recreates service
    entities and the kvreg (surviving on the dispatcher, or restored
    with the world's mirror) still maps each shard to its eid —
    check_services must ADOPT those entities instead of creating a
    duplicate orphan per shard per reload (reference checkServices
    re-links the registered eid, service.go:106-238)."""
    from goworld_tpu import freeze as freeze_mod

    shared_kv: dict[str, str] = {}

    def kv_write(k, v):
        shared_kv.setdefault(k, v)

    w1 = make_world()
    sm1 = ServiceManager(w1, game_id=1, kv_write=kv_write,
                         kv_get=shared_kv.get)
    sm1.register("CounterService", CounterService, shard_count=3)
    sm1.check_services()
    assert len(sm1._local_shards) == 3
    n_before = sum(1 for e in w1.entities.values()
                   if e.type_name == "CounterService")
    w1.tick()
    snap = freeze_mod.freeze_world(w1)

    # the reloaded process: fresh World + ServiceManager, SAME kvreg
    w2 = make_world()
    w2.register_entity("CounterService", CounterService)
    freeze_mod.restore_world(w2, snap)
    sm2 = ServiceManager(w2, game_id=1, kv_write=kv_write,
                         kv_get=shared_kv.get)
    sm2._services["CounterService"] = 3
    sm2.check_services()
    n_after = sum(1 for e in w2.entities.values()
                  if e.type_name == "CounterService")
    assert n_after == n_before, "reload duplicated service shards"
    # the adopted shards are the RESTORED entities (same eids)
    assert sm2._local_shards == sm1._local_shards
