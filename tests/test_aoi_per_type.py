"""Per-type AOI distance semantics (reference EntityTypeDesc.aoiDistance,
EntityManager.go:24-101 / SetUseAOI: useAOI=false or aoiDistance=0 types are
excluded from AOI; a positive aoiDistance bounds that type's view)."""

import jax.numpy as jnp
import numpy as np

from goworld_tpu.entity.manager import World, _type_aoi_radius
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.space import Space
from goworld_tpu.core.state import WorldConfig
from goworld_tpu.ops.aoi import GridSpec, grid_neighbors, neighbors_oracle


def _spec(**kw):
    base = dict(radius=25.0, extent_x=200.0, extent_z=200.0,
                k=64, cell_cap=64, row_block=64)
    base.update(kw)
    return GridSpec(**base)


def test_radius_zero_invisible_and_blind():
    rng = np.random.default_rng(0)
    n = 120
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, 200, n)
    pos[:, 2] = rng.uniform(0, 200, n)
    alive = np.ones(n, bool)
    wr = np.full(n, np.inf, np.float32)
    excluded = rng.choice(n, 30, replace=False)
    wr[excluded] = 0.0

    nbr, cnt = grid_neighbors(
        _spec(), jnp.asarray(pos), jnp.asarray(alive),
        watch_radius=jnp.asarray(wr),
    )
    nbr, cnt = np.asarray(nbr), np.asarray(cnt)

    # oracle over only the participating population
    oracle = neighbors_oracle(pos, alive & (wr > 0), 25.0)
    ex = set(excluded.tolist())
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        if i in ex:
            assert cnt[i] == 0 and not got, f"excluded row {i} watches"
        else:
            assert got == oracle[i], f"row {i}"
        assert not (got & ex), f"row {i} sees an excluded entity"


def test_per_type_distance_bounds_view():
    # watcher A radius 10, watcher B radius inf (-> spec radius 25); a
    # subject 15 away is visible to B but not A — and A stays visible to
    # everyone (distance only gates WATCHING, not visibility)
    pos = np.array(
        [[50, 0, 50], [50, 0, 50], [65, 0, 50]], np.float32
    )
    alive = np.ones(3, bool)
    wr = np.array([10.0, np.inf, np.inf], np.float32)
    nbr, cnt = grid_neighbors(
        _spec(k=8, cell_cap=8, row_block=4),
        jnp.asarray(pos), jnp.asarray(alive),
        watch_radius=jnp.asarray(wr),
    )
    nbr, cnt = np.asarray(nbr), np.asarray(cnt)
    sees = lambda i: set(nbr[i][nbr[i] < 3].tolist())
    assert sees(0) == {1}          # subject 2 is 15 > 10 away
    assert sees(1) == {0, 2}       # full spec radius
    assert sees(2) == {0, 1}       # A visible despite its small radius


def test_uniform_path_unchanged():
    rng = np.random.default_rng(1)
    n = 200
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, 200, n)
    pos[:, 2] = rng.uniform(0, 200, n)
    alive = rng.uniform(size=n) < 0.8
    spec = _spec()
    nbr_a, cnt_a = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    nbr_b, cnt_b = grid_neighbors(
        spec, jnp.asarray(pos), jnp.asarray(alive),
        watch_radius=jnp.full((n,), jnp.inf),
    )
    assert (np.asarray(nbr_a) == np.asarray(nbr_b)).all()
    assert (np.asarray(cnt_a) == np.asarray(cnt_b)).all()


def test_type_aoi_radius_mapping():
    class D:  # minimal EntityTypeDesc stand-in
        def __init__(self, use_aoi, aoi_distance):
            self.use_aoi = use_aoi
            self.aoi_distance = aoi_distance

    assert _type_aoi_radius(D(False, 0.0)) == 0.0
    assert _type_aoi_radius(D(False, 30.0)) == 0.0
    assert _type_aoi_radius(D(True, 30.0)) == 30.0
    assert _type_aoi_radius(D(True, 0.0)) == float("inf")


class _Plain(Entity):
    pass


class _ServiceLike(Entity):
    pass


class _NearSighted(Entity):
    pass


class _Arena(Space):
    pass


def _world():
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=30.0, extent_x=128.0, extent_z=128.0,
                      k=16, cell_cap=32, row_block=32),
    )
    w = World(cfg, n_spaces=1)
    w.register_space("Arena", _Arena)
    w.register_entity("Plain", _Plain)
    w.register_entity("ServiceLike", _ServiceLike, use_aoi=False)
    w.register_entity("NearSighted", _NearSighted, aoi_distance=5.0)
    w.create_nil_space()
    return w


def test_world_aoi_less_entity_never_entered():
    """VERDICT #7 done-condition: an AOI-less service entity placed in the
    middle of a crowd is never interested in anyone and no one is ever
    interested in it (reference: useAOI=false types are not in the AOI
    manager at all, Space.go:200-234)."""
    w = _world()
    arena = w.create_space("Arena")
    svc = w.create_entity("ServiceLike", space=arena, pos=(50, 0, 50))
    others = [
        w.create_entity("Plain", space=arena, pos=(50 + i, 0, 50))
        for i in range(3)
    ]
    for _ in range(3):
        w.tick()
    assert not svc.interested_in
    assert not svc.interested_by
    for o in others:
        assert svc.id not in o.interested_in
        assert svc.id not in o.interested_by
    # the plain entities do see each other (the space AOI still works)
    assert others[0].interested_in == {others[1].id, others[2].id}


def test_world_per_type_distance():
    w = _world()
    arena = w.create_space("Arena")
    near = w.create_entity("NearSighted", space=arena, pos=(50, 0, 50))
    close = w.create_entity("Plain", space=arena, pos=(53, 0, 50))
    far = w.create_entity("Plain", space=arena, pos=(70, 0, 50))
    for _ in range(3):
        w.tick()
    # near sees only the entity within its 5-unit view...
    assert near.interested_in == {close.id}
    # ...but is visible to both at the space radius (30)
    assert near.id in close.interested_in
    assert near.id in far.interested_in


def test_random_per_entity_radii_vs_oracle():
    """Fuzz the full per-type semantics at once: a population with mixed
    radii — excluded (0), short-sighted (5..radius), unbounded (inf) —
    must match a per-watcher oracle: i sees j iff both participate and
    cheb(i, j) <= min(radius_i, spec.radius)."""
    rng = np.random.default_rng(21)
    n = 500
    spec = _spec(k=128, cell_cap=128, row_block=128)
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, 200, n)
    pos[:, 2] = rng.uniform(0, 200, n)
    alive = rng.uniform(size=n) < 0.9
    wr = np.full(n, np.inf, np.float32)
    kinds = rng.integers(0, 3, n)
    wr[kinds == 0] = 0.0                          # excluded
    wr[kinds == 1] = rng.uniform(5, 25, (kinds == 1).sum())  # bounded

    nbr, cnt = grid_neighbors(
        spec, jnp.asarray(pos), jnp.asarray(alive),
        watch_radius=jnp.asarray(wr),
    )
    nbr, cnt = np.asarray(nbr), np.asarray(cnt)

    participates = alive & (wr > 0)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        if not participates[i]:
            assert got == set() and cnt[i] == 0
            continue
        reach = min(wr[i], spec.radius)
        dx = np.abs(pos[:, 0] - pos[i, 0])
        dz = np.abs(pos[:, 2] - pos[i, 2])
        want = set(np.nonzero(
            (np.maximum(dx, dz) <= reach) & participates
        )[0].tolist()) - {i}
        assert got == want, (
            f"row {i} (radius {wr[i]}): extra {got - want}, "
            f"missing {want - got}"
        )
        assert cnt[i] == len(want)
