"""Tier-1 gate for the scan-driven multichip bench path (ISSUE 10):
`bench.py --multichip` machinery on the 8 fake CPU devices the test env
arms, at small N — headline keys present, oracle-exact interest sets
after the scan (the dryrun's per-type Chebyshev oracle over the raw
stacked state), and zero host syncs across the scan body
(``jax.transfer_guard("disallow")``).
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
BENCH = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bench", BENCH)
_spec.loader.exec_module(BENCH)

from goworld_tpu.parallel.megaspace import make_mega_tick  # noqa: E402
from goworld_tpu.scenarios.spec import get_scenario  # noqa: E402

pytestmark = pytest.mark.multichip


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in list(BENCH.GRID_ENV.values()) + [
            "BENCH_HALO_CAP", "BENCH_MIGRATE_CAP", "BENCH_HALO_IMPL"]:
        monkeypatch.delenv(var, raising=False)


def _scan_states(mc, tick, st, inputs, policy, ticks: int):
    """Drive the mega tick through one jitted lax.scan (the bench's
    shape) and return (final_state, last_outputs)."""

    @jax.jit
    def run(state):
        def body(s, _):
            s2, outs = tick(s, inputs, policy)
            return s2, outs
        st2, outs = lax.scan(body, state, None, length=ticks)
        return st2, jax.tree.map(lambda x: x[-1], outs)
    return run


def test_mega_scan_oracle_exact_and_zero_sync():
    """After a scan of mega ticks, every alive entity's interest set
    matches the per-type (per-watch-radius) brute-force Chebyshev
    oracle — with the exactness preconditions (no over_k/over_cap, no
    halo overflow, no dropped migrants) asserted, the scenarios-runner
    contract. The scan itself runs under transfer_guard("disallow")."""
    spec = get_scenario("mixed_radius")  # heterogeneous watch radii
    mc, mesh, st, inputs, policy = BENCH.build_mega(512, scenario=spec)
    tick = make_mega_tick(mc, mesh)
    run = _scan_states(mc, tick, st, inputs, policy, 4)
    st_dev = jax.device_put(st)
    run(st_dev)  # trace + compile outside the guard
    with jax.transfer_guard("disallow"):
        st2, outs = run(jax.tree.map(lambda x: x, st_dev))

    # exactness preconditions (a degraded config can never "pass")
    b = outs.base
    assert int(np.asarray(b.aoi_over_k_rows).max()) == 0
    assert int(np.asarray(b.aoi_over_cap_cells).max()) == 0
    assert int(np.asarray(outs.halo_demand).max()) <= mc.halo_cap
    assert int(np.asarray(outs.migrate_dropped).sum()) == 0

    n_dev, cap = np.asarray(st2.alive).shape
    alive = np.asarray(st2.alive)
    pos = np.asarray(st2.pos)
    wr = np.asarray(st2.aoi_radius)
    nbr = np.asarray(st2.nbr)
    gsent = mc.gid_sentinel
    radius = mc.cfg.grid.radius

    gids, xy, wrs = [], [], []
    for d in range(n_dev):
        for s in range(cap):
            if alive[d, s]:
                gids.append(d * cap + s)
                xy.append((pos[d, s, 0], pos[d, s, 2]))
                wrs.append(wr[d, s])
    xy = np.asarray(xy, np.float32)
    wrs = np.asarray(wrs, np.float32)
    gids = np.asarray(gids)
    assert len(gids) >= 256

    checked = 0
    for i, g in enumerate(gids):
        if wrs[i] <= 0:
            continue
        d = np.maximum(np.abs(xy[:, 0] - xy[i, 0]),
                       np.abs(xy[:, 1] - xy[i, 1]))
        reach = min(wrs[i], radius)
        want = {int(gids[j]) for j in np.nonzero(
            (d <= reach) & (wrs > 0))[0] if gids[j] != g}
        got = {int(v) for v in nbr[g // cap, g % cap] if v != gsent}
        assert got == want, (
            f"gid {g}: {len(got)} vs {len(want)} oracle neighbors"
        )
        checked += 1
    assert checked >= 256


def test_measure_multichip_headline_keys(monkeypatch):
    """The full measure_multichip path at tiny N: headline block keys,
    comms gauges, border_churn phase, device-plane stamps — the
    MULTICHIP_r10 artifact contract, produced by the real code."""
    monkeypatch.setenv("BENCH_CHURN_SPEED", "40")
    res = BENCH.measure_multichip(1024, 2)
    hl = res["headline"]
    for k in ("entity_ticks_per_sec_mesh", "per_chip_efficiency",
              "n_entities", "n_devices", "platform", "tick_ms",
              "scale_2x", "halo_impl", "halo_cap", "migrate_cap",
              "sweep_impl", "topk_impl", "sort_impl", "skin"):
        assert k in hl, f"headline missing {k}"
    assert hl["entity_ticks_per_sec_mesh"] > 0
    assert hl["n_devices"] == len(jax.devices())
    assert hl["n_entities"] > 0
    g = res["gauges"]
    for k in ("halo_demand_max", "migrate_demand_max",
              "migrate_dropped_total", "migrated_total"):
        assert k in g, f"gauges missing {k}"
    churn = res["phases"]["border_churn"]
    assert "error" not in churn, churn
    assert churn["scenario"]
    assert churn["gauges"]["migrated_total"] > 0, (
        "border_churn phase forced no tile crossings"
    )
    # telemetry lanes incl. the mega comms set, drained once
    ost = res["op_stats"]
    for lane in ("tick_ms", "halo_demand", "migrate_demand",
                 "migrate_dropped"):
        assert lane in ost and "counts" in ost[lane]
    # device-plane stamps: real or honest error records
    assert isinstance(res["cost_report"], dict)
    assert isinstance(res["roofline_audit"], dict)
    if "error" not in res["roofline_audit"]:
        ph = res["roofline_audit"]["phases"]
        assert "ici_halo" in ph and "ici_migrate" in ph
        assert res["roofline_audit"]["mode"] == "multichip"


def test_mega_async_matches_ppermute_through_tick():
    """End-to-end: a mega scan with halo_impl=async produces the SAME
    final neighbor lists and event counts as ppermute (the halo parity
    holds through the whole tick pipeline)."""
    finals = {}
    for impl in ("ppermute", "async"):
        mc, mesh, st, inputs, policy = BENCH.build_mega(
            512, halo_impl=impl)
        tick = make_mega_tick(mc, mesh)
        run = _scan_states(mc, tick, st, inputs, policy, 3)
        st2, outs = run(st)
        finals[impl] = (np.asarray(st2.nbr), np.asarray(st2.pos),
                        np.asarray(outs.base.enter_n),
                        np.asarray(outs.base.sync_n))
    for a, b in zip(finals["ppermute"], finals["async"]):
        assert np.array_equal(a, b)


def test_mega_rejects_btree_scenario_mix():
    """A scenario mix with the btree member is refused at build time:
    the tile step's summary features carry no nearest-client offset,
    so the chase branch would silently freeze instead of chasing."""
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.ops.aoi import GridSpec
    from goworld_tpu.parallel.megaspace import MegaConfig
    from goworld_tpu.scenarios.spec import ScenarioSpec

    spec = ScenarioSpec(name="chasey",
                        mix=(("btree", 0.5), ("random_walk", 0.5)))
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=64),
        scenario=spec,
    )
    with pytest.raises(ValueError, match="btree"):
        MegaConfig(cfg=cfg, n_dev=8, tile_w=100.0)


def test_roofline_multichip_dirty_only_packing():
    """The async packed payload models FEWER ICI halo bytes than the
    5-lane ppermute path, and the dirty fraction scales the yaw lane
    (the acceptance criterion's modeled-bytes delta)."""
    from goworld_tpu.utils import devprof

    gk = dict(k=32, cell_cap=12, radius=50.0, extent_x=2000.0,
              extent_z=2000.0, sort_impl="argsort",
              sweep_impl="ranges", skin=0.0)
    base = dict(n_dev=8, halo_cap=1024, migrate_cap=256,
                mesh_shape=(4, 2))
    pp = devprof.roofline_model_bytes_multichip(
        65536, gk, {**base, "halo_impl": "ppermute"})
    asy = devprof.roofline_model_bytes_multichip(
        65536, gk, {**base, "halo_impl": "async", "dirty_frac": 1.0})
    asy_clean = devprof.roofline_model_bytes_multichip(
        65536, gk, {**base, "halo_impl": "async", "dirty_frac": 0.1})
    assert asy["ici_halo"] < pp["ici_halo"]
    assert asy_clean["ici_halo"] < asy["ici_halo"]
    assert pp["ici_migrate"] == asy["ici_migrate"]
    # the audit stamps the by-impl delta
    audit = devprof.roofline_audit_multichip(
        1.0, None, 524288, gk, {**base, "halo_impl": "async"})
    d = audit["ici_halo_mb_by_impl"]
    assert d["async"] < d["ppermute"]
    assert audit["mode"] == "multichip"
    assert "ici_halo" in audit["phases"]
