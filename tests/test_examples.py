"""Host the example games in-process and drive their logic.

The reference exercises examples only via full-cluster CI; here each
example's entity classes run against a local World (the single-process
path), which keeps the examples honest as API surface tests."""

import importlib
import os
import sys

import pytest

from goworld_tpu import api
from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import GameClient, World
from goworld_tpu.entity.service import ServiceManager
from goworld_tpu.ops.aoi import GridSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_example(name: str):
    """Import an example server module fresh, capturing its registrations."""
    api._reset_for_tests()
    path = os.path.join(REPO, "examples", name, "server.py")
    spec = importlib.util.spec_from_file_location(f"example_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _make_world(n_spaces=2, radius=20.0):
    return World(
        WorldConfig(
            capacity=256,
            grid=GridSpec(radius=radius, extent_x=100.0, extent_z=100.0),
            input_cap=128,
        ),
        n_spaces=n_spaces,
    )


@pytest.fixture()
def ex_world():
    """World + local ServiceManager wired for whichever example loads."""

    def build(name, **kw):
        mod = _load_example(name)
        w = _make_world(**kw)
        svc = ServiceManager(w)
        api._apply_registrations(w, svc=svc)
        w.create_nil_space()
        svc.start()
        w.tick()
        return mod, w, svc

    yield build
    api._reset_for_tests()


def test_test_game_flow(ex_world):
    _, w, svc = ex_world("test_game")
    # services exist (3+3+1+3 shards, all local)
    names = {e.type_name for e in w.entities.values()}
    assert {"OnlineService", "SpaceService", "MailService",
            "Pubsub"} <= names

    # login: Account -> Avatar -> SpaceService assigns a MySpace
    acct = w.create_entity("Account",
                           client=GameClient(1, "c" * 16, w))
    acct.Login_Client("alice")
    for _ in range(4):
        w.tick()
    avatars = [e for e in w.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert len(avatars) == 1
    av = avatars[0]
    assert av.client is not None
    assert av.attrs.get("name") == "alice"
    spaces = [s for s in w.spaces.values()
              if s.type_name == "MySpace"]
    assert len(spaces) == 1, "SpaceService did not create MySpace"
    assert av.space is spaces[0]
    # the space auto-summoned monsters
    monsters = [e for e in w.entities.values()
                if e.type_name == "Monster" and not e.destroyed]
    assert len(monsters) == 4

    # mail + pubsub routing
    av.SendMail_Client("bob", "hi bob")
    av.Subscribe_Client("news.*")
    av.Publish_Client("news.tpu", "v5e")
    for _ in range(3):
        w.tick()
    mail = [e for e in w.entities.values()
            if e.type_name == "MailService"][0]
    assert mail.mails.get("bob") == [["alice", "hi bob"]]
    # pubsub delivered the publish as a client RPC (OnPublish on avatar)
    rpcs = [m for _, _, m in w.client_messages if m.get("type") == "rpc"]
    assert any(m["method"] == "OnPublish" for m in rpcs), rpcs

    # second login with same name reuses the avatar id mapping (no kvdb
    # here -> new avatar, but flow must not crash)
    acct2 = w.create_entity("Account",
                            client=GameClient(1, "d" * 16, w))
    acct2.Login_Client("carol")
    for _ in range(3):
        w.tick()


def test_unity_demo_combat(ex_world):
    mod, w, _svc = ex_world("unity_demo", n_spaces=1, radius=40.0)
    sp = w.create_space("MySpace")
    w._demo_space = sp
    w.tick()
    monsters = [e for e in w.entities.values()
                if e.type_name == "Monster" and not e.destroyed]
    assert len(monsters) == 3

    player = w.create_entity("Player",
                             client=GameClient(1, "p" * 16, w))
    player.attrs["name"] = "hero"
    player.OnClientConnected()
    for _ in range(3):
        w.tick()
    assert player.space is sp
    # stand next to a monster (spawn positions are random; the corner
    # cases can exceed the AOI radius) — the player must then see it
    player.set_position(monsters[0].position)
    for _ in range(2):
        w.tick()
    assert any(w.entities[e].type_name == "Monster"
               for e in player.interested_in)

    target = next(e for e in player.interested_in
                  if w.entities[e].type_name == "Monster")
    for _ in range(20):
        player.Shoot_Client(target)
        w.tick()
    m = w.entities.get(target)
    assert m is None or m.attrs.get("hp", 100) == 0 or m.destroyed


def test_chatroom_filter_props(ex_world):
    _, w, _svc = ex_world("chatroom_demo", n_spaces=1)
    acct = w.create_entity("Account",
                           client=GameClient(1, "e" * 16, w))
    acct.Login_Client("dora")
    for _ in range(2):
        w.tick()
    av = [e for e in w.entities.values()
          if e.type_name == "ChatAvatar" and not e.destroyed][0]
    # joining room 1 sent a filter_prop message for the gate index
    props = [m for _, _, m in w.client_messages
             if m.get("type") == "filter_prop"]
    assert props and props[-1]["key"] == "chatroom" \
        and props[-1]["val"] == "1"
    av.EnterRoom_Client(7)
    props = [m for _, _, m in w.client_messages
             if m.get("type") == "filter_prop"]
    assert props[-1]["val"] == "7"


def test_megaspace_demo_from_its_own_ini():
    """The megaspace demo boots through the CONFIG path (megaspace=true,
    4x2 tiles, btree NPCs) and runs its deployment-ready setup: 200
    monsters spread over the mesh, an avatar joins via the boot flow."""
    from goworld_tpu import config as config_mod
    from goworld_tpu.api import _apply_registrations, _build_world

    api._reset_for_tests()
    try:
        mod = _load_example("megaspace_demo")
        cfg = config_mod.load(os.path.join(
            REPO, "examples", "megaspace_demo", "goworld_tpu.ini"
        ))
        gc = cfg.games[1]
        assert gc.megaspace and gc.mega_shape == "4x2"
        w = _build_world(gc, 1)
        _apply_registrations(w)
        w.create_nil_space()
        # stand in for run()'s runtime so gw.world()/gw.create_entity
        # work inside the example's deployment-ready hook
        api._rt = api._Runtime(w, None, None, None, None)
        for cb in api._ready_callbacks:
            cb()
        for _ in range(3):
            w.tick()
        monsters = [e for e in w.entities.values()
                    if e.type_name == "Monster" and not e.destroyed]
        assert len(monsters) == 200
        assert w.mega is not None and w.mega.shape == (4, 2)
        # the tick ran the behavior tree + halo + migration machinery
        assert int(w.last_outputs.global_alive[0]) >= 200
    finally:
        api._reset_for_tests()
