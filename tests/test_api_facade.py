"""The new function-level facade surface (docs/API_MAP.md) in-process:
accessors, service-call variants, and the runtime guard."""

import numpy as np
import pytest

from goworld_tpu import api
from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import World
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.service import ServiceManager
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec


class Counter(Entity):
    calls: list = []

    def Bump(self, tag):
        Counter.calls.append((self.id, tag))


class Arena(Space):
    pass


@pytest.fixture()
def rt():
    api._reset_for_tests()
    w = World(WorldConfig(
        capacity=64,
        grid=GridSpec(radius=20.0, extent_x=100.0, extent_z=100.0),
        input_cap=16,
    ))
    svc = ServiceManager(w)
    w.service_mgr = svc
    w.register_entity("Counter", Counter)
    w.register_space("Arena", Arena)
    svc.register("CounterSvc", Counter, shard_count=3)
    w.create_nil_space()
    api._rt = api._Runtime(w, None, None, None, None)
    svc.start()
    w.tick()
    Counter.calls.clear()
    yield w, svc
    api._reset_for_tests()


def test_accessors(rt):
    w, svc = rt
    sp = api.create_space("Arena")
    e = api.create_entity("Counter", space=sp, pos=(5.0, 0.0, 5.0))
    assert api.get_entity(e.id) is e
    assert api.get_entity(sp.id) is None          # spaces are not entities
    assert api.get_space(sp.id) is sp
    assert api.get_game_id() == w.game_id
    assert api.get_nil_space() is w.nil_space
    assert e.id in api.entities()
    # single-controller, no cluster: the view is just this game
    assert api.get_online_games() == {w.game_id}


def test_call_service_variants(rt):
    w, svc = rt
    w.tick()
    api.call_service("CounterSvc", "Bump", "any")
    api.call_service("CounterSvc", "Bump", "k", shard_key="alpha")
    api.call_service("CounterSvc", "Bump", "idx", shard_index=2)
    api.call_service("CounterSvc", "Bump", "all", all_shards=True)
    w.tick()
    tags = [t for _, t in Counter.calls]
    assert tags.count("any") == 1
    assert tags.count("k") == 1
    assert tags.count("idx") == 1
    assert tags.count("all") == 3                 # every shard


def test_requires_run():
    api._reset_for_tests()
    with pytest.raises(RuntimeError, match="run"):
        api.get_game_id()
