"""Persistence parity (VERDICT #4): a real networked backend (redis wire
protocol against an in-process server, mirroring the reference's CI that
provisions real Redis — .github/workflows/test.yml), periodic per-entity
save_interval saves (Entity.go:164-177), and the ext/db async wrappers
(ext/db/gwredis.go, gwmongo.go:31-355)."""

import time

import pytest

from goworld_tpu.ext.db.miniredis import MiniRedis
from goworld_tpu.ext.db.resp import RespClient
from goworld_tpu.kvdb import RedisKVDB
from goworld_tpu.storage import RedisStorage, Storage


@pytest.fixture()
def server():
    with MiniRedis() as srv:
        yield srv


def test_resp_client_roundtrip(server):
    c = RespClient.from_addr(server.addr)
    assert c.ping()
    c.set("a", "1")
    assert c.get("a") == b"1"
    assert c.get("missing") is None
    assert c.exists("a") and not c.exists("b")
    assert c.setnx("a", "2") is False
    assert c.get("a") == b"1"
    assert c.delete("a") == 1
    assert c.get("a") is None
    c.set("k:1", "x")
    c.set("k:2", "y")
    c.set("other", "z")
    assert sorted(c.scan_keys("k:*")) == [b"k:1", b"k:2"]
    # binary-safe values (msgpack blobs contain \r\n freely)
    blob = bytes(range(256)) * 3
    c.set("bin", blob)
    assert c.get("bin") == blob
    c.close()


def test_resp_client_reconnects(server):
    c = RespClient.from_addr(server.addr)
    c.set("x", "1")
    # sever the connection under the client; next command must recover
    c._sock.close()
    assert c.get("x") == b"1"
    c.close()


def test_redis_storage_backend(server):
    b = RedisStorage(server.addr)
    assert b.read("Avatar", "e1") is None
    assert not b.exists("Avatar", "e1")
    data = {"name": "hero", "hp": 42, "bag": {"gold": 7}}
    b.write("Avatar", "e1", data)
    assert b.read("Avatar", "e1") == data
    assert b.exists("Avatar", "e1")
    b.write("Avatar", "e2", {"name": "alt"})
    b.write("Account", "a1", {"pw": "x"})
    assert b.list_entity_ids("Avatar") == ["e1", "e2"]
    assert b.list_entity_ids("Account") == ["a1"]
    b.close()


def test_redis_kvdb_backend(server):
    b = RedisKVDB(server.addr)
    assert b.get("k") is None
    b.put("k", "v")
    assert b.get("k") == "v"
    for k, v in [("a1", "1"), ("a2", "2"), ("a3", "3"), ("b1", "4")]:
        b.put(k, v)
    assert b.get_range("a1", "a3") == [("a1", "1"), ("a2", "2")]
    assert b.get_range("a", "b") == [
        ("a1", "1"), ("a2", "2"), ("a3", "3")
    ]
    b.close()


def test_async_storage_over_redis(server):
    posted = []
    st = Storage(RedisStorage(server.addr), posted.append)
    results = []
    st.save("Avatar", "e9", {"hp": 1}, cb=lambda: results.append("saved"))
    st.load("Avatar", "e9", cb=lambda d: results.append(d))
    deadline = time.monotonic() + 10
    while len(posted) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    for cb in posted:
        cb()
    assert results == ["saved", {"hp": 1}]
    st.shutdown()


def test_redis_cluster_kvdb_backend():
    """Sharded kvdb over 3 nodes (reference kvdbrediscluster): keys
    distribute by CRC16 slot, ranges merge across every node."""
    from goworld_tpu.kvdb import RedisClusterKVDB, open_kvdb_backend

    with MiniRedis() as n1, MiniRedis() as n2, MiniRedis() as n3:
        b = open_kvdb_backend(
            "redis_cluster", f"{n1.addr},{n2.addr},{n3.addr}"
        )
        assert isinstance(b, RedisClusterKVDB)
        kv = {f"acct{i:03d}": str(i) for i in range(40)}
        for k, v in kv.items():
            b.put(k, v)
        for k, v in kv.items():
            assert b.get(k) == v
        assert b.get("missing") is None
        # keys actually sharded: more than one node holds data
        occupied = sum(
            1 for srv in (n1, n2, n3)
            if any(srv.dbs.get(0, {}))
        )
        assert occupied >= 2, "all keys landed on one node"
        # cross-node ordered range
        got = b.get_range("acct010", "acct015")
        assert got == [(f"acct{i:03d}", str(i)) for i in range(10, 15)]
        b.close()


# =======================================================================
# periodic save_interval (reference Entity.go:164-177: a crashed game
# must lose at most save_interval worth of mutations, not everything
# since the last destroy)
# =======================================================================
class _RecordingStorage:
    def __init__(self):
        self.saves = []

    def save(self, type_name, eid, data, cb=None):
        self.saves.append((type_name, eid, data))
        if cb is not None:
            cb()


def _persist_world():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.ops.aoi import GridSpec

    class Hero(Entity):
        ATTRS = {"name": "persistent", "hp": "persistent client"}

    class Lobby(Space):
        pass

    clock = {"t": 0.0}
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=10.0, extent_x=64.0, extent_z=64.0,
                      k=8, cell_cap=8, row_block=16),
    )
    w = World(cfg, n_spaces=1, clock=lambda: clock["t"])
    w.save_interval = 60.0
    w.register_space("Lobby", Lobby)
    w.register_entity("Hero", Hero, persistent=True)
    w.create_nil_space()
    w.storage = _RecordingStorage()
    return w, clock


def test_save_interval_periodic_save():
    w, clock = _persist_world()
    lobby = w.create_space("Lobby")
    h = w.create_entity("Hero", space=lobby, pos=(5, 0, 5),
                        attrs={"name": "conan", "hp": 100})
    assert not w.storage.saves
    clock["t"] = 61.0
    w.tick()
    assert w.storage.saves == [("Hero", h.id, {"name": "conan",
                                               "hp": 100})]
    # mutate, advance another interval: the NEW value lands (no destroy
    # was ever needed — the dead-knob bug this guards against)
    h.attrs["hp"] = 55
    clock["t"] = 121.5
    w.tick()
    assert w.storage.saves[-1] == ("Hero", h.id, {"name": "conan",
                                                  "hp": 55})
    assert len(w.storage.saves) == 2


def test_save_timer_cancelled_on_destroy():
    w, clock = _persist_world()
    lobby = w.create_space("Lobby")
    h = w.create_entity("Hero", space=lobby, pos=(5, 0, 5),
                        attrs={"name": "x", "hp": 1})
    w.destroy_entity(h)  # saves once via the destroy path
    n = len(w.storage.saves)
    clock["t"] = 500.0
    w.tick()
    assert len(w.storage.saves) == n, "save timer survived destroy"
    assert h.id not in w._save_timers


def test_save_timer_not_in_migrate_dump():
    """The save timer must be a raw timer: never serialized with the
    entity's own timers (reference addRawTimer vs AddTimer)."""
    w, clock = _persist_world()
    lobby = w.create_space("Lobby")
    h = w.create_entity("Hero", space=lobby, pos=(5, 0, 5),
                        attrs={"name": "x", "hp": 1})
    assert h.id in w._save_timers
    assert w._save_timers[h.id] not in h.timer_ids
    assert w.timers.dump(list(h.timer_ids)) == []


def test_save_interval_zero_disables():
    w, clock = _persist_world()
    w.save_interval = 0.0
    lobby = w.create_space("Lobby")
    h = w.create_entity("Hero", space=lobby, pos=(5, 0, 5),
                        attrs={"name": "x", "hp": 1})
    assert h.id not in w._save_timers


# =======================================================================
# ext/db async wrappers
# =======================================================================
def _pump(posted, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while len(posted) < n and time.monotonic() < deadline:
        time.sleep(0.01)
    for cb in posted[:]:
        posted.remove(cb)
        cb()


def test_gwredis_wrapper(server):
    from goworld_tpu.ext.db.gwredis import GWRedis
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    posted = []
    workers = AsyncWorkers(posted.append)
    r = GWRedis(server.addr, workers)
    got = []
    r.set("greet", "hello", cb=lambda res, err: got.append(("set", err)))
    r.get("greet", cb=lambda res, err: got.append(("get", res, err)))
    r.command(lambda res, err: got.append(("dbsize", res, err)), "DBSIZE")
    _pump(posted, 3)
    assert got[0] == ("set", None)
    assert got[1] == ("get", b"hello", None)
    assert got[2][1] >= 1 and got[2][2] is None
    r.close()


def test_gwmongo_wrapper(server):
    from goworld_tpu.ext.db.gwmongo import GWMongo
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    posted = []
    workers = AsyncWorkers(posted.append)
    m = GWMongo.connect_redis(server.addr, workers)
    got = {}
    did = m.insert_one("game", "mail", {"to": "e1", "title": "hi"},
                       cb=lambda res, err: got.update(ins=(res, err)))
    _pump(posted, 1)
    assert got["ins"] == (did, None)
    m.find_id("game", "mail", did,
              cb=lambda res, err: got.update(byid=res))
    m.find_one("game", "mail", {"to": "e1"},
               cb=lambda res, err: got.update(byq=res))
    _pump(posted, 2)
    assert got["byid"]["title"] == "hi"
    assert got["byq"]["_id"] == did
    m.update_id("game", "mail", did, {"read": True})
    m.find_id("game", "mail", did,
              cb=lambda res, err: got.update(upd=res))
    _pump(posted, 2)
    assert got["upd"]["read"] is True
    m.insert_one("game", "mail", {"to": "e2", "title": "yo"})
    m.count("game", "mail", cb=lambda res, err: got.update(n=res))
    m.find_all("game", "mail", {},
               cb=lambda res, err: got.update(all=res))
    _pump(posted, 3)
    assert got["n"] == 2 and len(got["all"]) == 2
    m.remove_id("game", "mail", did)
    m.count("game", "mail", cb=lambda res, err: got.update(n2=res))
    _pump(posted, 2)
    assert got["n2"] == 1
    m.close()


def test_redis_cluster_mode_protocol():
    """Real cluster-mode protocol (round 5, closes the PARITY
    deviation): slot map discovery via CLUSTER SLOTS from a single
    seed, hashtag routing, MOVED repair after a live reshard, and the
    ASK migration dance."""
    from goworld_tpu.ext.db.resp import key_slot
    from goworld_tpu.kvdb import RedisClusterKVDB, RedisKVDB

    with MiniRedis(cluster_slots=(0, 5000)) as n1, \
            MiniRedis(cluster_slots=(5001, 11000)) as n2, \
            MiniRedis(cluster_slots=(11001, 16383)) as n3:
        nodes = (n1, n2, n3)
        for srv in nodes:
            srv.peers = {o.addr: o.cluster_slots for o in nodes
                         if o is not srv}
        # seed with ONE node: the client must discover the rest
        b = RedisClusterKVDB([n1.addr])
        assert b._slot_map is not None
        kv = {f"acct{i:03d}": str(i) for i in range(60)}
        for k, v in kv.items():
            b.put(k, v)
        for k, v in kv.items():
            assert b.get(k) == v
        # keys landed on the node OWNING their slot (not just any node)
        for srv in nodes:
            lo, hi = srv.cluster_slots
            for fk in srv.dbs.get(0, {}):
                assert lo <= key_slot(fk) <= hi
        # hashtags co-locate
        s1 = key_slot((RedisKVDB.PREFIX + "{user9}.gold").encode())
        s2 = key_slot((RedisKVDB.PREFIX + "{user9}.level").encode())
        assert s1 == s2
        # ranges merge across the cluster
        got = b.get_range("acct010", "acct015")
        assert got == [(f"acct{i:03d}", str(i)) for i in range(10, 15)]

        # live reshard: n2's range moves to n3; the stale client map
        # must repair itself via -MOVED and keep working
        moved_kv = {}
        for k in kv:
            fk = (RedisKVDB.PREFIX + k).encode()
            if 5001 <= key_slot(fk) <= 11000:
                moved_kv[fk] = n2.dbs[0].pop(fk)
        n3.dbs.setdefault(0, {}).update(moved_kv)
        n2.cluster_slots = (5001, 5000)      # empty range
        n3.cluster_slots = (5001, 16383)
        for srv in nodes:
            srv.peers = {o.addr: o.cluster_slots for o in nodes
                         if o is not srv}
        assert moved_kv, "reshard moved nothing — broaden the key set"
        for k, v in kv.items():
            assert b.get(k) == v             # MOVED chains repaired

        # ASK: n1 marks one slot as migrating to n3; the client must
        # do the ASKING dance without updating its map
        ask_key = next(k for k in kv
                       if key_slot((RedisKVDB.PREFIX + k).encode())
                       <= 5000)
        fk = (RedisKVDB.PREFIX + ask_key).encode()
        slot = key_slot(fk)
        n3.dbs[0][fk] = b"asked"
        n1.ask[slot] = n3.addr
        map_before = b._slot_map[slot]
        assert b.get(ask_key) == "asked"
        assert b._slot_map[slot] == map_before   # ASK never remaps
        n1.ask.clear()
        assert b.get(ask_key) == kv[ask_key]     # back to the owner
        b.close()


def test_redis_cluster_legacy_routing_is_bare_key_compatible():
    """When nodes have cluster support disabled, routing must hash the
    BARE key (pre-cluster-protocol behavior) so an existing
    independent-node deployment keeps finding its data."""
    from goworld_tpu.ext.db.resp import crc16
    from goworld_tpu.kvdb import RedisClusterKVDB, RedisKVDB

    with MiniRedis() as n1, MiniRedis() as n2, MiniRedis() as n3:
        nodes = [n1, n2, n3]
        b = RedisClusterKVDB([s.addr for s in nodes])
        assert b._slot_map is None          # legacy mode detected
        for i in range(20):
            k = f"legacy{i:02d}"
            b.put(k, str(i))
            owner = nodes[crc16(k.encode()) % 3]
            fk = (RedisKVDB.PREFIX + k).encode()
            assert fk in owner.dbs.get(0, {}), \
                f"{k} not on the bare-key-hash node"
        b.close()


def test_miniredis_cluster_rejects_cross_slot_mget():
    """The stub must be as strict as real cluster redis: a multi-key
    command spanning slots errors with CROSSSLOT even when every slot
    is locally owned — otherwise tests certify client behavior a real
    cluster would reject."""
    from goworld_tpu.ext.db.resp import RespClient, RespError, key_slot

    with MiniRedis(cluster_slots=(0, 16383)) as srv:
        c = RespClient.from_addr(srv.addr)
        k1, k2 = b"alpha", b"beta"
        assert key_slot(k1) != key_slot(k2)
        c.command(b"SET", k1, b"1")
        c.command(b"SET", k2, b"2")
        with pytest.raises(RespError, match="CROSSSLOT"):
            c.command(b"MGET", k1, k2)
        # same-slot multi-key is fine (hashtags co-locate)
        c.command(b"SET", b"{t}a", b"1")
        c.command(b"SET", b"{t}b", b"2")
        assert c.command(b"MGET", b"{t}a", b"{t}b") == [b"1", b"2"]
        c.close()
