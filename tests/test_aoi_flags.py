"""Flag propagation through the AOI sweep + two-level bounded extraction.

grid_neighbors_flags rides per-entity dirty/has_client bits through the
packed candidate words (fast path) or a bounded [Q, k] gather (wide-id
fallback), so downstream sync collection never gathers over [N, k]
(reference hot loop being rebuilt: CollectEntitySyncInfos,
engine/entity/Entity.go:1208-1267)."""

import jax.numpy as jnp
import numpy as np
import pytest

from goworld_tpu.ops.aoi import GridSpec, grid_neighbors, \
    grid_neighbors_flags, neighbors_oracle
from goworld_tpu.ops.extract import bounded_extract, bounded_extract_rows
from goworld_tpu.ops.sync import collect_sync


def random_world(n, seed, extent=200.0):
    rng = np.random.default_rng(seed)
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, extent, n)
    pos[:, 2] = rng.uniform(0, extent, n)
    return pos, rng


@pytest.mark.parametrize("seed", [0, 3])
def test_flags_align_with_neighbors(seed):
    n = 256
    pos, rng = random_world(n, seed)
    alive = jnp.ones(n, bool)
    dirty = rng.uniform(size=n) < 0.3
    hc = rng.uniform(size=n) < 0.2
    flag_bits = jnp.asarray(
        dirty.astype(np.int32) | (hc.astype(np.int32) << 1)
    )
    spec = GridSpec(radius=25.0, extent_x=200.0, extent_z=200.0,
                    k=128, cell_cap=128, row_block=64)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(pos), alive, flag_bits=flag_bits
    )
    nbr, cnt, fl = np.asarray(nbr), np.asarray(cnt), np.asarray(fl)
    oracle = neighbors_oracle(pos, np.ones(n, bool), 25.0)
    for i in range(n):
        got = set(nbr[i][nbr[i] < n].tolist())
        assert got == oracle[i]
        for lane in range(nbr.shape[1]):
            j = nbr[i, lane]
            if j == n:
                assert fl[i, lane] == 0
            else:
                assert fl[i, lane] & 1 == int(dirty[j])
                assert (fl[i, lane] >> 1) & 1 == int(hc[j])
    # flags variant must agree with the plain sweep
    nbr2, cnt2 = grid_neighbors(spec, jnp.asarray(pos), alive)
    np.testing.assert_array_equal(nbr, np.asarray(nbr2))
    np.testing.assert_array_equal(cnt, np.asarray(cnt2))


def test_collect_sync_flag_path_matches_gather_path():
    n = 300
    pos, rng = random_world(n, 7)
    alive = jnp.ones(n, bool)
    dirty = jnp.asarray(rng.uniform(size=n) < 0.4)
    hc = jnp.asarray(rng.uniform(size=n) < 0.3)
    spec = GridSpec(radius=25.0, extent_x=200.0, extent_z=200.0,
                    k=64, cell_cap=64, row_block=64)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(pos), alive,
        flag_bits=dirty.astype(jnp.int32),
    )
    yaw = jnp.zeros(n)
    ref = collect_sync(nbr, dirty, hc, jnp.asarray(pos), yaw, 512)
    got = collect_sync(nbr, dirty, hc, jnp.asarray(pos), yaw, 512,
                       nbr_dirty=(fl & 1).astype(bool))
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("shape,density,cap", [
    ((64, 8), 0.2, 32),    # overflow: count > cap
    ((64, 8), 0.02, 64),   # sparse
    ((33, 5), 0.0, 16),    # empty
    ((128, 32), 1.0, 256),  # dense overflow
])
def test_two_level_extract_matches_flat(shape, density, cap):
    rng = np.random.default_rng(int(shape[0] * density * cap))
    mask = jnp.asarray(rng.uniform(size=shape) < density)
    f1, v1, c1 = bounded_extract(mask, cap)
    f2, v2, c2 = bounded_extract_rows(mask, cap)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    assert int(c1) == int(c2)
    # identical extraction INCLUDING which bits drop on overflow
    np.testing.assert_array_equal(
        np.asarray(f1)[np.asarray(v1)], np.asarray(f2)[np.asarray(v2)]
    )
