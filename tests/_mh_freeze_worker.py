"""Worker for test_multihost.py::test_multihost_checkpoint_restore —
freeze_world/restore_world on a TWO-CONTROLLER megaspace.

Every controller invokes freeze_world at the same point (its device
snapshot is a process_allgather, so the collective legs pair up) and
gets the identical global snapshot; restore_world replays the world API
SPMD-identically into a fresh World over the same mesh. §5.4
checkpoint/resume, extended across controllers (the reference freezes a
single game process, ``GameService.go:220-313``).

Invoked as: python -m tests._mh_freeze_worker <pid> <port>
(env: JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=4).
"""

import json
import sys


def main() -> int:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from goworld_tpu.parallel.multihost import global_mesh, init_distributed
    init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.freeze import freeze_world, restore_world
    from goworld_tpu.ops.aoi import GridSpec

    n_dev, tile_w, radius = 8, 100.0, 10.0
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=8, cell_cap=16, row_block=16),
        npc_speed=0.0,
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mesh = global_mesh()

    class Mega(Space):
        pass

    class Npc(Entity):
        ATTRS = {"hp": "client"}

    def build_world() -> World:
        w = World(cfg, n_spaces=n_dev, mesh=mesh, megaspace=True,
                  halo_cap=8, migrate_cap=4)
        w.registry.register("Mega", Mega, is_space=True, megaspace=True)
        w.register_entity("Npc", Npc)
        w.create_nil_space()
        return w

    w = build_world()
    sp = w.create_space("Mega")
    walker = w.create_entity("Npc", space=sp, pos=(398.5, 0.0, 50.0),
                             eid="walker_walker_00")
    watcher = w.create_entity("Npc", space=sp, pos=(406.0, 0.0, 50.0),
                              eid="watcher_watcher0")
    walker.attrs["hp"] = 7

    # drive the walker across the controller boundary (tile 3 -> 4)
    x = 398.5
    for _ in range(5):
        x += 1.5
        walker.set_position((x, 0.0, 50.0))
        w.tick()
    pre = {
        "walker_shard": walker.shard,
        "walker_x": float(walker.position[0]),
        "watcher_sees": sorted(watcher.interested_in),
    }

    # identical call on both controllers: the device snapshot inside is
    # an allgather, so this is itself a lockstep point
    snap = freeze_world(w)

    w2 = build_world()
    restore_world(w2, snap)
    walker2 = w2.entities["walker_walker_00"]
    watcher2 = w2.entities["watcher_watcher0"]
    # interest re-forms from the restored positions on the next sweep
    for _ in range(3):
        w2.tick()

    out = {
        "process": pid,
        "pre": pre,
        "restored_walker_shard": walker2.shard,
        "restored_walker_x": float(walker2.position[0]),
        "restored_hp": walker2.attrs.get("hp"),
        "restored_watcher_sees": sorted(watcher2.interested_in),
        "restored_alive": int(
            __import__("numpy").asarray(
                w2.last_outputs.global_alive
            )[0]
        ),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
