"""tools/bench_trend.py — the trajectory regression gate, in tier-1.

The real checked-in BENCH_r*/MULTICHIP_r* trajectory must PASS (the
gate runs after every round; a red gate on the committed history would
make it dead on arrival), an injected regression must FAIL, and a
missing file is a usage error, not a silent pass.
"""

import importlib.util
import json
import os

import pytest

pytestmark = pytest.mark.devprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


TREND = _load("bench_trend")


def _bench_rec(value, entities=1000, platform="cpu", tick_ms=10.0,
               phase_ms=None, slo=None, scenarios=None):
    rec = {
        "metric": "entity_ticks_per_sec_per_chip", "value": value,
        "unit": "entity-ticks/s/chip", "vs_baseline": 0.0,
        "entities": entities, "tick_ms": tick_ms, "platform": platform,
        "stage": "full", "attempts": [],
        "phase_ms": phase_ms or {"aoi": 5.0, "move": 1.0},
    }
    if slo is not None:
        rec["slo"] = slo
    if scenarios is not None:
        rec["scenarios"] = scenarios
    return rec


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


def test_real_checked_in_trajectory_passes():
    assert TREND.main(["--dir", REPO]) == 0


def test_missing_file_is_an_error(capsys):
    assert TREND.main([os.path.join(REPO, "BENCH_r99_missing.json")]) \
        == 1
    assert "missing file" in capsys.readouterr().err


def test_improvement_passes(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(1500.0, tick_ms=7.0))
    assert TREND.main([f1, f2]) == 0


def test_injected_headline_regression_fails(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0))
    f2 = _write(tmp_path, "BENCH_r02.json", _bench_rec(500.0))
    assert TREND.main([f1, f2]) == 2


def test_regression_vs_best_prior_not_just_previous(tmp_path):
    # r2 dipped (historic, not gated), r3 must still beat r1's best
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0))
    f2 = _write(tmp_path, "BENCH_r02.json", _bench_rec(100.0))
    f3 = _write(tmp_path, "BENCH_r03.json", _bench_rec(650.0))
    assert TREND.main([f1, f2, f3]) == 2  # 650 < 0.7 * 1000
    f3b = _write(tmp_path, "BENCH_r04.json", _bench_rec(900.0))
    assert TREND.main([f1, f2, f3b]) == 0


def test_phase_regression_fails(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json",
                _bench_rec(1000.0, phase_ms={"aoi": 5.0}))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(1000.0, phase_ms={"aoi": 9.0}))
    assert TREND.main([f1, f2]) == 2


def test_phase_regression_demoted_when_headline_improved(tmp_path):
    """The split gate catches a phase rotting UNDER a flat headline;
    when the headline itself improved past the threshold vs the same
    predecessor (r12 vs r05: different hardware, 1.9x faster headline,
    slower collect split), the split flags demote to NOTES — recorded,
    never gated. A flat headline keeps the hard gate (test above)."""
    f1 = _write(tmp_path, "BENCH_r01.json",
                _bench_rec(1000.0, phase_ms={"aoi": 5.0}))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(1900.0, phase_ms={"aoi": 9.0}))
    assert TREND.main([f1, f2]) == 0
    # just-under-threshold improvement still gates the split
    f2b = _write(tmp_path, "BENCH_r03.json",
                 _bench_rec(1200.0, phase_ms={"aoi": 20.0}))
    assert TREND.main([f1, f2b]) == 2


def test_shape_change_is_not_compared(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json",
                _bench_rec(1000.0, entities=1000))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(10.0, entities=8))  # different shape
    assert TREND.main([f1, f2]) == 0


def test_slo_pass_to_fail_transition_fails(tmp_path):
    ok = {"target_ms": 16.0, "p99_ms": 8.0, "pass": True}
    bad = {"target_ms": 16.0, "p99_ms": 33.0, "pass": False}
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0, slo=ok))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(1000.0, slo=bad))
    assert TREND.main([f1, f2]) == 2
    # fail -> fail is the recorded status quo, not a regression
    f1b = _write(tmp_path, "BENCH_r03.json",
                 _bench_rec(1000.0, slo=bad))
    f2b = _write(tmp_path, "BENCH_r04.json",
                 _bench_rec(1000.0, slo=bad))
    assert TREND.main([f1b, f2b]) == 0


def test_signature_drift_is_informational_not_gated(tmp_path):
    """A workload-signature class change between comparable rounds is
    surfaced as a NOTE but never fails the gate (ISSUE 11: the
    signature describes the workload, not the implementation)."""
    r1 = _bench_rec(1000.0)
    r1["workload_signature"] = {"sig": "churn=flock_like|density=exact"}
    r2 = _bench_rec(1100.0)
    r2["workload_signature"] = {
        "sig": "churn=teleport_like|density=over_k"}
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    problems: list = []
    notes: list = []
    TREND.check_bench([f1, f2], 0.30, problems, notes)
    assert problems == []
    assert any("workload signature drifted" in n for n in notes)
    assert TREND.main([f1, f2]) == 0
    # stable signature: just the informational stamp, no drift note
    r2["workload_signature"] = dict(r1["workload_signature"])
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    problems, notes = [], []
    TREND.check_bench([f1, f2], 0.30, problems, notes)
    assert problems == []
    assert not any("drifted" in n for n in notes)


def _gov_block(throughput, n=1024, schedule=("flock", "teleport",
                                             "hotspot")):
    return {"schedule": list(schedule), "n": n,
            "throughput": throughput,
            "phases": [], "static_wall_s": {"default": 1.0}}


def test_governor_mode_headline_is_its_own_anchor_series(tmp_path):
    """A headline stamped bench_mode=governor never gates against (or
    anchors) static rounds — the (entities, platform, mode) shape key
    (ISSUE 13): the governor number includes swap dynamics and a
    scenario schedule, a different experiment entirely."""
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0))
    gov_rec = _bench_rec(300.0)  # 70% "down" vs r1 — but governor-mode
    gov_rec["bench_mode"] = "governor"
    f2 = _write(tmp_path, "BENCH_r02.json", gov_rec)
    assert TREND.main([f1, f2]) == 0
    # and a static round after it gates against r1, not the governor
    f3 = _write(tmp_path, "BENCH_r03.json", _bench_rec(950.0))
    assert TREND.main([f1, f2, f3]) == 0
    f3b = _write(tmp_path, "BENCH_r03.json", _bench_rec(500.0))
    assert TREND.main([f1, f2, f3b]) == 2


def test_governor_block_series_gated_and_regression_fails(tmp_path):
    """The governor schedule block's throughput is its own series:
    same schedule shape gates vs the best prior; a skipped round
    neither gates nor anchors; an injected regression fails."""
    r1 = _bench_rec(1000.0)
    r1["governor"] = _gov_block(2000.0)
    r2 = _bench_rec(1000.0)
    r2["governor"] = _gov_block(1900.0)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected governor regression: static headline flat, governor
    # throughput down 60% -> gate fails
    r3 = _bench_rec(1000.0)
    r3["governor"] = _gov_block(800.0)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # a skipped-governor round between them is not a gate or an anchor
    r3b = _bench_rec(1000.0)
    r3b["governor"] = {"skipped": "--governor not requested"}
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # a different schedule shape is a different series
    r3c = _bench_rec(1000.0)
    r3c["governor"] = _gov_block(800.0, schedule=("flock", "shrink"))
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0


def test_governor_gate_survives_headline_shape_change(tmp_path):
    """The governor series is keyed by its OWN (n, platform, schedule)
    shape: a round that changes the HEADLINE entity count (so the
    headline has no prior and is not gated) must still gate its
    governor block against the prior rounds' — the early headline
    return must not swallow the governor comparison (review
    finding)."""
    r1 = _bench_rec(1000.0, entities=1000)
    r1["governor"] = _gov_block(2000.0)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    # headline shape changes (no prior -> headline ungated) while the
    # governor block regresses 60% at the SAME governor shape
    r2 = _bench_rec(5000.0, entities=4096)
    r2["governor"] = _gov_block(800.0)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # same headline-shape change with a healthy governor block passes
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["governor"] = _gov_block(1950.0)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 0


def test_scenario_value_regression_fails(tmp_path):
    sc_ok = {"hotspot": {"value": 500.0, "entities": 512,
                         "tick_ms": 1.0}}
    sc_bad = {"hotspot": {"value": 100.0, "entities": 512,
                          "tick_ms": 5.0}}
    f1 = _write(tmp_path, "BENCH_r01.json",
                _bench_rec(1000.0, scenarios=sc_ok))
    f2 = _write(tmp_path, "BENCH_r02.json",
                _bench_rec(1000.0, scenarios=sc_bad))
    assert TREND.main([f1, f2]) == 2


def test_suspect_and_failed_rounds_are_skipped(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json",
                {"cmd": "x", "rc": 1, "parsed": None, "tail": ""})
    rec = _bench_rec(1000.0)
    rec["timing_suspect"] = "2x scan took 1.1x"
    f2 = _write(tmp_path, "BENCH_r02.json", rec)
    f3 = _write(tmp_path, "BENCH_r03.json", _bench_rec(900.0))
    # only r3 has a trustworthy headline -> nothing to gate
    assert TREND.main([f1, f2, f3]) == 0


def test_multichip_ok_regression_fails(tmp_path):
    f1 = _write(tmp_path, "MULTICHIP_r01.json",
                {"n_devices": 8, "rc": 0, "ok": True, "tail": "",
                 "skipped": False})
    f2 = _write(tmp_path, "MULTICHIP_r02.json",
                {"n_devices": 8, "rc": 1, "ok": False, "tail": "",
                 "skipped": False})
    assert TREND.main([f1, f2]) == 2
    f2b = _write(tmp_path, "MULTICHIP_r03.json",
                 {"n_devices": 8, "rc": 0, "ok": True, "tail": "",
                  "skipped": False})
    assert TREND.main([f1, f2b]) == 0


def test_threshold_knob(tmp_path):
    f1 = _write(tmp_path, "BENCH_r01.json", _bench_rec(1000.0))
    f2 = _write(tmp_path, "BENCH_r02.json", _bench_rec(850.0))
    assert TREND.main([f1, f2]) == 0              # within default 30%
    assert TREND.main(["--threshold", "0.1", f1, f2]) == 2


def _multi_rec(value, eff=0.8, n=65536, n_dev=8, platform="cpu",
               **extra):
    rec = {
        "n_devices": n_dev, "rc": 0, "ok": True, "skipped": False,
        "tail": "",
        "headline": {
            "entity_ticks_per_sec_mesh": value,
            "per_chip_efficiency": eff,
            "n_entities": n, "platform": platform, "n_devices": n_dev,
        },
    }
    rec.update(extra)
    return rec


def test_multichip_headline_regression_fails(tmp_path):
    f1 = _write(tmp_path, "MULTICHIP_r10.json", _multi_rec(100000.0))
    f2 = _write(tmp_path, "MULTICHIP_r11.json", _multi_rec(60000.0))
    assert TREND.main([f1, f2]) == 2
    f2b = _write(tmp_path, "MULTICHIP_r12.json", _multi_rec(95000.0))
    assert TREND.main([f1, f2b]) == 0


def test_multichip_efficiency_drop_fails(tmp_path):
    """A mesh that keeps throughput but burns per-chip efficiency
    (>30% drop) regresses even with the headline flat."""
    f1 = _write(tmp_path, "MULTICHIP_r10.json",
                _multi_rec(100000.0, eff=0.8))
    f2 = _write(tmp_path, "MULTICHIP_r11.json",
                _multi_rec(100000.0, eff=0.5))
    assert TREND.main([f1, f2]) == 2
    f2b = _write(tmp_path, "MULTICHIP_r12.json",
                 _multi_rec(100000.0, eff=0.7))
    assert TREND.main([f1, f2b]) == 0


def test_multichip_shape_change_not_compared(tmp_path):
    """A different (entities, platform, n_devices) shape is a new
    baseline, not a regression."""
    f1 = _write(tmp_path, "MULTICHIP_r10.json",
                _multi_rec(100000.0, n=65536))
    f2 = _write(tmp_path, "MULTICHIP_r11.json",
                _multi_rec(20000.0, n=8192))
    assert TREND.main([f1, f2]) == 0
    f3 = _write(tmp_path, "MULTICHIP_r12.json",
                _multi_rec(20000.0, n_dev=16))
    assert TREND.main([f1, f3]) == 0


def test_multichip_dryrun_rounds_not_headline_gated(tmp_path):
    """Pre-r10 dryrun-only records neither gate nor anchor the mesh
    headline; the ok/rc invariants still apply."""
    f1 = _write(tmp_path, "MULTICHIP_r05.json",
                {"n_devices": 8, "rc": 0, "ok": True, "tail": "",
                 "skipped": False})
    f2 = _write(tmp_path, "MULTICHIP_r10.json", _multi_rec(100.0))
    assert TREND.main([f1, f2]) == 0


def _sa_block(p99, records=2048, clients=4, passed=None):
    return {"target_ms": 16.0, "records_per_tick": records,
            "clients": clients,
            "e2e": {"samples": 1000, "p50_ms": p99 / 3,
                    "p90_ms": p99 / 2, "p99_ms": p99},
            "hops": {}, "pass": (p99 <= 16.0 if passed is None
                                 else passed),
            "stamp_overhead_pct_of_budget": 0.05}


def test_sync_age_series_gated_and_regression_fails(tmp_path):
    """The sync-age loopback block's e2e p99 is its own
    lower-is-better series at the same (records, clients, platform)
    shape (ISSUE 15): a >30% p99 regression fails, skip/error rounds
    neither gate nor anchor, shape changes are new series."""
    r1 = _bench_rec(1000.0)
    r1["sync_age"] = _sa_block(10.0)
    r2 = _bench_rec(1000.0)
    r2["sync_age"] = _sa_block(11.0)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected regression: headline flat, e2e p99 up 3x -> gate fails
    r3 = _bench_rec(1000.0)
    r3["sync_age"] = _sa_block(30.0, passed=False)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # an honest skip neither gates nor anchors
    r3b = _bench_rec(1000.0)
    r3b["sync_age"] = {"skipped": "BENCH_SYNC_AGE=0"}
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # a different harness shape is a different series
    r3c = _bench_rec(1000.0)
    r3c["sync_age"] = _sa_block(30.0, records=32768, passed=False)
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0


def test_sync_age_pass_to_fail_transition_fails(tmp_path):
    """A verdict flip pass -> fail at the same shape always fails,
    even inside the 30% p99 band (the slo-flip rule)."""
    r1 = _bench_rec(1000.0)
    r1["sync_age"] = _sa_block(15.0)           # pass, close to target
    r2 = _bench_rec(1000.0)
    r2["sync_age"] = _sa_block(17.0, passed=False)  # +13%, but a flip
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # fail -> fail within the band is the recorded status quo
    r1b = _bench_rec(1000.0)
    r1b["sync_age"] = _sa_block(20.0, passed=False)
    r2b = _bench_rec(1000.0)
    r2b["sync_age"] = _sa_block(22.0, passed=False)
    f1b = _write(tmp_path, "BENCH_r03.json", r1b)
    f2b = _write(tmp_path, "BENCH_r04.json", r2b)
    assert TREND.main([f1b, f2b]) == 0


def test_sync_age_gate_survives_headline_shape_change(tmp_path):
    """Like the governor series: a round that changes the headline
    entity count must still gate its sync_age block against prior
    rounds' — the early headline return must not swallow it."""
    r1 = _bench_rec(1000.0, entities=1000)
    r1["sync_age"] = _sa_block(10.0)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(5000.0, entities=4096)
    r2["sync_age"] = _sa_block(30.0, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["sync_age"] = _sa_block(10.5)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 0


def _rs_block(p99, gap, entities=64, passed=None):
    return {
        "entities": entities,
        "bubble": {"samples": 90, "p50_ms": p99 / 4, "p90_ms": p99 / 2,
                   "p99_ms": p99},
        "tick": {"samples": 90, "p50_ms": 17.0, "p90_ms": 18.0,
                 "p99_ms": 20.0},
        "bubble_budget_ms": 4.0,
        "serve_gap": gap,
        "serve_gap_ref": "scan_marginal",
        "pass": (p99 <= 4.0 if passed is None else passed),
    }


def test_residency_series_gated_and_regression_fails(tmp_path):
    """The residency block's bubble p99 and serve_gap are their own
    lower-is-better series at the same (entities, platform) shape
    (ISSUE 16): an injected regression in either fails, skip/error
    rounds neither gate nor anchor, shape changes are new series."""
    r1 = _bench_rec(1000.0)
    r1["residency"] = _rs_block(2.0, 1.4)
    r2 = _bench_rec(1000.0)
    r2["residency"] = _rs_block(2.2, 1.5)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected bubble regression: headline flat, bubble p99 up 4x
    r3 = _bench_rec(1000.0)
    r3["residency"] = _rs_block(8.0, 1.4, passed=False)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # injected serve_gap regression with a healthy bubble
    r3g = _bench_rec(1000.0)
    r3g["residency"] = _rs_block(2.0, 2.5)
    f3g = _write(tmp_path, "BENCH_r03.json", r3g)
    assert TREND.main([f1, f2, f3g]) == 2
    # an honest skip neither gates nor anchors
    r3b = _bench_rec(1000.0)
    r3b["residency"] = {"skipped": "BENCH_RESIDENCY=0"}
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # a different residency shape is a different series
    r3c = _bench_rec(1000.0)
    r3c["residency"] = _rs_block(8.0, 2.5, entities=192, passed=False)
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0


def test_residency_pass_to_fail_and_inf_fail(tmp_path):
    """A verdict flip pass -> fail at the same shape always fails (the
    slo-flip rule), and a latest round whose bubble p99 lands past the
    last bucket ("inf", the ptiles convention) fails against any
    finite prior."""
    r1 = _bench_rec(1000.0)
    r1["residency"] = _rs_block(3.5, 1.4)            # pass, near budget
    r2 = _bench_rec(1000.0)
    r2["residency"] = _rs_block(4.2, 1.4, passed=False)  # flip
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # "inf" latest vs finite prior: strongest regression, gated
    r2b = _bench_rec(1000.0)
    r2b["residency"] = _rs_block(3.5, 1.4, passed=False)
    r2b["residency"]["bubble"]["p99_ms"] = "inf"
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2
    # zero-bubble prior + sub-slack latest: the 0.25 ms absolute slack
    # keeps timer noise from gating a healthy round
    r1c = _bench_rec(1000.0)
    r1c["residency"] = _rs_block(0.0, 1.4)
    r2c = _bench_rec(1000.0)
    r2c["residency"] = _rs_block(0.2, 1.4)
    f1c = _write(tmp_path, "BENCH_r03.json", r1c)
    f2c = _write(tmp_path, "BENCH_r04.json", r2c)
    assert TREND.main([f1c, f2c]) == 0


def test_residency_gate_survives_headline_shape_change(tmp_path):
    """Like the governor/sync_age series: a round that changes the
    headline entity count must still gate its residency block against
    prior rounds' — the early headline return must not swallow it."""
    r1 = _bench_rec(1000.0, entities=1000)
    r1["residency"] = _rs_block(2.0, 1.4)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(5000.0, entities=4096)
    r2["residency"] = _rs_block(8.0, 1.4, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["residency"] = _rs_block(2.1, 1.45)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 0


def _fo_block(latency, lost=0, dup=0, rejected=0, entities=48,
              replay_ok=True, passed=None):
    return {
        "entities": entities,
        "replication_bytes_per_tick": 5163.3,
        "client_sync_bytes_per_tick": 1214.4,
        "standby_apply_ms_per_tick": 0.9,
        "promotion_latency_ticks": latency,
        "lag_budget_ticks": 16,
        "entities_lost": lost,
        "entities_duplicated": dup,
        "frames_applied": 20,
        "frames_rejected": rejected,
        "decision_log_replay_ok": replay_ok,
        "pass": ((lost == 0 and dup == 0 and latency <= 16)
                 if passed is None else passed),
    }


def test_failover_entity_loss_always_fails(tmp_path):
    """ISSUE 18: ANY lost or duplicated EntityID across promotion
    fails unconditionally — conservation needs no prior round (a lost
    entity is a bug, not a trend), and a flat headline must not hide
    it. Torn frames and a failed decision-log replay gate the same
    way."""
    r1 = _bench_rec(1000.0)  # prior round without a failover block
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(1000.0)
    r2["failover"] = _fo_block(1, lost=2, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    r2b = _bench_rec(1000.0)
    r2b["failover"] = _fo_block(1, dup=1, passed=False)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2
    r2c = _bench_rec(1000.0)
    r2c["failover"] = _fo_block(1, rejected=3, passed=False)
    f2c = _write(tmp_path, "BENCH_r02.json", r2c)
    assert TREND.main([f1, f2c]) == 2
    r2d = _bench_rec(1000.0)
    r2d["failover"] = _fo_block(1, replay_ok=False, passed=False)
    f2d = _write(tmp_path, "BENCH_r02.json", r2d)
    assert TREND.main([f1, f2d]) == 2
    # a clean block with no prior is a new anchor, not a gate
    r2e = _bench_rec(1000.0)
    r2e["failover"] = _fo_block(1)
    f2e = _write(tmp_path, "BENCH_r02.json", r2e)
    assert TREND.main([f1, f2e]) == 0


def test_failover_promotion_latency_lower_is_better(tmp_path):
    """The promotion latency gates against the best (lowest) prior at
    the same (entities, platform) shape with a 1-tick absolute slack;
    skip rounds neither gate nor anchor; shape changes are new
    series."""
    r1 = _bench_rec(1000.0)
    r1["failover"] = _fo_block(1)
    r2 = _bench_rec(1000.0)
    r2["failover"] = _fo_block(2)  # within 1.3x + 1 tick slack
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected latency regression: headline flat, promotion 5x slower
    r3 = _bench_rec(1000.0)
    r3["failover"] = _fo_block(5)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # an honest skip neither gates nor anchors
    r3b = _bench_rec(1000.0)
    r3b["failover"] = {"skipped": "BENCH_FAILOVER=0"}
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # a different harness shape is a different series
    r3c = _bench_rec(1000.0)
    r3c["failover"] = _fo_block(5, entities=192)
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0


def test_failover_pass_to_fail_transition_fails(tmp_path):
    """A verdict flip pass -> fail at the same shape always fails,
    even when every individual number stays inside its band (the
    slo-flip rule)."""
    r1 = _bench_rec(1000.0)
    r1["failover"] = _fo_block(1)
    r2 = _bench_rec(1000.0)
    r2["failover"] = _fo_block(2, passed=False)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # fail -> fail is the recorded status quo, not a regression
    r1b = _bench_rec(1000.0)
    r1b["failover"] = _fo_block(2, passed=False)
    r2b = _bench_rec(1000.0)
    r2b["failover"] = _fo_block(2, passed=False)
    f1b = _write(tmp_path, "BENCH_r03.json", r1b)
    f2b = _write(tmp_path, "BENCH_r04.json", r2b)
    assert TREND.main([f1b, f2b]) == 0


def test_failover_gate_survives_headline_shape_change(tmp_path):
    """Like the governor/sync_age/residency series: a round that
    changes the headline entity count must still gate its failover
    block against prior rounds' — the early headline return must not
    swallow the conservation check."""
    r1 = _bench_rec(1000.0, entities=1000)
    r1["failover"] = _fo_block(1)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(5000.0, entities=4096)
    r2["failover"] = _fo_block(1, lost=1, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["failover"] = _fo_block(1)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 0


def _rb_block(recovery, lost=0, dup=0, moved=24, replay_ok=True,
              passed=None):
    return {
        "entities": 96,
        "donor_p99_before_ms": 12.1,
        "donor_p99_after_ms": 10.4,
        "batch": 24,
        "entities_moved": moved,
        "aborts": 0,
        "donor_recovery_windows": recovery,
        "entities_lost": lost,
        "entities_duplicated": dup,
        "decision_log_replay_ok": replay_ok,
        "pass": ((lost == 0 and dup == 0 and recovery is not None)
                 if passed is None else passed),
    }


def test_rebalance_entity_loss_always_fails(tmp_path):
    """ISSUE 19: ANY lost or duplicated entity across the automated
    handoff fails unconditionally — conservation needs no prior round
    — and a failed DecisionLog byte replay gates the same way."""
    r1 = _bench_rec(1000.0)  # prior round without a rebalance block
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(1000.0)
    r2["rebalance"] = _rb_block(2, lost=3, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    r2b = _bench_rec(1000.0)
    r2b["rebalance"] = _rb_block(2, dup=1, passed=False)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2
    r2c = _bench_rec(1000.0)
    r2c["rebalance"] = _rb_block(2, replay_ok=False, passed=False)
    f2c = _write(tmp_path, "BENCH_r02.json", r2c)
    assert TREND.main([f1, f2c]) == 2
    # a clean block with no prior is a new anchor, not a gate
    r2d = _bench_rec(1000.0)
    r2d["rebalance"] = _rb_block(2)
    f2d = _write(tmp_path, "BENCH_r02.json", r2d)
    assert TREND.main([f1, f2d]) == 0


def test_rebalance_recovery_latency_lower_is_better(tmp_path):
    """Donor recovery latency gates against the best (lowest) prior
    at the same (entities_moved, platform) shape with a 1-window
    absolute slack; an aborted round (recovery None) and an honest
    skip neither gate nor anchor; a different moved-count is a
    different series."""
    r1 = _bench_rec(1000.0)
    r1["rebalance"] = _rb_block(2)
    r2 = _bench_rec(1000.0)
    r2["rebalance"] = _rb_block(3)  # within 1.3x + 1 window slack
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected regression: headline flat, recovery 4x slower
    r3 = _bench_rec(1000.0)
    r3["rebalance"] = _rb_block(8)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # an aborted round carries recovery None: no gate, no anchor
    r3b = _bench_rec(1000.0)
    r3b["rebalance"] = _rb_block(None, moved=12, passed=False)
    r3b["rebalance"]["aborts"] = 1
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # an honest skip neither gates nor anchors
    r3c = _bench_rec(1000.0)
    r3c["rebalance"] = {"skipped": "BENCH_REBALANCE=0"}
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0
    # a different moved-count is a different series
    r3d = _bench_rec(1000.0)
    r3d["rebalance"] = _rb_block(8, moved=48)
    f3d = _write(tmp_path, "BENCH_r03.json", r3d)
    assert TREND.main([f1, f2, f3d]) == 0


def test_rebalance_pass_to_fail_and_shape_change(tmp_path):
    """A verdict flip pass -> fail at the same shape always fails;
    the conservation gate survives a headline-shape change (the early
    headline return must not swallow it)."""
    r1 = _bench_rec(1000.0)
    r1["rebalance"] = _rb_block(2)
    r2 = _bench_rec(1000.0)
    r2["rebalance"] = _rb_block(2, passed=False)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # headline shape change + a lost entity: still gated
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["rebalance"] = _rb_block(2, lost=1, passed=False)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2


def _ra_block(ratio=0.96, on_realloc=0, off_realloc=19, passed=None):
    return {
        "entities": 192,
        "capacity": 1024,
        "windows": 6,
        "ticks_per_window": 24,
        "tick_hz": 30.0,
        "on_ms_per_tick": round(30.0 * ratio, 3),
        "off_ms_per_tick": 30.0,
        "ratio": ratio,
        "on_census": {"samples": 12, "realloc": on_realloc,
                      "aliased": 19 - on_realloc,
                      "skipped_deleted": 0},
        "off_census": {"samples": 12, "realloc": off_realloc,
                       "aliased": 19 - off_realloc,
                       "skipped_deleted": 0},
        "pass": ((on_realloc == 0 and off_realloc >= 1
                  and ratio < 1.0) if passed is None else passed),
    }


def test_resident_ab_on_arm_realloc_always_fails(tmp_path):
    """ISSUE 20: ANY re-allocated carry lane in the donation-on arm's
    census fails unconditionally — the resident runtime's contract is
    zero steady-state allocation and needs no prior round; an off arm
    that also reads zero means the A/B measured nothing."""
    r1 = _bench_rec(1000.0)  # prior round without a resident_ab block
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    r2 = _bench_rec(1000.0)
    r2["resident_ab"] = _ra_block(on_realloc=3, passed=False)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # a list-typed census realloc (the raw snapshot form) gates too
    r2b = _bench_rec(1000.0)
    r2b["resident_ab"] = _ra_block(passed=False)
    r2b["resident_ab"]["on_census"]["realloc"] = ["pos", "vel"]
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2
    # an off arm with zero churn measured nothing: flagged
    r2c = _bench_rec(1000.0)
    r2c["resident_ab"] = _ra_block(off_realloc=0, passed=False)
    f2c = _write(tmp_path, "BENCH_r02.json", r2c)
    assert TREND.main([f1, f2c]) == 2
    # a clean block with no prior is a new anchor, not a gate
    r2d = _bench_rec(1000.0)
    r2d["resident_ab"] = _ra_block()
    f2d = _write(tmp_path, "BENCH_r02.json", r2d)
    assert TREND.main([f1, f2d]) == 0


def test_resident_ab_ratio_lower_is_better(tmp_path):
    """The on/off ratio gates against the best (lowest) prior at the
    same (entities, platform) shape — a pure ratio, no absolute
    slack; an honest skip neither gates nor anchors."""
    r1 = _bench_rec(1000.0)
    r1["resident_ab"] = _ra_block(ratio=0.90)
    r2 = _bench_rec(1000.0)
    r2["resident_ab"] = _ra_block(ratio=0.96)  # within 1.3x of 0.90
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 0
    # injected regression: headline flat, on arm now 1.5x the off arm
    r3 = _bench_rec(1000.0)
    r3["resident_ab"] = _ra_block(ratio=1.5, passed=False)
    f3 = _write(tmp_path, "BENCH_r03.json", r3)
    assert TREND.main([f1, f2, f3]) == 2
    # an honest skip neither gates nor anchors
    r3b = _bench_rec(1000.0)
    r3b["resident_ab"] = {"skipped": "BENCH_RESIDENT_AB=0"}
    f3b = _write(tmp_path, "BENCH_r03.json", r3b)
    assert TREND.main([f1, f2, f3b]) == 0
    # a different entity count is a different series
    r3c = _bench_rec(1000.0)
    r3c["resident_ab"] = _ra_block(ratio=1.5, passed=False)
    r3c["resident_ab"]["entities"] = 48
    f3c = _write(tmp_path, "BENCH_r03.json", r3c)
    assert TREND.main([f1, f2, f3c]) == 0


def test_resident_ab_pass_to_fail_and_shape_change(tmp_path):
    """A verdict flip pass -> fail at the same shape always fails;
    the zero-realloc gate survives a headline-shape change (the early
    headline return must not swallow it)."""
    r1 = _bench_rec(1000.0)
    r1["resident_ab"] = _ra_block()
    r2 = _bench_rec(1000.0)
    r2["resident_ab"] = _ra_block(passed=False)
    f1 = _write(tmp_path, "BENCH_r01.json", r1)
    f2 = _write(tmp_path, "BENCH_r02.json", r2)
    assert TREND.main([f1, f2]) == 2
    # headline shape change + an on-arm realloc: still gated
    r2b = _bench_rec(5000.0, entities=4096)
    r2b["resident_ab"] = _ra_block(on_realloc=2, passed=False)
    f2b = _write(tmp_path, "BENCH_r02.json", r2b)
    assert TREND.main([f1, f2b]) == 2
