"""Hot-standby replication (ISSUE 18): wire-frame CRC chaining and the
torn-stream taxonomy (truncated / corrupted / reordered / replayed
frames rejected WHOLE, stream self-heals at the next keyframe),
double-apply lattice-plane determinism, the bounded replication
worker's never-block-the-tick contract (slow disk -> loud drops +
keyframe collapse, never a stalled submit), standby apply/mirror
semantics into a live world, the kvreg promotion arbitration
(first-writer-wins + epoch guard — BOTH stale-claim race orders
refused), the byte-replayable decision log, and the ``/standby``
registry payloads."""

import threading
import time

import msgpack
import numpy as np
import pytest

from goworld_tpu import freeze
from goworld_tpu.replication.frames import (
    StreamDecoder,
    StreamEncoder,
    TornStreamError,
)
from goworld_tpu.replication.promote import (
    DecisionLog,
    adjudicate,
    claim_key,
    claim_value,
    parse_claim,
    replay_decisions,
)
from goworld_tpu.replication import standby as standby_mod
from goworld_tpu.replication.standby import StandbyApplier, StandbyTracker
from goworld_tpu.replication.worker import ReplicationWorker
from goworld_tpu.utils import audit, metrics

pytestmark = pytest.mark.replication


@pytest.fixture(autouse=True)
def _fresh_registries():
    metrics.REGISTRY.reset()
    standby_mod.reset()
    yield
    metrics.REGISTRY.reset()
    standby_mod.reset()


# =======================================================================
# a real primary world streaming real chain records
# =======================================================================
def _mk_world(game_id: int):
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.ops.aoi import GridSpec

    class Mob(Entity):
        ATTRS = {"hp": "allclients hot:0"}

    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=30.0, extent_x=200.0, extent_z=200.0),
        input_cap=64,
    )
    w = World(cfg, n_spaces=1, game_id=game_id)
    w.register_entity("Mob", Mob)
    w.register_space("Arena", Space)
    return w


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    """(primary_world, ents, frames) — frames is the encoded wire
    stream: 9 records at keyframe_every=4 (keys at indices 0, 4, 8),
    with deterministic pose churn between captures so the deltas carry
    real sparse rows."""
    from goworld_tpu.entity.entity import GameClient

    d = tmp_path_factory.mktemp("repl_chain")
    w = _mk_world(941)
    w.create_nil_space()
    sp = w.create_space("Arena")
    rng = np.random.default_rng(7)
    ents = []
    for i in range(10):
        x, z = rng.uniform(20.0, 180.0, 2)
        e = sp.create_entity("Mob", pos=(float(x), 0.0, float(z)))
        e.attrs["hp"] = i
        ents.append(e)
    ents[0].set_client(GameClient(1, "repl-c0", w))

    chain = freeze.SnapshotChain(w, str(d), keyframe_every=4)
    enc = StreamEncoder()
    frames = []  # (kind, tick, blob)
    for t in range(9):
        for e in ents:
            if e.destroyed:
                continue
            x, z = rng.uniform(20.0, 180.0, 2)
            w.stage_pose(e, (float(x), 0.0, float(z)),
                         yaw=float(rng.uniform(0, 6.28)))
        w.tick()
        data, tick = freeze.SnapshotChain.complete_capture(
            chain.capture())
        kind, rec = chain.build(data)
        frames.append((kind, tick, enc.encode(tick, kind, rec)))
    assert [k for k, _t, _b in frames].count("key") == 3
    yield w, ents, frames, chain, enc
    audit.unregister("game941")
    if w.audit is not None:
        w.audit.close()


def _tamper(blob: bytes, **patch) -> bytes:
    fr = msgpack.unpackb(blob, raw=False, strict_map_key=False)
    fr.update(patch)
    return msgpack.packb(fr, use_bin_type=True)


# =======================================================================
# stream determinism: double apply -> bit-identical lattice planes
# =======================================================================
def test_double_apply_is_bit_identical(stream):
    _w, _e, frames, _c, _enc = stream
    d1, d2 = StreamDecoder(), StreamDecoder()
    for _kind, _tick, blob in frames:
        k1, t1, _data1, planes1, eids1 = d1.feed(blob)
        k2, t2, _data2, planes2, eids2 = d2.feed(blob)
        assert (k1, t1, eids1) == (k2, t2, eids2)
        assert set(planes1) == {"pos_xz", "pos_y", "yaw", "moving"}
        for nm in planes1:  # the lattice-domain byte surface
            assert planes1[nm] == planes2[nm], nm
    assert d1.applied_seq == d2.applied_seq == len(frames) - 1


def test_delta_resolves_to_keyframe_identical_planes(stream):
    """A delta whose rows all reference the keyframe must reproduce the
    keyframe's planes byte-for-byte for the unchanged entities — the
    lattice-domain bit-exactness guarantee of the disk chain carried
    onto the wire."""
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    key_planes = None
    for kind, _t, blob in frames[:2]:
        k, _tick, _data, planes, eids = dec.feed(blob)
        if k == "key":
            key_planes = (planes, eids)
    planes, eids = key_planes
    assert planes["pos_xz"]  # non-empty population


# =======================================================================
# torn streams: rejected whole, named reason, heals at next keyframe
# =======================================================================
def test_truncated_frame_rejected(stream):
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    with pytest.raises(TornStreamError) as ei:
        dec.feed(frames[0][2][:-5])
    assert ei.value.reason == "unparseable"
    assert dec.needs_keyframe


def test_body_crc_corruption_rejected(stream):
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    fr = msgpack.unpackb(frames[0][2], raw=False, strict_map_key=False)
    body = bytearray(fr["body"])
    body[len(body) // 2] ^= 0x5A
    with pytest.raises(TornStreamError) as ei:
        dec.feed(_tamper(frames[0][2], body=bytes(body)))
    assert ei.value.reason == "body_crc"
    assert dec.needs_keyframe


def test_reordered_delta_rejected_as_seq_gap(stream):
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    dec.feed(frames[0][2])
    with pytest.raises(TornStreamError) as ei:
        dec.feed(frames[2][2])  # skipped frames[1]
    assert ei.value.reason == "seq_gap"


def test_chain_break_on_wrong_prev_crc(stream):
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    dec.feed(frames[0][2])
    fr = msgpack.unpackb(frames[1][2], raw=False, strict_map_key=False)
    with pytest.raises(TornStreamError) as ei:
        dec.feed(_tamper(frames[1][2],
                         prev_crc=fr["prev_crc"] ^ 1))
    assert ei.value.reason == "chain_break"


def test_replayed_old_keyframe_rejected_stale(stream):
    """A replayed/reordered OLD keyframe must never roll the mirror
    backward behind frames already applied."""
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    for _k, _t, blob in frames[:6]:
        dec.feed(blob)
    for old in (frames[0], frames[4]):  # both earlier keys
        with pytest.raises(TornStreamError) as ei:
            dec.feed(old[2])
        assert ei.value.reason == "stale_keyframe"


def test_torn_stream_heals_at_next_keyframe(stream):
    _w, _e, frames, _c, _enc = stream
    dec = StreamDecoder()
    dec.feed(frames[0][2])
    dec.feed(frames[1][2])
    with pytest.raises(TornStreamError):
        dec.feed(frames[2][2][:-9])       # torn mid-stream
    with pytest.raises(TornStreamError) as ei:
        dec.feed(frames[3][2])            # deltas can't re-anchor
    assert ei.value.reason == "awaiting_keyframe"
    kind, _tick, _data, _planes, _eids = dec.feed(frames[4][2])
    assert kind == "key"                  # heals at the next keyframe
    assert not dec.needs_keyframe
    dec.feed(frames[5][2])                # and the chain continues
    assert dec.applied_seq == 5


# =======================================================================
# standby apply: live-world mirror, census equality, quiet destroy
# =======================================================================
def test_applier_mirrors_census_and_destroys(stream):
    w, ents, frames, chain, enc = stream
    sb = _mk_world(942)
    tracker = StandbyTracker(942, 941, tick_hz=60.0)
    ap = StandbyApplier(sb, 941, tracker=tracker)
    for _k, _t, blob in frames:
        out = ap.apply(blob)
        assert out["ok"], out
    def census(world):
        out = {e.id for e in world.entities.values() if not e.destroyed}
        out.discard(world.nil_space.id)
        return out
    assert census(sb) == census(w)
    # attrs + client binding mirrored
    src = ents[0]
    mir = sb.entities[src.id]
    assert mir.attrs.get_int("hp") == src.attrs.get_int("hp")
    assert mir.client is not None
    assert (mir.client.gate_id, mir.client.client_id) == (1, "repl-c0")
    # a standby has no client sink: mirror-side client messages must
    # not pile up in the fallback buffer
    assert sb.client_messages == []

    # primary destroys one entity; the next frame quiet-destroys the
    # mirror copy — and the ledger verdict still balances
    victim = ents[3]
    w.destroy_entity(victim)
    w.tick()
    data, tick = freeze.SnapshotChain.complete_capture(chain.capture())
    kind, rec = chain.build(data)
    out = ap.apply(enc.encode(tick, kind, rec))
    assert out["ok"], out
    assert victim.id not in census(sb)
    assert census(sb) == census(w)
    snap = tracker.snapshot()
    assert snap["frames"] == len(frames) + 1
    assert snap["rejects"] == {}
    if sb.audit is not None:
        sb.audit.drain()
        v = audit.conservation_verdict(
            [sb.audit.snapshot(tick=sb.tick_count)])
        assert v["ok"], v["problems"]
    audit.unregister("game942")


def test_applier_reject_changes_nothing(stream):
    _w, _e, frames, _c, _enc = stream
    sb = _mk_world(943)
    tracker = StandbyTracker(943, 941, tick_hz=60.0)
    ap = StandbyApplier(sb, 941, tracker=tracker)
    out = ap.apply(frames[0][2][:-3])
    assert out == {"ok": False, "reason": "unparseable",
                   "needs_keyframe": True}
    assert len(sb.entities) == 0          # nothing half-applied
    assert tracker.snapshot()["rejects"] == {"unparseable": 1}
    audit.unregister("game943")


# =======================================================================
# the bounded worker: slow disk NEVER blocks the tick thread
# =======================================================================
class StubChain:
    """Chain stand-in: records every build's force_key flag; disk
    writes can be made arbitrarily slow; builds can be made to fail."""

    def __init__(self, write_delay: float = 0.0, fail_builds: int = 0):
        self.write_delay = write_delay
        self.fail_builds = fail_builds
        self.force_flags: list[bool] = []
        self.writes = 0
        self._built = 0
        self._lock = threading.Lock()

    def complete_capture(self, captured):
        return {"n": int(captured)}, int(captured)

    def build(self, data, force_key: bool = False):
        with self._lock:
            if self.fail_builds > 0:
                self.fail_builds -= 1
                raise RuntimeError("deliberate build failure")
            self.force_flags.append(bool(force_key))
            self._built += 1
            kind = "key" if force_key or self._built == 1 else "delta"
        return kind, {"tick": data["n"]}

    def write_record(self, kind, rec):
        if self.write_delay:
            time.sleep(self.write_delay)
        with self._lock:
            self.writes += 1
        return "unused"


def test_slow_disk_never_blocks_submit():
    """The PR-12 tradeoff retired: a wedged/slow disk costs DROPS (loud
    counter + keyframe collapse), never a stalled tick thread."""
    chain = StubChain(write_delay=0.25)
    sent = []
    worker = ReplicationWorker(
        chain, game_id=51, queue_max=2,
        send_fn=lambda blob, kind, tick: sent.append(kind))
    try:
        worst = 0.0
        accepted = 0
        builds_at_first_drop = None
        for i in range(10):
            t0 = time.perf_counter()
            if worker.submit(i, to_disk=True, to_stream=True):
                accepted += 1
            elif builds_at_first_drop is None:
                builds_at_first_drop = len(chain.force_flags)
            worst = max(worst, time.perf_counter() - t0)
        assert worst < 0.05, f"submit blocked {worst * 1e3:.1f} ms"
        assert worker.dropped_total() > 0          # loud, not silent
        assert accepted + worker.dropped_total() == 10
        assert worker.drain(timeout=30.0)
        assert chain.writes == accepted
        assert len(sent) == accepted
        # backlog collapse: a drop arms force_keyframe, so a capture
        # accepted after the drop re-anchors the stream with a full
        # keyframe instead of wedging the consumer on unbounded deltas
        chain.write_delay = 0.0
        assert worker.submit(99)
        assert worker.drain(timeout=10.0)
        assert any(chain.force_flags[builds_at_first_drop:]), \
            (builds_at_first_drop, chain.force_flags)
    finally:
        worker.close()


def test_request_keyframe_forces_next_build():
    chain = StubChain()
    worker = ReplicationWorker(chain, game_id=52, queue_max=4,
                               send_fn=lambda *a: None)
    try:
        worker.submit(1)
        assert worker.drain()
        worker.request_keyframe()           # standby attach / resync
        worker.submit(2)
        assert worker.drain()
        assert chain.force_flags == [False, True]
    finally:
        worker.close()


def test_worker_survives_build_failure():
    chain = StubChain(fail_builds=1)
    worker = ReplicationWorker(chain, game_id=53, queue_max=4,
                               send_fn=lambda *a: None)
    try:
        worker.submit(1)
        worker.submit(2)
        assert worker.drain()
        assert worker.errors == 1
        # the job after a failure is processed AND forced to a keyframe
        assert chain.force_flags == [True]
        assert worker.stats()["frames_sent"] == 1
    finally:
        worker.close()


def test_worker_rejects_zero_queue():
    with pytest.raises(ValueError):
        ReplicationWorker(StubChain(), game_id=54, queue_max=0)


# =======================================================================
# promotion arbitration: both stale-claim race orders refused
# =======================================================================
def _kvreg():
    """The dispatcher's exact first-writer-wins register semantics
    (net/dispatcher.py _h_kvreg) over a local dict."""
    reg: dict = {}

    def register(key, val, force=False):
        if key not in reg or force:
            reg[key] = val
        return reg[key]

    return reg, register


def test_claim_value_roundtrip():
    v = claim_value(4, 3, 77)
    assert parse_claim(v) == {"gid": 4, "epoch": 3, "seq": 77}
    assert parse_claim("garbage") is None
    assert parse_claim("gameX:eY:sZ") is None
    assert claim_key(9) == "promote/game9"


def test_stale_claim_second_is_refused():
    """Race order A: the live standby registers first; a replayed old
    claim (or zombie) lands after. First-writer-wins broadcasts the
    live winner; the zombie adjudicates lost — and the live claim
    adjudicates won against its own broadcast."""
    reg, register = _kvreg()
    key = claim_key(1)
    live = claim_value(2, epoch=3, frame_seq=90)
    stale = claim_value(9, epoch=1, frame_seq=10)
    assert adjudicate(register(key, live), live) == "won"
    assert adjudicate(register(key, stale), stale) == "lost"
    assert reg[key] == live                    # never overwritten


def test_stale_claim_first_is_refused():
    """Race order B: the replay lands FIRST. The live claimant sees a
    winner with a LOWER epoch — stale_winner — which licenses a
    force re-register exactly and only then; the zombie then loses the
    re-adjudication."""
    reg, register = _kvreg()
    key = claim_key(1)
    stale = claim_value(9, epoch=1, frame_seq=10)
    live = claim_value(2, epoch=3, frame_seq=90)
    register(key, stale)                       # zombie lands first
    assert adjudicate(register(key, live), live) == "stale_winner"
    assert adjudicate(register(key, live, force=True), live) == "won"
    assert adjudicate(reg[key], stale) == "lost"


def test_equal_epoch_loser_stands_down():
    """Two live standbys racing the SAME epoch: exactly one wins; the
    other adjudicates lost (never stale_winner — that would force-loop
    both forever)."""
    _reg, register = _kvreg()
    key = claim_key(1)
    a = claim_value(2, epoch=3, frame_seq=90)
    b = claim_value(5, epoch=3, frame_seq=88)
    assert adjudicate(register(key, a), a) == "won"
    assert adjudicate(register(key, b), b) == "lost"


def test_decision_log_replays_byte_for_byte():
    log = DecisionLog()
    log.note("claim", key="promote/game1", value="game2:e1:s9",
             epoch=1, applied_seq=9, applied_tick=40)
    log.note("adjudicate", winner="game2:e1:s9", mine="game2:e1:s9",
             verdict="won")
    log.note("promoted", epoch=1, tick=40, seq=9, entities=12)
    dump = log.dump()
    assert replay_decisions(log.inputs) == dump
    assert dump.endswith(b"\n")
    # field order in a line is canonical (sorted), independent of the
    # kwargs order the caller used
    other = DecisionLog()
    other.note("claim", applied_tick=40, applied_seq=9, epoch=1,
               value="game2:e1:s9", key="promote/game1")
    assert other.lines[0] == log.lines[0]


# =======================================================================
# /standby registry
# =======================================================================
def test_standby_registry_and_promotion_hook():
    clock = [100.0]
    tr = StandbyTracker(6, 5, tick_hz=10.0, lag_budget_ticks=4,
                        clock=lambda: clock[0])
    standby_mod.register("game6", tr)
    tr.note_applied("key", tick=7, seq=0, nbytes=900, apply_ms=1.5)
    clock[0] += 0.2                       # 2 ticks of staleness
    snap = standby_mod.snapshot_all()["game6"]
    assert snap["role"] == "standby"
    assert snap["applied_tick"] == 7
    assert snap["lag_ticks"] == 2.0
    assert snap["pass"] is True
    clock[0] += 1.0                       # blow the budget
    assert standby_mod.snapshot_all()["game6"]["pass"] is False

    calls = []
    tr.on_promote = lambda epoch=None: calls.append(epoch) or \
        {"status": "claiming"}
    out = standby_mod.request_promotion(epoch=9)
    assert out == {"standby": "game6", "status": "claiming"}
    assert calls == [9]
    standby_mod.unregister("game6")
    assert "error" in standby_mod.snapshot_all()   # honest when empty
    assert "error" in standby_mod.request_promotion()
