"""tools/ci_gate.py — the one-command pre-merge gate, in tier-1
(jax-free).

The contract under test:

* the REAL repo is green through all three chained gates (obs_lint +
  bench_schema + bench_trend) — this test IS the pre-merge check;
* a single failing gate turns the whole chain non-zero (drift can
  never ride through on a green neighbour);
* an unimportable gate counts as FAILED, never silently skipped.
"""

import importlib.util
import os

import pytest

pytestmark = pytest.mark.rebalance

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "ci_gate_under_test",
    os.path.join(REPO, "tools", "ci_gate.py"))
GATE = importlib.util.module_from_spec(spec)
spec.loader.exec_module(GATE)


def test_gate_order_is_the_documented_chain():
    assert GATE.GATES == ("obs_lint", "bench_schema", "bench_trend")


def test_real_repo_is_green(capsys):
    assert GATE.main([]) == 0
    out = capsys.readouterr().out
    # every gate actually ran (no silent skip) and the verdict printed
    for name in GATE.GATES:
        assert f"== {name} ==" in out
    assert "ci_gate: ok (3 gates green)" in out


def test_threshold_is_forwarded_to_bench_trend_only(monkeypatch):
    seen = {}

    class _Fake:
        def __init__(self, name):
            self.name = name

        def main(self, argv):
            seen[self.name] = list(argv)
            return 0

    monkeypatch.setattr(
        GATE.importlib, "import_module", lambda n: _Fake(n))
    assert GATE.main(["--threshold", "0.25"]) == 0
    assert seen["obs_lint"] == []
    assert seen["bench_schema"] == []
    assert seen["bench_trend"] == ["--threshold", "0.25"]


def test_one_failing_gate_fails_the_chain(monkeypatch, capsys):
    class _Fake:
        def __init__(self, name):
            self.name = name

        def main(self, argv):
            return 2 if self.name == "bench_schema" else 0

    monkeypatch.setattr(
        GATE.importlib, "import_module", lambda n: _Fake(n))
    assert GATE.main([]) == 2
    assert "bench_schema (rc=2)" in capsys.readouterr().out


def test_unimportable_gate_is_a_failure_not_a_skip(monkeypatch,
                                                   capsys):
    def _boom(name):
        if name == "bench_trend":
            raise ImportError("gate deleted")

        class _Ok:
            @staticmethod
            def main(argv):
                return 0

        return _Ok

    monkeypatch.setattr(GATE.importlib, "import_module", _boom)
    assert GATE.main([]) == 2
    out = capsys.readouterr().out
    assert "bench_trend: import failed" in out
    assert "bench_trend (rc=-1)" in out
