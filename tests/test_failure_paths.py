"""Failure-path parity (VERDICT #10): dispatcher cleanup when a game
dies (DispatcherService.go:586-634), gate self-termination on dispatcher
loss (gate.go:137-143), and the bot's view of both."""

import asyncio
import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net.botclient import BotClient
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec


class Account(Entity):
    def OnClientConnected(self):
        avatar = self.world.create_entity(
            "Avatar", space=self.world._arena, pos=(50.0, 0.0, 50.0)
        )
        avatar.attrs["name"] = "n"
        self.give_client_to(avatar)
        self.destroy()


class Avatar(Entity):
    ATTRS = {"name": "allclients"}


class Arena(Space):
    pass


def _make_world():
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=30.0, extent_x=200.0, extent_z=200.0,
                      k=16, cell_cap=32, row_block=64),
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Account", Account)
    w.register_entity("Avatar", Avatar)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    w._arena = w.create_space("Arena")
    return w


def _start_game(harness, game_id=1):
    w = _make_world()
    gs = GameServer(game_id, w, list(harness.dispatcher_addrs),
                    boot_entity="Account")
    gs.start_network()
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            gs.pump()
            gs.tick()
            time.sleep(0.01)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return w, gs, stop, t


def test_game_death_cleans_dispatcher_and_detaches_bot():
    """Kill a game (hard stop, no freeze): every dispatcher must drop the
    game's entity routes and broadcast NOTIFY_GAME_DISCONNECTED; a
    connected bot keeps its gate connection but its entities go silent."""
    harness = ClusterHarness(n_dispatchers=2, n_gates=1, desired_games=1)
    harness.start()
    stop = t = gs = None
    try:
        w, gs, stop, t = _start_game(harness)
        assert gs.ready_event.wait(20)
        host, port = harness.gate_addrs[0]
        bot = BotClient(host, port, strict=True, move_interval=0.1)
        bot_fut = harness.submit(bot.run(30.0))
        deadline = time.monotonic() + 30  # generous: full-suite runs saturate the box
        while bot.player is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert bot.player is not None and bot.player.type_name == "Avatar"

        routed = sum(
            1 for d in harness.dispatchers
            for info in d.entities.values() if info.game_id == 1
        )
        assert routed > 0, "dispatchers never learned the game's entities"

        # hard-kill the game (crash: no freeze handshake)
        stop.set()
        t.join(timeout=5)
        gs.stop()
        stop = t = gs = None

        deadline = time.monotonic() + 30  # generous: full-suite runs saturate the box
        while time.monotonic() < deadline:
            leftover = sum(
                1 for d in harness.dispatchers
                for info in d.entities.values() if info.game_id == 1
            )
            if leftover == 0:
                break
            time.sleep(0.1)
        assert leftover == 0, (
            f"{leftover} stale entity routes survived the game's death"
        )

        # bot is detached from the dead game: no further syncs arrive
        time.sleep(0.5)
        syncs = bot.sync_count
        time.sleep(1.0)
        assert bot.sync_count == syncs, "syncs from a dead game"
        bot._stop = True
        bot_fut.cancel()
    finally:
        if stop is not None:
            stop.set()
        if t is not None:
            t.join(timeout=5)
        if gs is not None:
            gs.stop()
        harness.stop()


def test_game_death_while_frozen_keeps_routes():
    """A game that died FREEZING keeps its routes and queues packets for
    the restore (reference :602-607) — the opposite of the crash path."""
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        harness = ClusterHarness(n_dispatchers=1, n_gates=0,
                                 desired_games=1)
        harness.start()
        try:
            w = _make_world()
            gs = GameServer(1, w, list(harness.dispatcher_addrs),
                            freeze_dir=tmp)
            gs.start_network()
            stop = threading.Event()

            def drive():
                while not stop.is_set() and gs.run_state == "running":
                    gs.pump()
                    gs.tick()
                    time.sleep(0.01)
                if gs.run_state == "freezing":
                    gs._do_freeze()

            t = threading.Thread(target=drive, daemon=True)
            t.start()
            assert gs.ready_event.wait(20)
            npc = w.create_entity("Avatar", space=w._arena,
                                  pos=(1.0, 0.0, 1.0))
            time.sleep(0.3)
            gs.request_freeze()
            deadline = time.monotonic() + 15
            while gs.run_state != "frozen" and time.monotonic() < deadline:
                time.sleep(0.05)
            assert gs.run_state == "frozen"
            stop.set()
            t.join(timeout=5)
            gs.stop()
            time.sleep(0.5)
            d = harness.dispatchers[0]
            gi = d.games.get(1)
            assert gi is not None and gi.blocked, \
                "frozen game lost its blocked state on disconnect"
            assert any(
                info.game_id == 1 for info in d.entities.values()
            ), "frozen game's entity routes were dropped"
        finally:
            harness.stop()


def test_gate_exits_on_dispatcher_loss():
    """Reference gate.go:137-143: a gate that loses a dispatcher kills
    itself (clients would be routing into a black hole)."""
    harness = ClusterHarness(
        n_dispatchers=1, n_gates=1, desired_games=0,
        gate_exit_on_dispatcher_loss=True,
    )
    harness.start()
    try:
        gate = harness.gates[0]
        assert not gate.terminated.is_set()

        harness.submit(harness.dispatchers[0].kill()).result(timeout=10)

        async def wait_term():
            await asyncio.wait_for(gate.terminated.wait(), 15)
            return True

        assert harness.submit(wait_term()).result(timeout=20), \
            "gate did not self-terminate after dispatcher loss"
    finally:
        harness.stop()
