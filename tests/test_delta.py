"""Interest delta (enter/leave) kernel vs python set difference.

Reference semantics: OnEnterAOI/OnLeaveAOI pair events, Entity.go:227-246."""

import jax.numpy as jnp
import numpy as np

from goworld_tpu.ops.delta import interest_delta, masked_pairs


def make_rows(rng, n, k):
    """Random sorted sentinel-padded neighbor rows."""
    rows = np.full((n, k), n, np.int32)
    for i in range(n):
        cnt = rng.integers(0, k + 1)
        vals = rng.choice(n, size=cnt, replace=False)
        rows[i, :cnt] = np.sort(vals)
    return rows


def test_delta_matches_sets():
    rng = np.random.default_rng(0)
    n, k = 50, 8
    old = make_rows(rng, n, k)
    new = make_rows(rng, n, k)
    enter_mask, leave_mask = interest_delta(
        jnp.asarray(old), jnp.asarray(new), n
    )
    enter_mask, leave_mask = np.asarray(enter_mask), np.asarray(leave_mask)
    for i in range(n):
        so, sn = set(old[i][old[i] < n]), set(new[i][new[i] < n])
        got_enter = set(new[i][enter_mask[i]].tolist())
        got_leave = set(old[i][leave_mask[i]].tolist())
        assert got_enter == sn - so
        assert got_leave == so - sn


def test_no_delta_when_equal():
    rng = np.random.default_rng(1)
    rows = make_rows(rng, 20, 6)
    e, l = interest_delta(jnp.asarray(rows), jnp.asarray(rows), 20)
    assert not np.asarray(e).any()
    assert not np.asarray(l).any()


def test_masked_pairs_extraction():
    mask = np.zeros((4, 3), bool)
    vals = np.arange(12, dtype=np.int32).reshape(4, 3)
    mask[1, 2] = mask[3, 0] = True
    w, j, cnt = masked_pairs(jnp.asarray(mask), jnp.asarray(vals), 8)
    w, j = np.asarray(w), np.asarray(j)
    assert int(cnt) == 2
    pairs = {(int(w[i]), int(j[i])) for i in range(2)}
    assert pairs == {(1, 5), (3, 9)}
    assert (w[2:] == -1).all() and (j[2:] == -1).all()


def test_masked_pairs_overflow_reports_true_count():
    mask = np.ones((4, 4), bool)
    vals = np.zeros((4, 4), np.int32)
    w, j, cnt = masked_pairs(jnp.asarray(mask), jnp.asarray(vals), 5)
    assert int(cnt) == 16      # true demand
    assert (np.asarray(w) >= 0).sum() == 5  # only cap extracted


def test_interest_pairs_matches_masked_pairs():
    from goworld_tpu.ops.delta import interest_pairs

    rng = np.random.default_rng(11)
    n, k, sentinel = 120, 6, 120
    def rand_lists():
        out = np.full((n, k), sentinel, np.int32)
        for i in range(n):
            cnt = rng.integers(0, k + 1)
            ids = rng.choice(n, size=cnt, replace=False)
            out[i, :cnt] = np.sort(ids)
        return out
    old = rand_lists()
    new = old.copy()
    touched = rng.uniform(size=n) < 0.3          # most rows unchanged
    new[touched] = rand_lists()[touched]
    em, lm = interest_delta(jnp.asarray(old), jnp.asarray(new), sentinel)
    ew0, ej0, en0 = masked_pairs(em, jnp.asarray(new), 64)
    lw0, lj0, ln0 = masked_pairs(lm, jnp.asarray(old), 64)
    ew, ej, en, lw, lj, ln, drn = interest_pairs(
        jnp.asarray(old), jnp.asarray(new), sentinel, 64, 64, n
    )
    assert int(drn) == int((old != new).any(axis=1).sum())
    np.testing.assert_array_equal(np.asarray(ew0), np.asarray(ew))
    np.testing.assert_array_equal(np.asarray(ej0), np.asarray(ej))
    np.testing.assert_array_equal(np.asarray(lw0), np.asarray(lw))
    np.testing.assert_array_equal(np.asarray(lj0), np.asarray(lj))
    assert int(en0) == int(en) and int(ln0) == int(ln)


def test_interest_pairs_row_overflow_saturates_counts():
    from goworld_tpu.ops.delta import interest_pairs

    n, k, sentinel = 16, 2, 16
    old = np.full((n, k), sentinel, np.int32)
    new = old.copy()
    new[:, 0] = (np.arange(n) + 1) % n           # every row changes
    ew, ej, en, lw, lj, ln, drn = interest_pairs(
        jnp.asarray(old), jnp.asarray(new), sentinel, 4, 4, 8
    )
    assert int(drn) == n  # true changed-row demand = the row-cap alarm
    # pair counts are TRUE demand within the 8 selected rows (one enter
    # each), never fabricated; the extraction itself is capped at 4
    assert int(en) == 8
    assert int((np.asarray(ew) >= 0).sum()) == 4
