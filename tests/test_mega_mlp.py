"""Neighbor-aware MLP behavior in megaspace (VERDICT #8): the policy's
observation includes neighbor features computed over the local+ghost
block, so NPC behavior reacts to entities across tile borders (BASELINE
config 5 sharded)."""

import jax
import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig, spawn
from goworld_tpu.models.npc_policy import init_policy
from goworld_tpu.ops.aoi import GridSpec
from goworld_tpu.parallel import MegaConfig, MultiTickInputs, make_mesh
from goworld_tpu.parallel.megaspace import create_mega_state, make_mega_tick
from goworld_tpu.parallel.mesh import shard_state

N_DEV = 8
TILE_W = 100.0
RADIUS = 10.0


def _mega(behavior="mlp", capacity=16):
    cfg = WorldConfig(
        capacity=capacity,
        grid=GridSpec(radius=RADIUS, extent_x=TILE_W + 2 * RADIUS,
                      extent_z=100.0, k=8, cell_cap=16,
                      row_block=capacity),
        behavior=behavior,
        npc_speed=5.0,
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mc = MegaConfig(cfg=cfg, n_dev=N_DEV, tile_w=TILE_W,
                    halo_cap=8, migrate_cap=4)
    mesh = make_mesh(N_DEV)
    step = make_mega_tick(mc, mesh)
    st = create_mega_state(mc)
    return cfg, mc, mesh, step, st


def _spawn_on(st, dev, slot, **kw):
    one = jax.tree.map(lambda x: x[dev], st)
    one = spawn(one, slot, **kw)
    return jax.tree.map(lambda full, new: full.at[dev].set(new), st, one)


def test_mega_neighbor_features_cross_border():
    """An entity near the border must see ghosts from the adjacent tile
    in its mean-offset feature."""
    cfg, mc, mesh, step, st = _mega()
    # watcher on tile 0 at x=98; three neighbors on tile 1 at x=103
    st = _spawn_on(st, 0, 0, pos=(98.0, 0.0, 50.0))
    for s, z in ((0, 48.0), (1, 50.0), (2, 52.0)):
        st = _spawn_on(st, 1, s, pos=(103.0, 0.0, z))
    st = shard_state(st, mesh)
    policy = init_policy(jax.random.PRNGKey(0))
    st, out = step(st, MultiTickInputs.empty(cfg, N_DEV), policy)
    jax.block_until_ready(st)
    cnt = np.asarray(st.nbr_cnt)
    moff = np.asarray(st.nbr_mean_off)
    assert cnt[0, 0] == 3, f"watcher sees {cnt[0, 0]} ghosts, want 3"
    # mean offset points across the border: +5 in x, 0 in z
    np.testing.assert_allclose(moff[0, 0], [5.0, 0.0, 0.0], atol=1e-4)
    # tile-1 slot 0 at (103,48) sees watcher(98,50) + (103,50) + (103,52):
    # mean z offset = (2 + 2 + 4) / 3
    assert cnt[1, 0] == 3
    np.testing.assert_allclose(moff[1, 0, 2], 8.0 / 3.0, atol=1e-4)


def test_mega_mlp_reacts_to_cross_border_neighbors():
    """Same entity, same seed: its velocity after two ticks must DIFFER
    when a neighbor cluster sits across the border — proof the policy
    consumes the neighbor features, not a neighbor-blind observation."""
    policy = init_policy(jax.random.PRNGKey(0))

    def run(with_cluster: bool):
        cfg, mc, mesh, step, st = _mega()
        st = _spawn_on(st, 0, 0, pos=(98.0, 0.0, 50.0), npc_moving=True)
        if with_cluster:
            for s, z in ((0, 48.0), (1, 50.0), (2, 52.0)):
                st = _spawn_on(st, 1, s, pos=(103.0, 0.0, z))
        st = shard_state(st, mesh)
        inputs = MultiTickInputs.empty(cfg, N_DEV)
        for _ in range(2):  # tick 1 computes features; tick 2 uses them
            st, _ = step(st, inputs, policy)
        jax.block_until_ready(st)
        return np.asarray(st.vel)[0, 0]

    v_alone = run(False)
    v_crowded = run(True)
    assert not np.allclose(v_alone, v_crowded, atol=1e-6), (
        f"velocity identical with and without cross-border neighbors: "
        f"{v_alone} == {v_crowded} — observation is neighbor-blind"
    )


def test_single_space_mlp_unchanged():
    """The single-space MLP path still builds its observation from the
    prev-tick local neighbor lists (regression guard for the refactor)."""
    from goworld_tpu.core.state import create_state
    from goworld_tpu.core.step import TickInputs, make_tick

    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=32),
        behavior="mlp",
        enter_cap=64, leave_cap=64, sync_cap=64,
    )
    st = create_state(cfg)
    st = spawn(st, 0, pos=(50.0, 0.0, 50.0), npc_moving=True)
    st = spawn(st, 1, pos=(53.0, 0.0, 50.0))
    tick = make_tick(cfg)
    policy = init_policy(jax.random.PRNGKey(0))
    inputs = TickInputs.empty(cfg)
    for _ in range(2):
        st, out = tick(st, inputs, policy)
    jax.block_until_ready(st)
    assert int(np.asarray(st.nbr_cnt)[0]) == 1
    assert np.abs(np.asarray(st.vel)[0]).sum() > 0
