"""Sync-record collection kernel vs oracle (CollectEntitySyncInfos analog,
Entity.go:1208-1267: records only for dirty subjects seen by client-owning
watchers)."""

import jax.numpy as jnp
import numpy as np

from goworld_tpu.ops.sync import collect_attr_deltas, collect_sync


def test_collect_sync_oracle():
    rng = np.random.default_rng(0)
    n, k = 40, 6
    nbr = np.full((n, k), n, np.int32)
    for i in range(n):
        cnt = rng.integers(0, k + 1)
        nbr[i, :cnt] = np.sort(rng.choice(n, cnt, replace=False))
    dirty = rng.uniform(size=n) < 0.3
    has_client = rng.uniform(size=n) < 0.4
    pos = rng.uniform(0, 100, (n, 3)).astype(np.float32)
    yaw = rng.uniform(0, 6.28, n).astype(np.float32)

    w, j, vals, cnt = collect_sync(
        jnp.asarray(nbr), jnp.asarray(dirty), jnp.asarray(has_client),
        jnp.asarray(pos), jnp.asarray(yaw), 256,
    )
    w, j, vals = np.asarray(w), np.asarray(j), np.asarray(vals)

    expect = set()
    for i in range(n):
        if not has_client[i]:
            continue
        for x in nbr[i][nbr[i] < n]:
            if dirty[x]:
                expect.add((i, int(x)))
    got = {(int(w[r]), int(j[r])) for r in range(int(cnt))}
    assert got == expect
    for r in range(int(cnt)):
        assert np.allclose(vals[r, :3], pos[j[r]])
        assert np.isclose(vals[r, 3], yaw[j[r]])


def test_collect_attr_deltas():
    n, a = 10, 5
    attrs = np.arange(n * a, dtype=np.float32).reshape(n, a)
    dirty = np.zeros(n, np.uint32)
    dirty[2] = 0b00101  # attrs 0, 2
    dirty[7] = 0b10000  # attr 4
    e, i, v, cnt = collect_attr_deltas(
        jnp.asarray(attrs), jnp.asarray(dirty), 16
    )
    e, i, v = np.asarray(e), np.asarray(i), np.asarray(v)
    assert int(cnt) == 3
    got = {(int(e[r]), int(i[r]), float(v[r])) for r in range(3)}
    assert got == {(2, 0, attrs[2, 0]), (2, 2, attrs[2, 2]), (7, 4, attrs[7, 4])}
