"""Compile-only smoke over EVERY bench autotune candidate at tiny N —
including the BENCH_AUTOTUNE_DIAG set — so kernel variants cannot
silently rot between relay windows (a candidate that stops compiling
would otherwise only be discovered mid-bench on scarce TPU time, where
autotune's try/except hides it as a fallback-to-default).
"""

import importlib.util
import os

import numpy as np
import pytest

import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_smoke_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BENCH = _load_bench()
N = 256
EXTENT = float(int((N * 10000 / 12) ** 0.5))


def _ids():
    return [
        ",".join(f"{k}={v}" for k, v in ov.items()) or "default"
        for _sel, ov in BENCH.AUTOTUNE_CANDIDATES
    ]


@pytest.mark.parametrize(
    "selectable,overrides", BENCH.AUTOTUNE_CANDIDATES, ids=_ids()
)
def test_autotune_candidate_builds_and_runs(selectable, overrides,
                                            monkeypatch):
    for var in BENCH.GRID_ENV.values():
        monkeypatch.delenv(var, raising=False)
    from goworld_tpu.ops.aoi import (
        GridSpec,
        grid_neighbors_flags,
        grid_neighbors_verlet,
        init_verlet_cache,
    )

    gk = BENCH._grid_kw_from_env(N, overrides)
    spec = GridSpec(radius=50.0, extent_x=EXTENT, extent_z=EXTENT, **gk)
    rng = np.random.default_rng(1)
    pos = np.zeros((N, 3), np.float32)
    pos[:, 0] = rng.random(N) * EXTENT
    pos[:, 2] = rng.random(N) * EXTENT
    alive = jnp.ones(N, bool)
    flags = jnp.asarray(rng.integers(0, 4, N).astype(np.int32))
    if spec.skin > 0:
        # the bench autotune harness exercises this exact path
        cache = init_verlet_cache(spec, N)
        nbr, cnt, fl, _s, cache, _rb, _sl = grid_neighbors_verlet(
            spec, jnp.asarray(pos), alive, cache, flag_bits=flags)
    else:
        nbr, cnt, fl = grid_neighbors_flags(
            spec, jnp.asarray(pos), alive, flag_bits=flags)
    assert nbr.shape == (N, spec.k)
    assert int(cnt.sum()) >= 0  # forces execution, not just tracing


def test_diag_set_is_covered():
    """The parametrization above must include the diagnostics (the
    BENCH_AUTOTUNE_DIAG=1 set), not just the selectable pool."""
    assert any(not sel for sel, _ in BENCH.AUTOTUNE_CANDIDATES)


def test_fused_rows_are_candidates():
    """The r6 fused back half must stay in the candidate pool — both
    the fused-over-argsort row and the full-Pallas pipeline (fused over
    the counting-sort front half) — so the parametrized smoke above
    keeps compiling them every tier-1 run."""
    impls = [(ov.get("sweep_impl"), ov.get("sort_impl"))
             for _sel, ov in BENCH.AUTOTUNE_CANDIDATES]
    assert ("fused", None) in impls
    assert ("fused", "counting") in impls


@pytest.mark.pallas
def test_lowered_counting_sort_compiles_at_bench_shape():
    """The serial kernel body — the real TPU lowering of the
    counting-sort fill pass (2D-tiled VMEM bins, no vector gathers) —
    must keep building at the autotune smoke shape, under interpret on
    CPU (the same body lowers on hardware). The autotune candidates
    only reach the "vector" interpret body off-TPU, so this is the
    tier-1 guard on the lowering itself."""
    from goworld_tpu.ops.sort import counting_sort_cells_pallas

    rng = np.random.default_rng(6)
    n_rows = 37
    srow = rng.integers(0, n_rows, N).astype(np.int32)
    ref = np.argsort(srow, kind="stable").astype(np.int32)
    order, sorted_row = counting_sort_cells_pallas(
        jnp.asarray(srow), n_rows, chunk=64, interpret=True,
        lowering="serial",
    )
    assert np.array_equal(np.asarray(order), ref)
    assert np.array_equal(np.asarray(sorted_row), srow[ref])
