"""From-scratch snappy codec (net/snappy.py + native/snappy_core.cpp).

The reference compresses gate<->client streams with snappy
(``ClientProxy.go:38-53``); round 5 replaces the documented zlib
deviation with a real implementation of the public block and framing
formats. No reference snappy library exists in this environment, so
correctness rests on: spec-derived known vectors (hand-encoded from
format_description.txt), the standard CRC32C test vector, format
property checks on the emitted bytes, and adversarial decoder inputs —
plus roundtrips at many shapes and split points.
"""

import os
import random

import pytest

from goworld_tpu.net import snappy


pytestmark = pytest.mark.skipif(
    not snappy.available(), reason="native snappy core failed to build")


CASES = [
    b"",
    b"a",
    b"ab" * 3,
    b"abcabcabcabcabcabc" * 100,       # short-period matches
    b"x" * 70000,                      # long run, >64KB literal span
    os.urandom(4096),                  # incompressible
    bytes(random.Random(7).choices(b"abcd", k=300000)),
]


@pytest.mark.parametrize("data", CASES, ids=[f"n{len(c)}" for c in CASES])
def test_block_roundtrip(data):
    blk = snappy.compress(data)
    assert snappy.uncompress(blk, max(len(data) + 16, 32)) == data


def test_block_known_vectors():
    # spec: varint(len) + literal tag ((len-1)<<2) + bytes
    assert snappy.compress(b"abc") == bytes([3, (3 - 1) << 2]) + b"abc"
    assert snappy.compress(b"") == b"\x00"
    # decode a hand-built stream using a copy element the encoder
    # wouldn't produce the same way: "abcd" + copy(offset=4, len=4)
    # copy1 tag: 01 | (len-4)<<2 | (offset>>8)<<5, then offset low byte
    src = bytes([8,                      # ulen = 8
                 (4 - 1) << 2]) + b"abcd" + bytes([
                 0b001 | ((4 - 4) << 2), 4])
    assert snappy.uncompress(src, 16) == b"abcdabcd"
    # copy2 form of the same
    src2 = bytes([8, (4 - 1) << 2]) + b"abcd" + bytes([
        0b010 | ((4 - 1) << 2), 4, 0])
    assert snappy.uncompress(src2, 16) == b"abcdabcd"


def test_overlapping_copy_replicates():
    # offset < len: snappy's RLE idiom — "a" then copy(offset=1, len=7)
    src = bytes([8, 0]) + b"a" + bytes([0b010 | ((7 - 1) << 2), 1, 0])
    assert snappy.uncompress(src, 16) == b"a" * 8


def test_malformed_blocks_rejected():
    for bad in (
        b"\x05\x00",                    # ulen 5 but one literal byte
        bytes([4, (4 - 1) << 2]) + b"ab",   # literal overruns input
        bytes([8, 0]) + b"a" + bytes([0b001, 9]),  # offset > written
        bytes([2, 0]) + b"a" + bytes([0b001, 0]),  # offset 0
        b"\xff\xff\xff\xff\xff",        # varint runs past end
    ):
        with pytest.raises(ValueError):
            snappy.uncompress(bad, 64)


def test_crc32c_standard_vector():
    assert snappy.crc32c(b"123456789") == 0xE3069283
    assert snappy.crc32c(b"") == 0


def test_stream_roundtrip_any_split():
    enc = snappy.StreamCompressor()
    dec = snappy.StreamDecompressor()
    wire = b"".join(enc.compress(c) for c in CASES)
    want = b"".join(CASES)
    got = b""
    rng = random.Random(3)
    i = 0
    while i < len(wire):
        j = min(len(wire), i + rng.randint(1, 1000))
        got += dec.decompress(wire[i:j])
        i = j
    assert got == want


def test_stream_layout_per_spec():
    enc = snappy.StreamCompressor()
    w = enc.compress(b"hello" * 100)
    # first chunk: stream identifier ff 06 00 00 "sNaPpY"
    assert w[:10] == b"\xff\x06\x00\x00sNaPpY"
    # next chunk: compressed (0x00) with 3-byte length then masked crc
    assert w[10] == 0x00
    body_len = w[11] | (w[12] << 8) | (w[13] << 16)
    assert len(w) == 10 + 4 + body_len
    # second call must NOT repeat the stream id
    w2 = enc.compress(b"hello")
    assert w2[0] in (0x00, 0x01)


def test_stream_corruption_detected():
    enc = snappy.StreamCompressor()
    w = bytearray(enc.compress(b"payload" * 50))
    w[-1] ^= 0xFF  # flip a data byte -> CRC mismatch
    with pytest.raises(ValueError):
        snappy.StreamDecompressor().decompress(bytes(w))


def test_stream_bomb_bound():
    # a 64KB zero block compresses to a few bytes; feed many chunks and
    # require the decoder to stop at max_out instead of allocating all
    enc = snappy.StreamCompressor()
    wire = enc.compress(b"\x00" * 65536 * 8)
    dec = snappy.StreamDecompressor()
    with pytest.raises(ValueError):
        dec.decompress(wire, max_out=100000)


def test_skippable_and_reserved_chunks():
    dec = snappy.StreamDecompressor()
    # skippable padding chunk (0xfe) is ignored
    assert dec.decompress(b"\xfe\x02\x00\x00ab") == b""
    # unskippable reserved chunk (0x02) is an error
    with pytest.raises(ValueError):
        snappy.StreamDecompressor().decompress(b"\x02\x01\x00\x00a")


def test_decoder_never_crashes_on_garbage():
    """Adversarial robustness: random bytes and mutated valid streams
    must produce ValueError (or clean output) — never an unhandled
    crash, hang, or out-of-bounds read."""
    rng = random.Random(11)
    # pure garbage blocks
    for _ in range(500):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 80)))
        try:
            snappy.uncompress(blob, 1 << 16)
        except ValueError:
            pass
    # bit-flipped valid blocks
    valid = snappy.compress(bytes(rng.choices(b"abcdef", k=5000)))
    for _ in range(300):
        m = bytearray(valid)
        for _ in range(rng.randrange(1, 4)):
            m[rng.randrange(len(m))] ^= 1 << rng.randrange(8)
        try:
            snappy.uncompress(bytes(m), 1 << 16)
        except ValueError:
            pass
    # garbage framed streams
    for _ in range(300):
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 120)))
        try:
            snappy.StreamDecompressor().decompress(blob, max_out=1 << 20)
        except ValueError:
            pass
