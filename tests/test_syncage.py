"""End-to-end sync-age plane (ISSUE 15): the 45-byte per-batch stamp
trailer (wire format + byte-identical-off contract), the World's
fetch-anchored epoch capture, gate age-at-delivery histograms with
exact per-hop lane sums, the ``sync_age_breach`` flight-recorder
trigger, the ``/syncage`` endpoint and the deployment aggregator —
capped by a live standalone gate -> dispatcher -> game harness over
real sockets (test_tracing style) asserting nonzero monotone ages on
both sync legs (full-record 1503 and delta 1505)."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from goworld_tpu.net import codec, proto
from goworld_tpu.net.packet import (
    AGE_FLAG,
    MSGTYPE_MASK,
    TRACE_FLAG,
    Packet,
    decode_wire,
    new_packet,
    wire_payload,
)
from goworld_tpu.utils import debug_http, flightrec, metrics, syncage

pytestmark = pytest.mark.syncage


@pytest.fixture(autouse=True)
def _fresh_registries():
    """Metric families are process-global; sync-age series must start
    empty per test or cross-test counts leak into lane-sum asserts."""
    metrics.REGISTRY.reset()
    syncage.reset()
    yield
    metrics.REGISTRY.reset()
    syncage.reset()


# =======================================================================
# stamp + lanes
# =======================================================================
def test_stamp_pack_unpack_roundtrip():
    s = syncage.SyncAgeStamp(7, 1000, 2000, 3000, 4000, 4500)
    b = s.pack()
    assert len(b) == syncage.STAMP_WIRE_SIZE == 45
    back = syncage.SyncAgeStamp.unpack(b)
    assert (back.seq, back.t_tick_us, back.t_fetch_us,
            back.t_stage_us, back.t_send_us, back.t_disp_us) == \
        (7, 1000, 2000, 3000, 4000, 4500)
    with pytest.raises(ValueError):
        syncage.SyncAgeStamp.unpack(b[:-1])
    with pytest.raises(ValueError):
        syncage.SyncAgeStamp.unpack(b"\x07" + b[1:])  # bad version


def test_lanes_exact_sum_and_zero_disp_fold():
    s = syncage.SyncAgeStamp(1, 1000, 2000, 3000, 4000, 0)
    lanes, warped = s.lanes_us(10000)
    assert warped == 0
    assert lanes == {"device_tick": 1000, "drain_decode": 1000,
                     "encode": 1000, "dispatcher": 0,
                     "gate_flush": 6000}
    assert sum(lanes.values()) == 10000 - 1000
    # with a dispatcher instant the wire leg splits
    s.t_disp_us = 7000
    lanes, _ = s.lanes_us(10000)
    assert lanes["dispatcher"] == 3000 and lanes["gate_flush"] == 3000
    assert sum(lanes.values()) == 9000


def test_lanes_clock_warp_clamps_and_counts():
    # fetch/stage behind tick, deliver behind send: every negative
    # boundary clamps (never a negative histogram sample) and is
    # counted; the lane sum still covers max(boundary) - t_tick
    s = syncage.SyncAgeStamp(1, 5000, 4000, 4500, 6000, 5500)
    lanes, warped = s.lanes_us(5400)
    assert warped == 4
    assert all(v >= 0 for v in lanes.values())
    assert sum(lanes.values()) == 1000  # 6000 (send) - 5000 (tick)


def test_histogram_observe_n_weighting():
    h = metrics.Histogram(buckets=(1.0, 10.0))
    h.observe_n(5.0, 100)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["buckets"][1][1] == 100
    assert snap["sum"] == pytest.approx(500.0)
    h.observe_n(5.0, 0)  # no-op
    assert h.count == 100


# =======================================================================
# wire format: AGE_FLAG trailer, byte-identical when absent
# =======================================================================
def _sync_packet() -> Packet:
    p = new_packet(proto.MT_SYNC_POSITION_YAW_ON_CLIENTS)
    p.append_u16(1)
    p.append_bytes(b"x" * 96)
    return p


def test_age_flag_constants():
    assert AGE_FLAG == 0x4000
    assert AGE_FLAG & TRACE_FLAG == 0
    # every routing range stays clear of bit 14 (the proto invariant
    # suite holds the ranges themselves)
    assert proto.MT_GATE_SERVICE_MSG_TYPE_STOP < AGE_FLAG


def test_stamped_wire_roundtrip_and_strip():
    p = _sync_packet()
    legacy = wire_payload(p)
    p.age = syncage.SyncAgeStamp(9, 10, 20, 30, 40, 0)
    stamped = wire_payload(p)
    assert len(stamped) == len(legacy) + syncage.STAMP_WIRE_SIZE
    assert int.from_bytes(stamped[:2], "little") & AGE_FLAG
    mt, back = decode_wire(stamped)
    assert mt == proto.MT_SYNC_POSITION_YAW_ON_CLIENTS
    assert back.age is not None and back.age.seq == 9
    # handlers see payload bytes identical to an unstamped packet's
    assert bytes(back.buf) == legacy
    # re-serializing the decoded packet keeps the stamp (the
    # dispatcher's forward path: decode -> patch -> send)
    back.age.t_disp_us = 50
    rewire = wire_payload(back)
    _, back2 = decode_wire(rewire)
    assert back2.age.t_disp_us == 50


def test_stamp_and_trace_trailers_coexist():
    from goworld_tpu.utils import tracing

    p = _sync_packet()
    legacy = wire_payload(p)
    p.age = syncage.SyncAgeStamp(9, 10, 20, 30, 40, 0)
    p.trace = tracing.new_trace()
    mt, back = decode_wire(wire_payload(p))
    assert mt == proto.MT_SYNC_POSITION_YAW_ON_CLIENTS
    assert back.age is not None and back.trace is not None
    assert bytes(back.buf) == legacy


def test_unstamped_wire_byte_identical():
    """The always-on-able contract: with no stamp attached the framed
    bytes are EXACTLY the pre-plane wire."""
    p = _sync_packet()
    assert wire_payload(p) == bytes(p.buf)
    assert not int.from_bytes(wire_payload(p)[:2], "little") & AGE_FLAG


def test_truncated_stamp_trailer_is_connection_error():
    raw = bytearray(_sync_packet().buf[:4])
    raw[1] |= 0x40  # AGE_FLAG set but no room for a 45 B trailer
    with pytest.raises(ConnectionError):
        decode_wire(bytes(raw))


def test_packet_release_clears_stamp():
    p = _sync_packet()
    p.age = syncage.SyncAgeStamp(1, 1, 2)
    p.release()
    assert p.age is None


# =======================================================================
# tracker
# =======================================================================
def test_tracker_record_weighted_lanes_and_snapshot():
    t = syncage.AgeTracker(target_ms=16.0)
    s = syncage.SyncAgeStamp(3, 0, 1000, 2000, 3000, 4000)
    t.observe(s, 8000, 500)
    snap = t.snapshot()
    assert snap["e2e"]["samples"] == 500
    for hop in syncage.HOPS:
        assert snap["hops"][hop]["samples"] == 500
    assert snap["pass"] is True
    assert t.last_seq == 3
    assert sum(t.last_lanes_ms.values()) == pytest.approx(
        t.last_e2e_ms)
    # /syncage raw vectors merge exactly into a fresh histogram
    h = metrics.Histogram(buckets=snap["edges_ms"])
    h.add_counts(snap["e2e_counts"])
    assert h.count == 500


def test_tracker_window_verdict_deltas():
    t = syncage.AgeTracker()
    s = syncage.SyncAgeStamp(1, 0, 0, 0, 0, 0)
    t.observe(s, 5000, 10)
    p99, n = t.window_verdict()   # first call: establishes the mark
    assert (p99, n) == (None, 0)
    t.observe(s, 50000, 20)       # 50 ms ages
    p99, n = t.window_verdict()
    assert n == 20 and p99 is not None and p99 > 16.0
    p99, n = t.window_verdict()   # empty window
    assert (p99, n) == (None, 0)


def test_syncage_registry_weakref():
    t = syncage.AgeTracker()
    syncage.register("gate9", t)
    assert "gate9" in syncage.snapshot_all()
    del t
    import gc

    gc.collect()
    assert "error" in syncage.snapshot_all()


# =======================================================================
# flight-recorder trigger
# =======================================================================
def test_sync_age_breach_trigger_fires_and_cools_down():
    clock = [0.0]
    rec = flightrec.FlightRecorder(ring=16, cooldown_secs=30.0,
                                   clock=lambda: clock[0])
    frame = {"tick": 1, "sync_age_p99_ms": 40.0,
             "sync_age_target_ms": 16.0,
             "sync_age_hops": {"device_tick": 30.0,
                               "gate_flush": 10.0}}
    out = rec.record(dict(frame))
    assert len(out) == 1 and out[0]["trigger"] == "sync_age_breach"
    assert "40" in out[0]["detail"]
    # the per-hop breakdown rides the frozen frames
    assert out[0]["frames"][-1]["sync_age_hops"]["device_tick"] == 30.0
    # cooldown dedups the second breach
    clock[0] = 5.0
    assert rec.record(dict(frame, tick=2)) == []
    clock[0] = 35.0
    out = rec.record(dict(frame, tick=3))
    assert len(out) == 1
    # under target: no trigger
    ok = {"tick": 4, "sync_age_p99_ms": 3.0,
          "sync_age_target_ms": 16.0}
    clock[0] = 99.0
    assert rec.record(ok) == []


# =======================================================================
# encoder byte-kind split (satellite)
# =======================================================================
def test_delta_encoder_splits_keyframe_vs_delta_bytes():
    enc = codec.DeltaSyncEncoder(step=0.25, keyframe_every=100)
    cids = np.asarray([b"C%015d" % 1], "S16")
    eids = np.asarray([b"E%015d" % 1], "S16")
    v0 = np.asarray([[1.0, 2.0, 3.0, 0.5]], np.float32)
    enc.encode_batch(cids, eids, v0, tick=0)
    assert enc.stats["keyframe_bytes"] == 53
    assert enc.stats["delta_bytes"] == 0
    enc.encode_batch(cids, eids, v0 + 0.25, tick=1)
    assert enc.stats["delta_bytes"] == 13
    assert enc.stats["keyframe_bytes"] == 53
    # the per-kind split never exceeds the wire total (headers make up
    # the difference)
    assert (enc.stats["keyframe_bytes"] + enc.stats["delta_bytes"]
            <= enc.stats["wire_bytes"])


# =======================================================================
# game-side flush stamping (unit, no sockets)
# =======================================================================
def _tiny_world():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.manager import World
    from goworld_tpu.ops.aoi import GridSpec

    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=50.0, extent_x=200.0, extent_z=200.0),
        input_cap=32,
    )
    return World(cfg, n_spaces=1)


class _CaptureConn:
    def __init__(self):
        self.wires: list[bytes] = []

    def send(self, p) -> None:
        self.wires.append(wire_payload(p))
        p.release()


def _flush_capture(gs, cids, eids, vals):
    conn = _CaptureConn()
    gs.cluster.select_by_gate_id = lambda gid: conn
    gs._sync_sink(1, cids, eids, vals)
    gs._flush_sync_out()
    return conn.wires


@pytest.fixture(scope="module")
def tiny_world_ticked():
    """One ticked world shared by the flush-stamping units (the tick
    compiles the device step once; sync_age_anchor is then set)."""
    w = _tiny_world()
    w.tick()
    w.tick()
    return w


def test_world_tick_sets_age_anchor(tiny_world_ticked):
    w = tiny_world_ticked
    anchor = w.sync_age_anchor
    assert anchor is not None
    seq, t_tick, t_fetch = anchor
    assert t_fetch >= t_tick > 0
    # wall-anchored: within a day of now (catches unit mixups)
    assert abs(t_fetch / 1e6 - time.time()) < 86400


def test_flush_stamps_when_enabled_and_legacy_when_off(
        tiny_world_ticked):
    from goworld_tpu.net.game import GameServer

    w = tiny_world_ticked
    cids = np.asarray([b"C%015d" % i for i in range(4)], "S16")
    eids = np.asarray([b"E%015d" % i for i in range(4)], "S16")
    vals = np.ones((4, 4), np.float32)

    gs_on = GameServer(97, w, [], gc_freeze_on_boot=False)
    wires_on = _flush_capture(gs_on, cids, eids, vals)
    assert len(wires_on) == 1
    mt, p = decode_wire(wires_on[0])
    assert mt == proto.MT_SYNC_POSITION_YAW_ON_CLIENTS
    assert p.age is not None
    anchor = w.sync_age_anchor
    assert p.age.seq == anchor[0]
    assert p.age.t_tick_us == anchor[1]
    assert p.age.t_fetch_us == anchor[2]
    # the flush instants are monotone after the fetch anchor
    assert p.age.t_send_us >= p.age.t_stage_us >= p.age.t_fetch_us
    assert p.age.t_disp_us == 0  # dispatcher hop not taken yet
    # the full-record byte counter saw the payload
    assert metrics.counter("sync_bytes_out",
                           kind="full").value == 4 * 48

    gs_off = GameServer(98, w, [], gc_freeze_on_boot=False,
                        sync_age=False)
    wires_off = _flush_capture(gs_off, cids, eids, vals)
    assert len(wires_off) == 1
    # THE acceptance contract: stamp off => byte-identical legacy wire
    expected = new_packet(proto.MT_SYNC_POSITION_YAW_ON_CLIENTS)
    expected.append_u16(1)
    expected.append_bytes(
        codec.encode_client_sync_batch(cids, eids, vals))
    assert wires_off[0] == bytes(expected.buf)
    # and the stamped wire is exactly legacy + flag + trailer
    unflagged = bytearray(wires_on[0][:len(wires_off[0])])
    unflagged[1] &= 0xBF
    assert bytes(unflagged) == wires_off[0]


def test_delta_leg_carries_stamp_and_kind_split(tiny_world_ticked):
    from goworld_tpu.net.game import GameServer

    w = tiny_world_ticked
    cids = np.asarray([b"C%015d" % i for i in range(3)], "S16")
    eids = np.asarray([b"E%015d" % i for i in range(3)], "S16")
    vals = np.ones((3, 4), np.float32)
    gs = GameServer(96, w, [], gc_freeze_on_boot=False,
                    sync_delta=True)
    wires = _flush_capture(gs, cids, eids, vals)
    mt, p = decode_wire(wires[0])
    assert mt == proto.MT_SYNC_POSITION_YAW_DELTA_ON_CLIENTS
    assert p.age is not None and p.age.seq == w.sync_age_anchor[0]
    # first batch is all keyframes -> the keyframe byte series moved
    assert metrics.counter("sync_bytes_out",
                           kind="keyframe").value == 3 * 53
    assert metrics.counter("sync_bytes_out",
                           kind="delta").value == 0


# =======================================================================
# gate-side delivery aging (unit, no sockets)
# =======================================================================
def test_gate_relay_ages_delivered_records(monkeypatch):
    """_relay_sync_records observes the tracker weighted by the records
    that actually left toward connected clients (unknown cids don't
    count)."""
    from goworld_tpu.net.gate import GateService

    gate = GateService.__new__(GateService)
    gate.gate_id = 5
    gate.clients = {}
    gate._m_down_batch = metrics.histogram(
        "gate_downstream_batch_records",
        buckets=metrics.DEFAULT_SIZE_BUCKETS)
    gate.syncage = syncage.AgeTracker()
    gate.downstream_max_bytes = 0

    sent = []

    class _CP:
        client_id = "C" + "0" * 15

        def send(self, p, release=True):
            sent.append(bytes(p.buf))
            if release:
                p.release()

    gate.clients[_CP.client_id] = _CP()
    cids = np.asarray([_CP.client_id.encode(), b"C%015d" % 9], "S16")
    eids = np.asarray([b"E%015d" % i for i in range(2)], "S16")
    vals = np.ones((2, 4), np.float32)
    now = syncage.now_us()
    stamp = syncage.SyncAgeStamp(1, now - 5000, now - 4000,
                                 now - 3000, now - 2000, now - 1000)
    gate._relay_sync_records(cids, eids, vals, age=stamp)
    snap = gate.syncage.snapshot()
    # only the ONE connected client's record was delivered and aged
    assert snap["e2e"]["samples"] == 1
    assert len(sent) == 1
    lanes = gate.syncage.last_lanes_ms
    assert lanes["device_tick"] == pytest.approx(1.0)
    assert sum(lanes.values()) == pytest.approx(
        gate.syncage.last_e2e_ms)
    # no stamp -> no observation, relay unchanged
    gate._relay_sync_records(cids, eids, vals, age=None)
    assert gate.syncage.snapshot()["e2e"]["samples"] == 1
    assert len(sent) == 2


# =======================================================================
# live standalone harness: game -> dispatcher -> gate over real sockets
# =======================================================================
def _run_loopback(sync_delta: bool, ticks: int = 20,
                  records_per_client: int = 32):
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.net.botclient import BotClient
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.standalone import ClusterHarness

    class Account(Entity):
        ATTRS: dict = {}

    harness = ClusterHarness(n_dispatchers=1, n_gates=1,
                             desired_games=1)
    harness.start()
    gs = None
    stop = threading.Event()
    t = None
    try:
        world = _tiny_world()
        world.register_entity("Account", Account)
        world.create_nil_space()
        gs = GameServer(1, world, list(harness.dispatcher_addrs),
                        boot_entity="Account", gc_freeze_on_boot=False,
                        sync_delta=sync_delta)
        gs.start_network()
        inject = {"batch": None, "left": 0}

        def loop():
            while not stop.is_set():
                gs.pump()
                if inject["left"] > 0 and inject["batch"] is not None:
                    gs._sync_sink(1, *inject["batch"])
                    inject["left"] -= 1
                gs.tick()
                time.sleep(0.01)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        assert gs.ready_event.wait(30), "deployment never ready"
        bots = [BotClient(*harness.gate_addrs[0], bot_id=i)
                for i in range(2)]

        async def drain(bot):
            await bot.connect()
            try:
                await bot._recv_loop()
            except Exception:
                pass

        for b in bots:
            harness.submit(drain(b))
        deadline = time.time() + 20
        while time.time() < deadline:
            live = [e for e in world.entities.values()
                    if e.client is not None]
            if len(live) >= 2:
                break
            time.sleep(0.05)
        live = [e for e in world.entities.values()
                if e.client is not None]
        assert len(live) >= 2, "bots never reached the game"
        cids = np.repeat(np.asarray(
            [e.client.client_id for e in live], "S16"),
            records_per_client)
        eids = np.asarray([b"E%015d" % i for i in range(len(cids))],
                          "S16")
        vals = np.random.default_rng(0).random(
            (len(cids), 4), dtype=np.float32)
        tracker = harness.gates[0].syncage
        inject["batch"] = (cids, eids, vals)
        inject["left"] = ticks
        deadline = time.time() + 30
        while time.time() < deadline and (
                inject["left"] > 0
                or int(tracker.snapshot()["batches"]) < ticks // 2):
            time.sleep(0.1)
        return tracker, len(cids), harness, gs, stop, t
    except BaseException:
        stop.set()
        if t is not None:
            t.join(timeout=5)
        if gs is not None:
            gs.stop()
        harness.stop()
        raise


def _teardown(harness, gs, stop, t):
    stop.set()
    t.join(timeout=5)
    gs.stop()
    harness.stop()


def test_e2e_loopback_full_leg_ages_monotone_and_sum():
    tracker, n_rec, harness, gs, stop, t = _run_loopback(
        sync_delta=False)
    try:
        snap = tracker.snapshot()
        # nonzero ages, every record delivered was aged
        assert snap["batches"] >= 10
        assert snap["e2e"]["samples"] >= 10 * n_rec
        assert snap["e2e"]["p50_ms"] > 0
        # monotone boundaries on one host: ZERO warped clamps
        assert snap["clock_warp_total"] == 0
        # the dispatcher hop was actually stamped mid-path
        assert snap["hops"]["dispatcher"]["samples"] == \
            snap["e2e"]["samples"]
        # per-hop lanes sum EXACTLY to the e2e age (the freshest
        # observation is pre-bucketing; bucket tolerance not needed)
        lanes = tracker.last_lanes_ms
        assert lanes["device_tick"] > 0
        assert sum(lanes.values()) == pytest.approx(
            tracker.last_e2e_ms, abs=1e-6)
        # histogram-level: every lane saw the same weighted count
        for hop in syncage.HOPS:
            assert snap["hops"][hop]["samples"] == \
                snap["e2e"]["samples"]

        # /syncage endpoint serves this tracker (registered by the
        # GateService constructor)
        srv = debug_http.start(0, process_name="gate1-test")
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/syncage",
                    timeout=5) as resp:
                payload = json.loads(resp.read())
            assert "gate1" in payload
            assert payload["gate1"]["e2e"]["samples"] == \
                snap["e2e"]["samples"]

            # deployment aggregator merges this process's plane and
            # renders the ONE verdict line (cli.py watch path)
            import obs_aggregate

            agg = obs_aggregate.aggregate(
                [("gate1", f"http://127.0.0.1:{port}")])
            assert agg["e2e"]["samples"] == snap["e2e"]["samples"]
            assert "pass" in agg
            line = obs_aggregate.verdict_line(agg)
            assert "deployment sync-age" in line and "p99" in line
            assert obs_aggregate.hop_table(agg)
        finally:
            srv.shutdown()
    finally:
        _teardown(harness, gs, stop, t)


def test_e2e_loopback_delta_leg_carries_ages():
    tracker, n_rec, harness, gs, stop, t = _run_loopback(
        sync_delta=True, ticks=12)
    try:
        snap = tracker.snapshot()
        assert snap["batches"] >= 6
        assert snap["e2e"]["samples"] >= 6 * n_rec
        assert snap["clock_warp_total"] == 0
        assert tracker.last_lanes_ms["device_tick"] > 0
        # the delta codec's byte-kind split moved on the game side
        assert metrics.counter("sync_bytes_out",
                               kind="keyframe").value > 0
    finally:
        _teardown(harness, gs, stop, t)


# =======================================================================
# aggregator units (no sockets)
# =======================================================================
def test_aggregator_merges_counts_exactly(monkeypatch):
    import obs_aggregate

    t1 = syncage.AgeTracker(name="g1")
    t2 = syncage.AgeTracker(name="g2")
    s = syncage.SyncAgeStamp(1, 0, 1000, 2000, 3000, 4000)
    t1.observe(s, 8000, 100)
    t2.observe(s, 30000, 50)  # 30 ms ages on the second gate
    snaps = {"g1": {"gate1": t1.snapshot()},
             "g2": {"gate2": t2.snapshot()}}

    def fake_fetch(url, timeout=2.0):
        for label, payload in snaps.items():
            if url.startswith(f"http://{label}"):
                if url.endswith("/syncage"):
                    return payload
                raise OSError("only /syncage faked")
        raise OSError("unknown target")

    monkeypatch.setattr(obs_aggregate, "_fetch_json", fake_fetch)
    agg = obs_aggregate.aggregate(
        [("g1", "http://g1"), ("g2", "http://g2"),
         ("dead", "http://dead")])
    assert agg["e2e"]["samples"] == 150
    assert len(agg["gates"]) == 2
    assert "dead" in agg["skipped"]
    # the merged p99 reflects the slow gate's mass
    assert agg["e2e"]["p99_ms"] == "inf" or \
        agg["e2e"]["p99_ms"] > 16.0
    assert agg["pass"] is False
    assert "FAIL" in obs_aggregate.verdict_line(agg)


def test_aggregator_honest_when_nothing_answers(monkeypatch):
    import obs_aggregate

    def fail(url, timeout=2.0):
        raise OSError("down")

    monkeypatch.setattr(obs_aggregate, "_fetch_json", fail)
    agg = obs_aggregate.aggregate([("g1", "http://g1")])
    assert agg["gates"] == [] and "e2e" not in agg
    assert "no stamped deliveries" in obs_aggregate.verdict_line(agg)
