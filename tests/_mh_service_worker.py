"""Worker for test_multihost.py::test_multihost_services — sharded
singleton services on a TWO-CONTROLLER world.

The kvreg claim/create cycle must reach identical conclusions on every
controller: kvreg updates replicate through the mutation log, the group
claims shards under one token (``mh:<world.game_id>``), and reconciles
run on the allgathered-ready + tick-count cadence — so both controllers
create the SAME service entities with the SAME deterministic ids, and a
service RPC invoked from SPMD logic executes on both.

Invoked as: python -m tests._mh_service_worker <pid> <coord> <disp>.
"""

import asyncio
import json
import sys
import threading
import time

TICKS = 260
TICK_SLEEP = 0.02


def main() -> int:
    pid = int(sys.argv[1])
    coord_port = sys.argv[2]
    disp_port = int(sys.argv[3])

    from goworld_tpu.parallel.multihost import global_mesh, init_distributed
    init_distributed(f"127.0.0.1:{coord_port}", num_processes=2,
                     process_id=pid)

    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.entity import Entity
    from goworld_tpu.entity.manager import World
    from goworld_tpu.entity.space import Space
    from goworld_tpu.net.dispatcher import DispatcherService
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.ops.aoi import GridSpec

    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=16),
        npc_speed=0.0,
        enter_cap=128, leave_cap=128, sync_cap=128,
    )
    w = World(cfg, n_spaces=8, mesh=global_mesh(), megaspace=True,
              halo_cap=8, migrate_cap=4)

    class Mega(Space):
        pass

    class Counter(Entity):
        calls: list = []

        def Incr(self, amount):
            Counter.calls.append(int(amount))

    w.registry.register("Mega", Mega, is_space=True, megaspace=True)
    w.create_nil_space()
    w.create_space("Mega")

    ready = threading.Event()

    def services_thread() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def boot():
            if pid == 0:
                d = DispatcherService(1, "127.0.0.1", disp_port,
                                      desired_games=2, desired_gates=0)
                asyncio.ensure_future(d.serve())
                await d.started.wait()

        loop.run_until_complete(boot())
        ready.set()
        loop.run_forever()

    threading.Thread(target=services_thread, daemon=True).start()
    assert ready.wait(30)
    if pid == 1:
        time.sleep(1.0)  # let the dispatcher bind first

    gs = GameServer(pid + 1, w, [("127.0.0.1", disp_port)])
    svc = gs.setup_services()
    svc.register("Counter", Counter, shard_count=2)
    gs.start_network()

    called_at = None
    for t in range(TICKS):
        gs.pump()
        # SPMD service call once both shards resolve (world state +
        # kvreg mirror are SPMD-consistent, so both controllers fire
        # at the same tick)
        if called_at is None \
                and svc.entity_id_of("Counter", 0) is not None \
                and svc.entity_id_of("Counter", 1) is not None:
            svc.call("Counter", "Incr", (5,), shard_index=0)
            called_at = t
        gs.tick()
        time.sleep(TICK_SLEEP)

    eids = [svc.entity_id_of("Counter", i) for i in (0, 1)]
    out = {
        "process": pid,
        "service_eids": eids,
        "local_entities": sorted(
            e.id for e in w.entities.values()
            if e.type_name == "Counter" and not e.destroyed
        ),
        "incr_calls": Counter.calls,
        "claim": svc._claim,
        "called": called_at is not None,
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
