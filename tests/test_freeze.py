"""Freeze/restore (hot reload) tests.

Mirrors the reference's live-reload soak (``test_game.yml``: run bots,
``goworld reload``, run bots again) at unit scale, plus round-trip unit
tests in the spirit of ``engine/entity/migarte_test.go``."""

import threading
import time

import pytest

from goworld_tpu import freeze
from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import Entity, GameClient, Space, World
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec


class Npc(Entity):
    ATTRS = {"hp": "allclients", "name": "client"}

    def __init__(self):
        super().__init__()
        self.heal_count = 0

    def Heal(self, amount):
        self.heal_count += 1
        self.attrs["hp"] = self.attrs.get("hp", 0) + amount


class Arena(Space):
    pass


def _cfg():
    return WorldConfig(
        capacity=64,
        grid=GridSpec(radius=30.0, extent_x=120.0, extent_z=120.0),
        input_cap=64,
    )


def _register(world):
    world.register_entity("Npc", Npc)
    world.register_space("Arena", Arena)


def _make_world():
    w = World(_cfg(), n_spaces=1)
    _register(w)
    w.create_nil_space()
    return w


class TestFreezeRoundtrip:
    def test_requires_nil_space(self):
        w = World(_cfg(), n_spaces=1)
        with pytest.raises(RuntimeError):
            freeze.freeze_world(w)

    def test_world_roundtrip(self):
        w = _make_world()
        arena = w.create_space("Arena", motd="welcome")
        a = w.create_entity("Npc", space=arena, pos=(10.0, 0.0, 10.0))
        a.attrs["hp"] = 70
        a.attrs["name"] = "alice"
        b = w.create_entity("Npc", space=arena, pos=(12.0, 0.0, 12.0))
        b.attrs["hp"] = 55
        b.set_yaw(1.5)
        # timer by method name: migration/freeze-safe like the reference
        b.add_timer(0.05, "Heal", 5)
        # client binding must survive quietly
        a.client = GameClient(2, "c" * 16, w)
        parked = w.create_entity("Npc", pos=(0.0, 0.0, 0.0))  # nil space
        for _ in range(3):
            w.tick()

        data = freeze.freeze_world(w)

        w2 = _make_world()
        freeze.restore_world(w2, data)
        assert set(w2.entities) == set(w.entities)
        arena2 = w2.spaces[arena.id]
        assert arena2.attrs.get("motd") == "welcome"
        a2, b2 = w2.entities[a.id], w2.entities[b.id]
        assert a2.attrs.get("hp") == 70
        assert a2.attrs.get("name") == "alice"
        assert a2.client is not None and a2.client.gate_id == 2
        assert a2.space is arena2
        assert w2.entities[parked.id].space is w2.nil_space
        # positions/yaw carried over (device state was snapshotted)
        for _ in range(3):
            w2.tick()
        assert tuple(w2.read_pos(0, a2.slot)) == pytest.approx(
            (10.0, 0.0, 10.0))
        assert w2.read_yaw(0, b2.slot) == pytest.approx(1.5)
        # AOI re-fires: a and b are within radius -> interest rebuilt
        assert b2.id in a2.interested_in
        # restored method-name timer still fires
        deadline = time.monotonic() + 2.0
        while b2.heal_count == 0 and time.monotonic() < deadline:
            w2.tick()
            time.sleep(0.01)
        assert b2.heal_count >= 1
        assert b2.attrs.get("hp") >= 60

    def test_file_roundtrip(self, tmp_path):
        w = _make_world()
        arena = w.create_space("Arena")
        e = w.create_entity("Npc", space=arena, pos=(5.0, 0.0, 5.0))
        e.attrs["hp"] = 1
        path = freeze.freeze_to_file(w, str(tmp_path))
        assert path.endswith("game1_freezed.dat")
        w2 = _make_world()
        freeze.restore_from_file(w2, str(tmp_path))
        assert e.id in w2.entities

    def test_restore_rejects_populated_world(self):
        w = _make_world()
        data = freeze.freeze_world(w)
        w2 = _make_world()
        w2.create_space("Arena")
        with pytest.raises(RuntimeError):
            freeze.restore_world(w2, data)


def _drive(gs, stop):
    while not stop.is_set() and gs.run_state == "running":
        gs.pump()
        gs.tick()
        time.sleep(0.01)
    # freeze path: serve_forever would do this; emulate its tail
    if gs.run_state == "freezing":
        gs._do_freeze()


def test_cluster_freeze_then_restore(tmp_path):
    """Full protocol: game asks dispatchers to block, snapshots, exits;
    a new game process restores and traffic resumes (SURVEY.md#3.6)."""
    harness = ClusterHarness(n_dispatchers=2, n_gates=0, desired_games=1)
    harness.start()
    try:
        w = _make_world()
        arena = w.create_space("Arena")
        npc = w.create_entity("Npc", space=arena, pos=(1.0, 0.0, 1.0))
        npc.attrs["hp"] = 9

        gs = GameServer(1, w, list(harness.dispatcher_addrs),
                        freeze_dir=str(tmp_path))
        gs.start_network()
        stop = threading.Event()
        t = threading.Thread(target=_drive, args=(gs, stop), daemon=True)
        t.start()
        assert gs.ready_event.wait(20)

        gs.request_freeze()
        deadline = time.monotonic() + 15
        while gs.run_state != "frozen" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert gs.run_state == "frozen"
        stop.set()
        t.join(timeout=5)

        # dispatcher kept the game blocked: queue an RPC while "down"
        from goworld_tpu.net import proto as P
        d = harness.dispatchers[0]
        pkt = P.pack_call_entity_method(npc.id, "Heal", (3,))
        harness.submit(_inject(d, npc.id, pkt)).result(timeout=5)

        # new process, same game id, -restore
        w2 = _make_world()
        gs2 = GameServer(1, w2, list(harness.dispatcher_addrs),
                         freeze_dir=str(tmp_path), restore=True)
        assert npc.id in w2.entities
        gs2.start_network()
        stop2 = threading.Event()
        t2 = threading.Thread(target=_drive, args=(gs2, stop2), daemon=True)
        t2.start()
        try:
            npc2 = w2.entities[npc.id]
            deadline = time.monotonic() + 15
            while npc2.heal_count == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert npc2.heal_count == 1, \
                "queued RPC was not delivered after restore"
            assert npc2.attrs.get("hp") == 12
        finally:
            stop2.set()
            t2.join(timeout=5)
            gs2.stop()
    finally:
        harness.stop()


async def _inject(dispatcher, eid, pkt):
    """Route a packet through the dispatcher's entity table as if it came
    from another game."""
    dispatcher._dispatch_to_entity(eid, pkt)


class TestAsyncCheckpoint:
    def test_checkpoint_while_running_restores_capture_point(self, tmp_path):
        """checkpoint_async captures the tick boundary it was called at;
        the world keeps ticking and mutating afterwards, and restoring
        the file reproduces the CAPTURED state, not the later one."""
        import numpy as np

        from goworld_tpu import freeze as fz
        from goworld_tpu.core.state import WorldConfig
        from goworld_tpu.entity.manager import World
        from goworld_tpu.ops.aoi import GridSpec

        def build():
            cfg = WorldConfig(
                capacity=64,
                grid=GridSpec(radius=20.0, extent_x=200.0, extent_z=200.0,
                              k=8, cell_cap=16, row_block=64),
                npc_speed=6.0,
                enter_cap=256, leave_cap=256, sync_cap=256,
                attr_sync_cap=64, input_cap=8,
            )
            w = World(cfg)
            w.register_entity("Npc", type("Npc", (Entity,), {}))
            w.register_space("Arena", type("Arena", (Space,), {}))
            w.create_nil_space()
            return w

        w = build()
        arena = w.create_space("Arena")
        rng = np.random.default_rng(0)
        for i in range(20):
            e = w.create_entity(
                "Npc", space=arena,
                pos=(rng.uniform(0, 200), 0, rng.uniform(0, 200)),
                moving=True,
            )
            e.attrs["hp"] = 100 + i
        for _ in range(3):
            w.tick()

        handle = fz.checkpoint_async(w, str(tmp_path))
        # the world keeps running + mutating while the worker transfers
        captured_pos = {
            e.id: tuple(e.position) for e in w.entities.values()
            if not e.is_space
        }
        for _ in range(5):
            w.tick()
        for e in list(w.entities.values()):
            if not e.is_space:
                e.attrs["hp"] = 1          # post-capture mutation
        handle.join(30)
        assert handle.path is not None

        w2 = build()
        fz.restore_world(w2, fz.read_freeze_file(handle.path))
        w2.tick()
        npcs = [e for e in w2.entities.values()
                if not e.is_space and e.type_name == "Npc"]
        assert len(npcs) == 20
        for e in npcs:
            assert e.attrs["hp"] >= 100    # captured value, not the 1
            ref = captured_pos[e.id]
            got = e.position
            # captured positions (one tick of drift allowed: capture is
            # the state AFTER the last tick; restore re-integrates)
            d = max(abs(got[0] - ref[0]), abs(got[2] - ref[2]))
            assert d < 1.0, (e.id, got, ref)

    def test_checkpoint_contains_no_slot_refs(self, tmp_path):
        """The written file is plain freeze format: every deferred
        (shard, slot) placeholder must have been patched out."""
        import numpy as np

        from goworld_tpu import freeze as fz
        from goworld_tpu.core.state import WorldConfig
        from goworld_tpu.entity.manager import World
        from goworld_tpu.ops.aoi import GridSpec

        cfg = WorldConfig(
            capacity=16,
            grid=GridSpec(radius=20.0, extent_x=100.0, extent_z=100.0,
                          k=8, cell_cap=16, row_block=16),
            enter_cap=64, leave_cap=64, sync_cap=64,
            attr_sync_cap=16, input_cap=4,
        )
        w = World(cfg)
        w.register_entity("Npc", type("Npc", (Entity,), {}))
        w.register_space("Arena", type("Arena", (Space,), {}))
        w.create_nil_space()
        sp = w.create_space("Arena")
        w.create_entity("Npc", space=sp, pos=(50.0, 0.0, 50.0))
        w.tick()
        h = fz.checkpoint_async(w, str(tmp_path)).join(30)
        data = fz.read_freeze_file(h.path)
        assert all("_slot" not in rec for rec in data["entities"])
        pos = data["entities"][0]["pos"]
        assert abs(pos[0] - 50.0) < 1e-3 and abs(pos[2] - 50.0) < 1e-3


class TestSnapshotCorruption:
    """A partial/corrupt snapshot must be REJECTED whole — restore falls
    back to the next-freshest candidate or fails loudly, never
    half-loads (ISSUE 3 recovery invariant)."""

    def _frozen(self):
        w = _make_world()
        arena = w.create_space("Arena")
        e = w.create_entity("Npc", space=arena, pos=(5.0, 0.0, 5.0))
        e.attrs["hp"] = 3
        return e, freeze.freeze_world(w)

    def test_truncated_freeze_falls_back_to_checkpoint(self, tmp_path):
        import msgpack

        e, data = self._frozen()
        # older but VALID checkpoint...
        freeze.write_freeze_file(
            str(tmp_path / freeze.checkpoint_filename(1)), data)
        # ...shadowed by a newer TRUNCATED freeze file (simulated crash
        # of a non-atomic writer / disk fault)
        blob = msgpack.packb(data, use_bin_type=True)
        fz = tmp_path / freeze.freeze_filename(1)
        fz.write_bytes(blob[: len(blob) // 2])
        later = time.time() + 5
        import os
        os.utime(str(fz), (later, later))

        assert freeze.latest_snapshot_path(1, str(tmp_path)) \
            == str(fz)                      # mtime says the corrupt one
        w2 = _make_world()
        freeze.restore_from_file(w2, str(tmp_path))   # ...but it falls back
        assert e.id in w2.entities
        assert w2.entities[e.id].attrs.get("hp") == 3
        assert freeze.has_restorable_snapshot(1, str(tmp_path))

    def test_all_corrupt_rejected_not_half_loaded(self, tmp_path):
        import msgpack

        _e, data = self._frozen()
        blob = msgpack.packb(data, use_bin_type=True)
        (tmp_path / freeze.freeze_filename(1)).write_bytes(blob[:40])
        assert not freeze.has_restorable_snapshot(1, str(tmp_path))
        w2 = _make_world()
        with pytest.raises(freeze.CorruptSnapshotError):
            freeze.restore_from_file(w2, str(tmp_path))
        # nothing was half-loaded: the world still holds only nil space
        assert list(w2.entities) == [w2.nil_space.id]

    def test_parseable_but_wrong_shape_rejected(self, tmp_path):
        import msgpack

        (tmp_path / freeze.freeze_filename(1)).write_bytes(
            msgpack.packb(["not", "a", "freeze"], use_bin_type=True))
        with pytest.raises(freeze.CorruptSnapshotError):
            freeze.read_freeze_file(
                str(tmp_path / freeze.freeze_filename(1)))

    def test_crash_mid_freeze_leaves_only_tmp(self, tmp_path):
        """Injected crash between the tmp write and the atomic rename
        (`crash:freeze.write`): the snapshot path must hold only the
        .tmp — a later -restore boot sees no (partial) freeze file at
        all, exactly the no-half-load guarantee."""
        import os
        import subprocess
        import sys

        from goworld_tpu.utils import faults as faults_mod

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        target = str(tmp_path / freeze.freeze_filename(1))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo
        env["JAX_PLATFORMS"] = "cpu"
        env["GOWORLD_FAULTS"] = "crash:freeze.write:1.0"
        r = subprocess.run(
            [sys.executable, "-c",
             "from goworld_tpu.utils import faults; "
             "faults.install('freezer'); "
             "from goworld_tpu import freeze; "
             f"freeze.write_freeze_file({target!r}, "
             "{'version': 1, 'entities': []})"],
            env=env, capture_output=True, text=True, timeout=240,
        )
        assert r.returncode == faults_mod.KILL_EXIT_CODE, \
            r.stdout + r.stderr
        assert not os.path.exists(target)          # no partial snapshot
        assert os.path.exists(target + ".tmp")     # the crash artifact
        w2 = _make_world()
        with pytest.raises(FileNotFoundError):
            freeze.restore_from_file(w2, str(tmp_path))


@pytest.mark.precision
class TestSnapshotChain:
    """Quantized + delta-compressed snapshot chain (ISSUE 12,
    freeze.SnapshotChain): keyframe cadence, bit-exact roundtrip in
    the lattice domain, and corrupt/mismatched deltas falling back to
    the keyframe through the existing CorruptSnapshotError path."""

    def _world_with_npcs(self, n=8):
        w = _make_world()
        sp = w.create_space("Arena")
        ents = [w.create_entity("Npc", space=sp,
                                pos=(3.0 * i, 0.0, 5.0 * i))
                for i in range(n)]
        w.tick()
        return w, sp, ents

    def test_keyframe_cadence_honored(self, tmp_path):
        w, _sp, _es = self._world_with_npcs()
        chain = freeze.SnapshotChain(w, str(tmp_path), keyframe_every=3)
        kinds = []
        for _ in range(7):
            kinds.append("K" if chain.write().endswith("_ckpt_key.dat")
                         else "D")
        assert kinds == ["K", "D", "D", "K", "D", "D", "K"]

    def test_roundtrip_bit_exact_on_restore(self, tmp_path):
        import msgpack

        w, _sp, ents = self._world_with_npcs()
        chain = freeze.SnapshotChain(w, str(tmp_path), keyframe_every=4)
        pk = chain.write()
        pd = chain.write()
        data = freeze.read_freeze_file(pd)   # delta resolves via key
        assert data["version"] == 1
        w2 = _make_world()
        freeze.restore_world(w2, data)
        assert len([e for e in w2.entities.values()
                    if isinstance(e, Npc)]) == len(ents)
        # restored positions are lattice points; a SECOND chain write
        # of the restored world produces BYTE-IDENTICAL planes
        # (lattice points re-quantize to themselves)
        w2.tick()
        chain2 = freeze.SnapshotChain(w2, str(tmp_path / "b"),
                                      keyframe_every=4)
        import os as _os

        _os.makedirs(tmp_path / "b", exist_ok=True)
        pk2 = chain2.write()
        a = msgpack.unpackb(open(pk, "rb").read(), raw=False)
        b = msgpack.unpackb(open(pk2, "rb").read(), raw=False)
        for nm in ("pos_xz", "pos_y", "yaw", "moving"):
            assert a["planes"][nm] == b["planes"][nm], nm

    def test_delta_ships_only_changed_rows(self, tmp_path):
        import msgpack
        import numpy as np

        w, _sp, ents = self._world_with_npcs()
        chain = freeze.SnapshotChain(w, str(tmp_path), keyframe_every=8)
        chain.write()
        # move ONE entity by a super-lattice amount
        ents[3].set_position((100.0, 0.0, 100.0))
        w.tick()
        pd = chain.write()
        rec = msgpack.unpackb(open(pd, "rb").read(), raw=False)
        rows = np.frombuffer(rec["rows"], np.int32)
        assert (rows < 0).sum() <= 2     # the mover (+jitter slack)
        data = freeze.read_freeze_file(pd)
        by_id = {e["id"]: e for e in data["entities"]}
        got = by_id[ents[3].id]["pos"]
        step = freeze.snapshot_quant_step(w)
        assert abs(got[0] - 100.0) <= step
        assert abs(got[2] - 100.0) <= step

    def test_corrupt_delta_falls_back_to_keyframe(self, tmp_path):
        w, _sp, ents = self._world_with_npcs()
        chain = freeze.SnapshotChain(w, str(tmp_path), keyframe_every=4)
        chain.write()
        pd = chain.write()
        with open(pd, "r+b") as f:
            f.seek(24)
            f.write(b"\xff" * 16)
        with pytest.raises(freeze.CorruptSnapshotError):
            freeze.read_freeze_file(pd)
        # the candidate walk lands on the keyframe instead
        w2 = _make_world()
        freeze.restore_from_file(w2, str(tmp_path))
        assert len([e for e in w2.entities.values()
                    if isinstance(e, Npc)]) == len(ents)

    def test_rewritten_keyframe_fails_delta_crc(self, tmp_path):
        """A delta whose keyframe was REPLACED (CRCs mismatch) must be
        rejected whole — merging planes across two worlds' keyframes
        would silently mix states."""
        w, _sp, ents = self._world_with_npcs()
        chain = freeze.SnapshotChain(w, str(tmp_path), keyframe_every=4)
        chain.write()
        pd = chain.write()
        # a different world rewrites the keyframe under the delta
        w3 = _make_world()
        sp3 = w3.create_space("Arena")
        w3.create_entity("Npc", space=sp3, pos=(99.0, 0.0, 99.0))
        w3.tick()
        freeze.SnapshotChain(w3, str(tmp_path), keyframe_every=4).write()
        with pytest.raises(freeze.CorruptSnapshotError,
                           match="CRC mismatch"):
            freeze.read_freeze_file(pd)
        # ...and recovery still restores (the fresh keyframe parses)
        w2 = _make_world()
        freeze.restore_from_file(w2, str(tmp_path))
