"""Multi-host (multi-controller) megaspace: two OS processes, one global
8-device mesh, entity migration and AOI ghost interest across the PROCESS
boundary (SURVEY.md §5.8 — the reference scales across machines via its
dispatcher TCP star; here the data plane rides XLA collectives whose
cross-process legs run over the distributed runtime: Gloo/gRPC on this
CPU rig, ICI+DCN on real hardware)."""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _drain(procs, timeout):
    """communicate() every worker, KILLING all of them on a timeout —
    a leaked worker pair keeps burning CPU (and its jax.distributed
    rendezvous) long after the test fails."""
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout))
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.communicate()
        raise
    return outs


@pytest.mark.slow
def test_two_process_megaspace_migration_and_ghosts():
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU relay
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_worker", str(pid), str(port)],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 300)):
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r

    # each controller owns its half of the mesh
    assert results[0]["local_shards"] == [0, 1, 2, 3]
    assert results[1]["local_shards"] == [4, 5, 6, 7]
    # both controllers agree on the global population (psum over DCN)
    assert results[0]["global_alive"] == 2
    assert results[1]["global_alive"] == 2
    # the walker crossed the process boundary: process 1 saw the arrival
    # on its shard 4 (process 0 can never see it — not addressable there)
    assert results[1]["migrated_tick"] >= 0, (
        f"no cross-process migration: {results[1]}"
    )
    # the tile-4 watcher (process 1) saw an AOI enter BEFORE the walker
    # migrated — ghost-zone interest across the process boundary
    shard4_enters = [
        e for e in results[1]["enters"] if e[0] == 4 and e[1] == 0
    ]
    assert shard4_enters, (
        f"tile-4 watcher never saw the cross-border ghost: {results[1]}"
    )


@pytest.mark.slow
def test_world_api_multihost():
    """The full World (entity API + megaspace + host bookkeeping) running
    SPMD on two controllers: slot bookkeeping stays identical everywhere,
    while AOI event fan-out is owner-local — the watcher's interest set
    updates on the controller owning its tile."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_world_worker",
             str(pid), str(port)],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 300)):
        assert p.returncode == 0, f"worker failed:\n{err[-2500:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r

    r0, r1 = results[0], results[1]
    # slot/shard bookkeeping is SPMD-identical on both controllers
    assert r0["walker_shard"] == r1["walker_shard"] == 4, (r0, r1)
    assert r0["watcher_shard"] == r1["watcher_shard"] == 4
    assert r0["walker_alive"] and r1["walker_alive"]
    # both controllers read the same committed device position
    assert abs(r0["walker_pos_x"] - r1["walker_pos_x"]) < 1e-4
    assert r0["walker_pos_x"] > 400.0
    # event fan-out is owner-local: tile 4 belongs to process 1, so ONLY
    # process 1 fired the watcher's OnEnterAOI / updated its interest set
    assert "walker_walker_00" in r1["watcher_interested_in"]
    assert ("watcher_sees", "walker_walker_00") in [
        tuple(e) for e in r1["events"]
    ]
    assert "walker_walker_00" not in r0["watcher_interested_in"]


@pytest.mark.slow
def test_cross_controller_client_visibility():
    """The reference's any-client-sees-any-entity contract
    (``components/gate/GateService.go:258-306``) across CONTROLLERS: a
    strict-mirror bot on controller 0's gate logs in, its Avatar lands on
    a tile owned by controller 1, and a Walker moving on that remote tile
    must appear and position-sync in the bot's mirror — controller 1
    decodes the events and the dispatcher wire carries them to gate 1 by
    gate id. Exercises the multihost mutation log (client connect + Login
    RPC arrive on one controller, applied on both) and the per-entity
    client-send ownership dedup."""
    coord = _free_port()
    disp = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_cluster_worker",
             str(pid), str(coord), str(disp)],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 700)):
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r

    r0, r1 = results[0], results[1]
    assert "bot_script_error" not in r0, r0
    assert r0["bot_errors"] == [], r0["bot_errors"]
    # SPMD bookkeeping: both controllers agree the Avatar sits on tile 4
    # (controller 1's side) and owned the gate-1 client
    assert r0["avatar_shard"] == r1["avatar_shard"] == 4, (r0, r1)
    assert r0["avatar_had_client"] and r1["avatar_had_client"]
    assert r0["avatar_gate"] == r1["avatar_gate"] == 1
    # the bot's hang-up propagated through the mutation log: BOTH
    # controllers unbound the avatar's client
    assert r0["disconnect_propagated"] and r1["disconnect_propagated"], \
        (r0.get("extra_ticks"), r1.get("extra_ticks"))
    # the bot completed the Account -> Avatar handoff
    assert r0["bot_player_type"] == "Avatar", r0
    assert r0["bot_player_name"] == "bob", r0
    # the remote tile's walker reached the bot's mirror and kept syncing
    assert "walker_walker_00" in r0["bot_mirrors"], r0["bot_mirrors"]
    assert r0["walker_mirror_x"] is not None \
        and r0["walker_mirror_x"] > 420.5, r0
    assert r0["bot_sync_count"] >= 3, r0
    # and the traffic was emitted by CONTROLLER 1 (the tile owner), not 0
    assert r1["sent"]["create_entity"] >= 1, r1["sent"]
    assert r1["sent"]["sync_records"] >= 3, r1["sent"]


@pytest.mark.slow
def test_two_process_stress_consistency():
    """40 churny ticks with 60 movers over the 2-controller mesh: both
    controllers agree on the global population every tick, nobody is
    lost or duplicated (the union of local occupancies is exactly the
    population), and cross-process migrations actually happened."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_worker",
             str(pid), str(port), "stress"],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 420)):
        assert p.returncode == 0, f"worker failed:\n{err[-2500:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r
    r0, r1 = results[0], results[1]
    assert r0["global_alive"] == r1["global_alive"] == [60] * 40
    total = sum(r0["occupancy"].values()) + sum(r1["occupancy"].values())
    assert total == 60, (r0["occupancy"], r1["occupancy"])
    assert r0["dropped"] == 0 and r1["dropped"] == 0
    # churn actually crossed tiles (and with 4x2... 8 tiles over 2
    # processes, some hops crossed the process boundary)
    assert r0["migrations"] + r1["migrations"] > 0


@pytest.mark.slow
def test_multihost_checkpoint_restore():
    """§5.4 checkpoint/resume EXTENDED across controllers: every
    controller calls freeze_world at the same point (the device snapshot
    is an allgather — itself a lockstep point), gets the identical
    global snapshot, and restore_world rebuilds a fresh World over the
    same mesh with positions, attrs, tile ownership, and (after one
    sweep) interest sets intact. The reference can only freeze a single
    game process (GameService.go:220-313)."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_freeze_worker",
             str(pid), str(port)],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 420)):
        assert p.returncode == 0, f"worker failed:\n{err[-2500:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r

    r0, r1 = results[0], results[1]
    # the walker had crossed onto controller 1's tile pre-freeze, and the
    # restored world agrees on every controller
    assert r0["pre"]["walker_shard"] == 4
    assert r0["restored_walker_shard"] == r1["restored_walker_shard"] == 4
    for r in (r0, r1):
        assert abs(r["restored_walker_x"] - r0["pre"]["walker_x"]) < 1e-3
        assert r["restored_hp"] == 7
        assert r["restored_alive"] == 2
    # interest was re-derived from restored positions; fan-out stays
    # owner-local, so the watcher's set updates on controller 1
    assert r1["restored_watcher_sees"] == r1["pre"]["watcher_sees"] \
        == ["walker_walker_00"]


@pytest.mark.slow
def test_multihost_services():
    """Sharded singleton services on a multi-controller world: kvreg
    updates replicate through the mutation log, the group claims shards
    under one token, reconciles run on the allgathered-ready tick
    cadence — both controllers create the SAME service entities with
    the SAME deterministic ids, and a service RPC from SPMD logic
    executes on both (reference service.go:106-238 kvreg race,
    single-process-per-claim)."""
    coord = _free_port()
    disp = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "tests._mh_service_worker",
             str(pid), str(coord), str(disp)],
            cwd=REPO, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    results = {}
    for p, (out, err) in zip(procs, _drain(procs, 420)):
        assert p.returncode == 0, f"worker failed:\n{err[-2500:]}"
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        r = json.loads(line)
        results[r["process"]] = r

    r0, r1 = results[0], results[1]
    assert r0["claim"] == r1["claim"] == "mh:1"
    # both shards placed, identical ids on both controllers, and the
    # entities EXIST locally on both (SPMD host replication)
    assert all(r0["service_eids"]), r0
    assert r0["service_eids"] == r1["service_eids"]
    assert r0["local_entities"] == r1["local_entities"] \
        == sorted(r0["service_eids"])
    # the SPMD service RPC executed exactly once on each controller
    assert r0["called"] and r1["called"]
    assert r0["incr_calls"] == r1["incr_calls"] == [5]
