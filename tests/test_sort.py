"""Counting-sort front half (ops/sort.py): bit-parity with stable
argsort — the contract that makes GridSpec.sort_impl a pure lowering
choice (docs/ROOFLINE.md replaces the bitonic-network traffic term with
this kernel). The Pallas form is validated in interpret mode for BOTH
kernel bodies: the "vector" gather form (the interpret default) and the
"serial" body that IS the TPU lowering (2D-tiled VMEM bins + per-element
fill walk, real block specs, no interpret flag on hardware) — so a relay
run exercises a CPU-validated algorithm.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from goworld_tpu.ops.sort import (
    counting_sort_cells,
    counting_sort_cells_pallas,
    row_starts,
)


CASES = [
    # (n, n_rows, chunk): dup-heavy, single-bin, chunk larger than n,
    # chunk not dividing n, many empty bins
    (1000, 37, 128),
    (4096, 1, 8192),
    (777, 500, 100),
    (64, 9, 2048),
    (2048, 2048, 512),
]


def _keys(rng, n, n_rows, dead_frac=0.1):
    """Cell-row keys incl. the dump bin n_rows (dead entities)."""
    srow = rng.integers(0, n_rows, n).astype(np.int32)
    srow[rng.random(n) < dead_frac] = n_rows
    return srow


@pytest.mark.parametrize("n,n_rows,chunk", CASES)
def test_counting_sort_matches_stable_argsort(n, n_rows, chunk):
    rng = np.random.default_rng(n + n_rows)
    srow = _keys(rng, n, n_rows)
    ref = np.argsort(srow, kind="stable").astype(np.int32)
    order, sorted_row = counting_sort_cells(
        jnp.asarray(srow), n_rows, chunk
    )
    assert np.array_equal(np.asarray(order), ref)
    assert np.array_equal(np.asarray(sorted_row), srow[ref])


@pytest.mark.pallas
@pytest.mark.parametrize("lowering", ["vector", "serial"])
@pytest.mark.parametrize("n,n_rows,chunk", CASES[:3])
def test_pallas_kernel_interpret_parity(n, n_rows, chunk, lowering):
    """Both kernel bodies — the vector-gather interpret form and the
    serial body that is the real TPU lowering — must match stable
    argsort bit-for-bit under interpret mode."""
    rng = np.random.default_rng(3 * n + n_rows)
    srow = _keys(rng, n, n_rows)
    ref = np.argsort(srow, kind="stable").astype(np.int32)
    order, sorted_row = counting_sort_cells_pallas(
        jnp.asarray(srow), n_rows, chunk, interpret=True,
        lowering=lowering,
    )
    assert np.array_equal(np.asarray(order), ref)
    assert np.array_equal(np.asarray(sorted_row), srow[ref])


@pytest.mark.pallas
def test_pallas_lowering_knob_validated():
    with pytest.raises(ValueError, match=r"auto\|serial\|vector"):
        counting_sort_cells_pallas(
            jnp.zeros(8, jnp.int32), 4, lowering="bogus"
        )


@pytest.mark.pallas
def test_serial_lowering_wide_bin_space():
    """More bins than one 128-lane row (the 2D [ceil(bins/128), 128]
    VMEM tile actually wraps) and a non-multiple-of-128 bin count."""
    rng = np.random.default_rng(77)
    n, n_rows = 3000, 1000          # nrp = ceil(1001/128) = 8 rows
    srow = _keys(rng, n, n_rows)
    ref = np.argsort(srow, kind="stable").astype(np.int32)
    order, sorted_row = counting_sort_cells_pallas(
        jnp.asarray(srow), n_rows, 512, interpret=True,
        lowering="serial",
    )
    assert np.array_equal(np.asarray(order), ref)
    assert np.array_equal(np.asarray(sorted_row), srow[ref])


def test_chunk_size_is_pure_execution_knob():
    rng = np.random.default_rng(11)
    srow = _keys(rng, 1500, 64)
    outs = [
        np.asarray(counting_sort_cells(jnp.asarray(srow), 64, c)[0])
        for c in (1, 7, 256, 1500, 4096)
    ]
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)


def test_row_starts_exclusive_cumsum():
    srow = np.array([2, 0, 2, 5, 0, 2], np.int32)
    starts = np.asarray(row_starts(jnp.asarray(srow), 5))
    # bins: 0 -> 2 elems, 2 -> 3, 5(dump) -> 1
    assert starts.tolist() == [0, 2, 2, 5, 5, 5]


def test_all_same_and_degenerate_bins():
    srow = np.full(300, 7, np.int32)
    order, sorted_row = counting_sort_cells(jnp.asarray(srow), 20, 64)
    assert np.array_equal(np.asarray(order), np.arange(300))
    assert np.all(np.asarray(sorted_row) == 7)
