"""Behavior-tree kernel (BASELINE config 5) vs a scalar oracle.

The fused tree must decide exactly like a per-entity interpreter of the
same tree (reference control flow: examples/unity_demo/Monster.go:32-100 —
chase nearest player in AOI, else wander)."""

import jax
import jax.numpy as jnp
import numpy as np

from goworld_tpu.core.state import WorldConfig, create_state, spawn
from goworld_tpu.core.step import TickInputs, make_tick
from goworld_tpu.models.behavior_tree import (
    BTFeatures, btree_velocity, features_from_neighbors,
)
from goworld_tpu.ops.aoi import GridSpec, grid_neighbors


def scalar_oracle(i, client_cnt, nbr_cnt, client_off, mean_off, speed,
                  crowd_threshold=12):
    """Per-entity decision of monster_tree (selector order)."""
    def toward(off, sign):
        n = np.sqrt(off[0] ** 2 + off[2] ** 2 + 1e-6)
        return sign * speed * np.array([off[0] / n, 0.0, off[2] / n])
    if client_cnt[i] > 0:
        return "chase", toward(client_off[i], 1.0)
    if nbr_cnt[i] >= crowd_threshold:
        return "separate", toward(mean_off[i], -1.0)
    return "wander", None    # random; only the branch is checked


def test_btree_matches_scalar_oracle():
    n = 128
    rng = np.random.default_rng(4)
    pos = np.zeros((n, 3), np.float32)
    pos[:, 0] = rng.uniform(0, 300, n)
    pos[:, 2] = rng.uniform(0, 300, n)
    # a dense cluster to trigger "crowded", far from any client so the
    # higher-priority chase branch cannot shadow it
    pos[40:60, 0] = 250.0 + rng.uniform(-3, 3, 20)
    pos[40:60, 2] = 250.0 + rng.uniform(-3, 3, 20)
    has_client = (rng.uniform(size=n) < 0.15) & (pos[:, 0] < 150) \
        & (pos[:, 2] < 150)
    alive = np.ones(n, bool)
    spec = GridSpec(radius=30.0, extent_x=300.0, extent_z=300.0,
                    k=64, cell_cap=64, row_block=64)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    feats = features_from_neighbors(
        jnp.asarray(pos), jnp.asarray(has_client), nbr, cnt
    )
    moving = jnp.ones(n, bool)
    vel0 = jnp.zeros((n, 3))
    out = btree_velocity(
        jax.random.PRNGKey(0), feats, vel0, moving, speed=5.0,
        turn_prob=0.1,
    )
    out = np.asarray(out)
    fc = np.asarray(feats.client_cnt)
    fn = np.asarray(feats.nbr_cnt)
    fo = np.asarray(feats.client_off)
    fm = np.asarray(feats.mean_off)
    checked_branches = set()
    for i in range(n):
        branch, want = scalar_oracle(i, fc, fn, fo, fm, 5.0)
        checked_branches.add(branch)
        if want is not None:
            np.testing.assert_allclose(out[i], want, atol=1e-4,
                                       err_msg=f"row {i} ({branch})")
        # wander rows: speed-capped random walk, just bounded
        assert np.sqrt(out[i, 0] ** 2 + out[i, 2] ** 2) <= 5.0 + 1e-4
    # the workload must actually exercise every branch
    assert checked_branches == {"chase", "separate", "wander"}


def test_btree_chases_the_nearest_player():
    n = 8
    pos = np.zeros((n, 3), np.float32)
    pos[0] = (50, 0, 50)       # the monster
    pos[1] = (60, 0, 50)       # nearer player
    pos[2] = (80, 0, 50)       # farther player
    has_client = np.zeros(n, bool)
    has_client[1] = has_client[2] = True
    alive = np.zeros(n, bool)
    alive[:3] = True
    spec = GridSpec(radius=40.0, extent_x=128.0, extent_z=128.0,
                    k=8, cell_cap=8, row_block=8)
    nbr, cnt = grid_neighbors(spec, jnp.asarray(pos), jnp.asarray(alive))
    feats = features_from_neighbors(
        jnp.asarray(pos), jnp.asarray(has_client), nbr, cnt
    )
    vel = btree_velocity(
        jax.random.PRNGKey(1), feats,
        jnp.zeros((n, 3)), jnp.asarray(alive), speed=4.0, turn_prob=0.0,
    )
    v0 = np.asarray(vel)[0]
    assert v0[0] > 3.9 and abs(v0[2]) < 1e-3   # straight +x toward slot 1


def test_world_tick_with_btree_behavior():
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=20.0, extent_x=100.0, extent_z=100.0,
                      k=16, cell_cap=16, row_block=64),
        behavior="btree",
        npc_speed=6.0,
        enter_cap=512, leave_cap=512, sync_cap=512,
        attr_sync_cap=64, input_cap=4,
    )
    st = create_state(cfg, seed=0)
    rng = np.random.default_rng(0)
    for slot in range(20):
        st = spawn(st, slot,
                   pos=(rng.uniform(0, 100), 0, rng.uniform(0, 100)),
                   npc_moving=True)
    st = spawn(st, 20, pos=(50.0, 0.0, 50.0), has_client=True)
    tick = make_tick(cfg)
    for _ in range(3):
        st, out = tick(st, TickInputs.empty(cfg), None)
    assert int(out.alive_count) == 21
    # nbr_client_cnt is maintained by the sweep: anyone near slot 20 sees 1
    ncc = np.asarray(st.nbr_client_cnt)
    nbr = np.asarray(st.nbr)
    for i in range(20):
        if (nbr[i] == 20).any():
            assert ncc[i] >= 1
    # NPCs near the player chase it: their velocity points toward (50, 50)
    posn = np.asarray(st.pos)
    veln = np.asarray(st.vel)
    chasers = 0
    for i in range(20):
        if (nbr[i] == 20).any():
            to_player = np.array([50.0 - posn[i, 0], 50.0 - posn[i, 2]])
            nrm = np.linalg.norm(to_player)
            if nrm < 1e-3:
                continue
            v = np.array([veln[i, 0], veln[i, 2]])
            if np.linalg.norm(v) > 1e-3:
                cos = v @ to_player / (np.linalg.norm(v) * nrm)
                assert cos > 0.9, f"row {i} not chasing"
                chasers += 1
    assert chasers > 0


def test_mega_btree_chases_cross_border_player():
    """Megaspace behavior-tree: a monster near the tile border must see a
    LOCAL player's has_client bit through the sweep flags and chase along
    the mean-offset feature next tick."""
    from goworld_tpu.parallel import MegaConfig, MultiTickInputs, make_mesh
    from goworld_tpu.parallel.megaspace import (
        create_mega_state, make_mega_tick,
    )
    from goworld_tpu.parallel.mesh import shard_state

    n_dev, tile_w, radius = 8, 100.0, 10.0
    cfg = WorldConfig(
        capacity=16,
        grid=GridSpec(radius=radius, extent_x=tile_w + 2 * radius,
                      extent_z=100.0, k=8, cell_cap=16, row_block=16),
        behavior="btree",
        npc_speed=5.0,
        enter_cap=256, leave_cap=256, sync_cap=256,
    )
    mc = MegaConfig(cfg=cfg, n_dev=n_dev, tile_w=tile_w,
                    halo_cap=8, migrate_cap=4)
    mesh = make_mesh(n_dev)
    step = make_mega_tick(mc, mesh)
    st = create_mega_state(mc)

    from tests.conftest import spawn_on

    # monster on tile 2 at x=250; player 6 units east, same tile
    st = spawn_on(st, 2, 0, pos=(250.0, 0.0, 50.0), npc_moving=True)
    st = spawn_on(st, 2, 1, pos=(256.0, 0.0, 50.0), has_client=True)
    st = shard_state(st, mesh)
    inputs = MultiTickInputs.empty(cfg, n_dev)
    for _ in range(2):   # tick 1 computes flags/features; tick 2 chases
        st, out = step(st, inputs, None)
    jax.block_until_ready(st)
    assert int(np.asarray(st.nbr_client_cnt)[2, 0]) == 1
    v = np.asarray(st.vel)[2, 0]
    # tick-1 wander may add a small z drift before the chase kicks in
    assert v[0] > 4.0 and abs(v[2]) < 1.0, f"not chasing east: {v}"
