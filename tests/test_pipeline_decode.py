"""Pipelined host decode (World(pipeline_decode=True)): tick N's device
step overlaps tick N-1's host event decode. The device trajectory is
UNCHANGED (decode never feeds back into the step); host-visible events
arrive one tick late but none are lost — after a final
flush_pending_outputs(), interest sets, client mirrors, and event
totals must match a non-pipelined world run over the same seed."""

import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec


class Npc(Entity):
    ATTRS = {"name": "allclients"}


class Arena(Space):
    pass


def _world(pipeline: bool, n=96):
    cfg = WorldConfig(
        capacity=n,
        grid=GridSpec(radius=12.0, extent_x=200.0, extent_z=200.0,
                      k=16, cell_cap=32, row_block=n),
        npc_speed=20.0, turn_prob=0.3,
        enter_cap=2048, leave_cap=2048, sync_cap=2048,
        attr_sync_cap=64, input_cap=n, delta_rows_cap=n,
    )
    world = World(cfg, n_spaces=1, seed=5, pipeline_decode=pipeline)
    sent = []
    world.client_sink = lambda g, c, m: sent.append((c, m["type"],
                                                     m.get("eid")))
    world.register_space("Arena", Arena)
    world.register_entity("Npc", Npc)
    world.create_nil_space()
    arena = world.create_space("Arena")
    rng = np.random.default_rng(4)
    pts = rng.uniform(20, 180, size=(n - 16, 2))
    ents = []
    for i in range(n - 16):
        client = GameClient(1, f"CL{i:010d}", world) if i % 9 == 0 \
            else None
        ents.append(world.create_entity(
            "Npc", space=arena, pos=(pts[i, 0], 0.0, pts[i, 1]),
            moving=True, client=client,
        ))
    return world, ents, sent


def _interest_maps(ents):
    return {e.id: (frozenset(e.interested_in),
                   frozenset(e.interested_by)) for e in ents}


def test_pipelined_equals_eager_after_drain():
    wa, ea, sa = _world(False)
    wb, eb, sb = _world(True)
    for _ in range(12):
        wa.tick()
        wb.tick()
    wb.flush_pending_outputs()
    # identical device trajectory -> identical final interest relation
    ma, mb = _interest_maps(ea), _interest_maps(eb)
    # entity ids differ between worlds; compare by creation order
    for a, b in zip(ea, eb):
        ia, _ = ma[a.id]
        ib, _ = mb[b.id]
        # map a-world ids to creation indices for comparison
        idx_a = {e.id: i for i, e in enumerate(ea)}
        idx_b = {e.id: i for i, e in enumerate(eb)}
        assert {idx_a[x] for x in ia} == {idx_b[x] for x in ib}, \
            f"interest mismatch for entity #{idx_a[a.id]}"
    # same client message multiset (order may shift by one tick)
    def norm(sent, idx):
        out = []
        for cid, t, eid in sent:
            out.append((cid, t, idx.get(eid, eid)))
        return sorted(out)

    assert norm(sa, {e.id: i for i, e in enumerate(ea)}) \
        == norm(sb, {e.id: i for i, e in enumerate(eb)})


def test_pipeline_lags_exactly_one_tick():
    wb, eb, _ = _world(True)
    wb.tick()
    # first tick's outputs are pending, nothing decoded yet
    assert wb._pending_outs is not None
    assert all(not e.interested_in for e in eb)
    wb.tick()
    # now tick 1's spawn-wave enters have decoded
    assert any(e.interested_in for e in eb)


def test_pipeline_rejected_on_mesh_and_mega():
    cfg = WorldConfig(
        capacity=32,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=32),
    )
    from goworld_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="pipeline_decode"):
        World(cfg, n_spaces=8, mesh=make_mesh(8), pipeline_decode=True)


def test_freeze_drains_pending():
    from goworld_tpu import freeze as freeze_mod

    wb, eb, _ = _world(True)
    for _ in range(3):
        wb.tick()
    assert wb._pending_outs is not None
    data = freeze_mod.freeze_world(wb)
    assert wb._pending_outs is None          # drained before snapshot
    assert data is not None
    # the snapshot's host interest state includes the last tick's events
    assert any(e.interested_in for e in eb)


def test_pipelined_churn_with_slot_reuse_matches_eager():
    """The quarantine skew: a destroyed entity's slot must not free
    before its leave events decode, even though pipelined decode runs
    one tick behind — otherwise a reused slot captures the old
    entity's leaves (spurious client destroys, stuck interest). Drive
    identical create/destroy churn through both modes on a SMALL
    capacity (forcing reuse) and require identical final state."""
    def run(pipeline: bool):
        world, ents, sent = _world(pipeline, n=48)
        rng = np.random.default_rng(9)
        alive = list(ents)
        created = list(ents)
        for t in range(16):
            if len(alive) > 8:
                victim = alive.pop(int(rng.integers(len(alive))))
                world.destroy_entity(victim)
            e = world.create_entity(
                "Npc", space=alive[0].space,
                pos=(float(rng.uniform(20, 180)), 0.0,
                     float(rng.uniform(20, 180))),
                moving=True,
            )
            alive.append(e)
            created.append(e)
            world.tick()
        world.flush_pending_outputs()
        idx = {e.id: i for i, e in enumerate(created)}
        state = sorted(
            (idx[e.id], frozenset(idx[x] for x in e.interested_in
                                  if x in idx))
            for e in alive if not e.destroyed
        )
        msgs = sorted((c, ty, idx.get(eid, eid)) for c, ty, eid in sent)
        return state, msgs

    sa, ma = run(False)
    sb, mb = run(True)
    assert sa == sb
    assert ma == mb


def test_pipeline_rejected_on_megaspace():
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=64),
    )
    from goworld_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="pipeline_decode"):
        World(cfg, n_spaces=8, mesh=make_mesh(8), megaspace=True,
              halo_cap=64, migrate_cap=32, pipeline_decode=True)
