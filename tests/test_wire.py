"""Wire layer: packet framing, proto pack/unpack, native/numpy codec parity.

Mirrors the reference's serialization unit tests
(``engine/netutil/MsgPacker_test.go``, packet round-trips).
"""

import numpy as np
import pytest

from goworld_tpu.net import codec, proto
from goworld_tpu.net.packet import Packet, frame, new_packet
from goworld_tpu.utils import ids


def test_packet_roundtrip_scalars():
    p = new_packet(42)
    p.append_bool(True)
    p.append_u8(200)
    p.append_u16(0xBEEF)
    p.append_u32(0xDEADBEEF)
    p.append_f32(1.5)
    eid = ids.gen_entity_id()
    p.append_entity_id(eid)
    p.append_var_str("héllo wörld")
    p.append_var_bytes(b"\x00\x01\x02")
    p.append_data({"a": [1, 2.5, "x"], "b": None})
    p.append_args((1, "two", [3.0], {"k": b"v"}))

    q = Packet(bytes(p.buf))
    assert q.read_u16() == 42
    assert q.read_bool() is True
    assert q.read_u8() == 200
    assert q.read_u16() == 0xBEEF
    assert q.read_u32() == 0xDEADBEEF
    assert q.read_f32() == 1.5
    assert q.read_entity_id() == eid
    assert q.read_var_str() == "héllo wörld"
    assert q.read_var_bytes() == b"\x00\x01\x02"
    assert q.read_data() == {"a": [1, 2.5, "x"], "b": None}
    assert q.read_args() == [1, "two", [3.0], {"k": b"v"}]
    assert q.remaining() == 0


def test_packet_underrun_raises():
    p = Packet(b"\x01")
    with pytest.raises(EOFError):
        p.read_u32()


def test_frame_and_scan():
    packets = []
    for i in range(5):
        p = new_packet(proto.MT_HEARTBEAT)
        p.append_u32(i)
        packets.append(frame(p))
    stream = b"".join(packets)
    # a partial 6th packet at the tail
    stream += packets[0][:5]
    frames, consumed = codec.scan_frames(stream)
    assert len(frames) == 5
    assert consumed == sum(len(x) for x in packets)
    for i, (off, size) in enumerate(frames):
        q = Packet(stream[off:off + size])
        assert q.read_u16() == proto.MT_HEARTBEAT
        assert q.read_u32() == i


def test_scan_malformed_raises():
    bad = (10**9).to_bytes(4, "little") + b"xx"
    with pytest.raises(ConnectionError):
        codec.scan_frames(bad)


def test_sync_batch_roundtrip():
    n = 257
    rng = np.random.default_rng(0)
    eids = [ids.gen_entity_id() for _ in range(n)]
    vals = rng.standard_normal((n, 4)).astype(np.float32)
    buf = codec.encode_sync_batch(eids, vals)
    assert len(buf) == n * proto.SYNC_RECORD_SIZE
    out_ids, out_vals = codec.decode_sync_batch(buf)
    assert [b.decode() for b in out_ids] == eids
    np.testing.assert_array_equal(out_vals, vals)


def test_client_sync_batch_roundtrip():
    n = 63
    rng = np.random.default_rng(1)
    cids = [ids.gen_entity_id() for _ in range(n)]
    eids = [ids.gen_entity_id() for _ in range(n)]
    vals = rng.standard_normal((n, 4)).astype(np.float32)
    buf = codec.encode_client_sync_batch(cids, eids, vals)
    assert len(buf) == n * proto.CLIENT_SYNC_RECORD_SIZE
    oc, oe, ov = codec.decode_client_sync_batch(buf)
    assert [b.decode() for b in oc] == cids
    assert [b.decode() for b in oe] == eids
    np.testing.assert_array_equal(ov, vals)


def test_native_numpy_parity():
    """The C++ codec and the numpy fallback must produce identical bytes."""
    if not codec.native_available():
        pytest.skip("native codec unavailable")
    n = 100
    rng = np.random.default_rng(2)
    eids = [ids.gen_entity_id() for _ in range(n)]
    vals = rng.standard_normal((n, 4)).astype(np.float32)
    native = codec.encode_sync_batch(eids, vals)
    rec = np.empty(n, codec.SYNC_DTYPE)
    rec["eid"] = np.asarray(eids, "S16")
    rec["v"] = vals
    assert native == rec.tobytes()


def test_bucket_by_shard():
    shard_of = np.array([0, 1, 0, 2, -1, 1, 0, 0], np.int32)
    idx, counts = codec.bucket_by_shard(shard_of, 3, capacity=3)
    assert counts.tolist() == [3, 2, 1]  # 4th shard-0 record dropped (cap)
    assert idx[0, :3].tolist() == [0, 2, 6]
    assert idx[1, :2].tolist() == [1, 5]
    assert idx[2, :1].tolist() == [3]


def test_proto_call_entity_method_roundtrip():
    eid = ids.gen_entity_id()
    cid = ids.gen_entity_id()
    p = proto.pack_call_entity_method(eid, "TestMethod", (1, "a"), cid)
    q = Packet(bytes(p.buf))
    assert q.read_u16() == proto.MT_CALL_ENTITY_METHOD_FROM_CLIENT
    assert q.read_entity_id() == eid
    assert q.read_entity_id() == cid
    assert q.read_var_str() == "TestMethod"
    assert q.read_args() == [1, "a"]


def test_proto_create_entity_on_client_roundtrip():
    cid = ids.gen_entity_id()
    eid = ids.gen_entity_id()
    p = proto.pack_create_entity_on_client(
        3, cid, eid, "Avatar", True, {"name": "bob"}, (1.0, 2.0, 3.0), 0.5
    )
    q = Packet(bytes(p.buf))
    assert q.read_u16() == proto.MT_CREATE_ENTITY_ON_CLIENT
    assert q.read_u16() == 3
    assert q.read_entity_id() == cid
    assert q.read_entity_id() == eid
    assert q.read_var_str() == "Avatar"
    assert q.read_bool() is True
    assert [q.read_f32() for _ in range(4)] == [1.0, 2.0, 3.0, 0.5]
    assert q.read_data() == {"name": "bob"}


def test_create_load_anywhere_carry_routing_gameid():
    """The placement messages carry a leading routing gameid (0 = choose)
    that the dispatcher consumes and the game skips — both readers must
    agree with the packer."""
    from goworld_tpu.net import proto

    p = proto.pack_create_entity_anywhere("Avatar", {"hp": 5},
                                          "abcdefghabcdefgh", gameid=3)
    p.rpos = 2
    assert p.read_u16() == 3
    assert p.read_var_str() == "Avatar"
    assert p.read_var_str() == "abcdefghabcdefgh"
    assert p.read_data() == {"hp": 5}

    p = proto.pack_load_entity_anywhere("Avatar", "abcdefghabcdefgh",
                                        gameid=0)
    p.rpos = 2
    assert p.read_u16() == 0
    assert p.read_var_str() == "Avatar"
    assert p.read_entity_id() == "abcdefghabcdefgh"


def test_client_events_batch_roundtrip_and_order():
    """MT_CLIENT_EVENTS_BATCH bundles redirect-range client messages
    per gate per tick; the gate must recover each record's msgtype and
    a body byte-identical to the per-message packet minus its
    [u16 msgtype][u16 gate_id] prefix, in emission order."""
    cid = "c" * ids.ENTITYID_LENGTH
    eid = "e" * ids.ENTITYID_LENGTH
    singles = [
        proto.pack_create_entity_on_client(
            3, cid, eid, "Avatar", True, {"hp": 7}, (1.0, 2.0, 3.0), 0.5),
        proto.pack_notify_attr_change_on_client(
            3, cid, eid, [{"path": ["hp"], "op": "set", "value": 8}]),
        proto.pack_destroy_entity_on_client(3, cid, eid, False),
        proto.pack_call_entity_method_on_client(
            3, cid, eid, "Ping_Client", (1, "x")),
    ]
    recs = []
    for p in singles:
        mt = int.from_bytes(bytes(p.buf[0:2]), "little")
        recs.append((mt, bytes(memoryview(p.buf)[4:])))

    batch = proto.pack_client_events_batch(3, recs)
    pkt = Packet(bytes(batch.buf))
    assert pkt.read_u16() == proto.MT_CLIENT_EVENTS_BATCH
    assert pkt.read_u16() == 3
    assert pkt.read_u32() == len(recs)
    for want_mt, want_body in recs:
        mt = pkt.read_u16()
        ln = pkt.read_u32()
        body = bytes(memoryview(pkt.buf)[pkt.rpos:pkt.rpos + ln])
        pkt.rpos += ln
        assert mt == want_mt
        assert body == want_body
    assert pkt.remaining() == 0
    # each body starts at the 16B client id, as _relay_to_client reads
    rec = Packet(recs[0][1])
    assert rec.read_entity_id() == cid
    assert rec.read_entity_id() == eid
