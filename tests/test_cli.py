"""Ops CLI end-to-end: start / status / reload (freeze+restore) / stop.

Mirrors the reference's CI game test (``test_game.yml:34-46``): start the
cluster from a server directory, drive it with a client, live-reload, drive
it again, stop — but at unit scale with one bot."""

import asyncio
import os
import shutil
import socket
import subprocess
import sys
import time

import pytest

from goworld_tpu import cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def server_dir(tmp_path):
    src = os.path.join(REPO, "examples", "nil_game")
    dst = str(tmp_path / "nil_game")
    shutil.copytree(src, dst)
    dport, gport = _free_port(), _free_port()
    ini = os.path.join(dst, "goworld_tpu.ini")
    with open(ini) as f:
        text = f.read()
    text = text.replace("port = 14300", f"port = {dport}")
    text = text.replace("port = 15300", f"port = {gport}")
    with open(ini, "w") as f:
        f.write(text)
    yield dst, gport
    cli.cmd_stop(dst)


async def _bot_session(port: int, expect_status: str = "online"):
    from goworld_tpu.net.botclient import BotClient

    bot = BotClient("127.0.0.1", port)
    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 15)
        assert bot.player.type_name == "Account"
        for _ in range(100):
            if bot.player.attrs.get("status") == expect_status:
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("status") == expect_status
    finally:
        recv.cancel()
        await bot.conn.close()
    return bot


def test_cli_start_reload_stop(server_dir):
    dst, gport = server_dir
    assert cli.cmd_start(dst) == 0, _logs(dst)
    try:
        assert cli.cmd_status(dst) == 0

        asyncio.run(_bot_session(gport))

        # hot reload: SIGHUP -> freeze file -> -restore restart
        assert cli.cmd_reload(dst) == 0, _logs(dst)
        assert cli.cmd_status(dst) == 0

        asyncio.run(_bot_session(gport))
    finally:
        assert cli.cmd_stop(dst) == 0
    assert cli.cmd_status(dst) == 1  # everything reported stopped


def _logs(server_dir: str) -> str:
    out = []
    rd = os.path.join(server_dir, "run")
    if os.path.isdir(rd):
        for name in sorted(os.listdir(rd)):
            if name.endswith(".log"):
                with open(os.path.join(rd, name), errors="replace") as f:
                    out.append(f"==== {name} ====\n" + f.read()[-4000:])
    return "\n".join(out)


def test_sample_config_prints(capsys):
    assert cli.main(["sample-config"]) == 0
    assert "[dispatcher1]" in capsys.readouterr().out
