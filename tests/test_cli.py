"""Ops CLI end-to-end: start / status / reload (freeze+restore) / stop.

Mirrors the reference's CI game test (``test_game.yml:34-46``): start the
cluster from a server directory, drive it with a client, live-reload, drive
it again, stop — but at unit scale with one bot."""

import asyncio
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

import pytest

from goworld_tpu import cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _copy_example(name: str, tmp_path, dport_old: int,
                  gport_old: int) -> tuple[str, int]:
    """Copy an example server dir and rebind its dispatcher/gate ports
    to free ones; asserts the rewrites actually happened (a changed ini
    default would otherwise silently bind the stock port and collide
    with parallel runs)."""
    src = os.path.join(REPO, "examples", name)
    dst = str(tmp_path / name)
    shutil.copytree(src, dst)
    dport, gport = _free_port(), _free_port()
    ini = os.path.join(dst, "goworld_tpu.ini")
    with open(ini) as f:
        text = f.read()
    for old, new in ((f"port = {dport_old}", f"port = {dport}"),
                     (f"port = {gport_old}", f"port = {gport}")):
        assert old in text, f"{name} ini default moved: {old!r} missing"
        text = text.replace(old, new)
    with open(ini, "w") as f:
        f.write(text)
    return dst, gport


@pytest.fixture()
def server_dir(tmp_path):
    dst, gport = _copy_example("nil_game", tmp_path, 14300, 15300)
    yield dst, gport
    cli.cmd_stop(dst)


async def _bot_session(port: int, expect_status: str = "online"):
    from goworld_tpu.net.botclient import BotClient

    bot = BotClient("127.0.0.1", port)
    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 15)
        assert bot.player.type_name == "Account"
        for _ in range(100):
            if bot.player.attrs.get("status") == expect_status:
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("status") == expect_status
    finally:
        recv.cancel()
        await bot.conn.close()
    return bot


def test_cli_start_reload_stop(server_dir):
    dst, gport = server_dir
    assert cli.cmd_start(dst) == 0, _logs(dst)
    try:
        assert cli.cmd_status(dst) == 0

        asyncio.run(_bot_session(gport))

        # hot reload: SIGHUP -> freeze file -> -restore restart
        assert cli.cmd_reload(dst) == 0, _logs(dst)
        assert cli.cmd_status(dst) == 0

        asyncio.run(_bot_session(gport))
    finally:
        assert cli.cmd_stop(dst) == 0
    assert cli.cmd_status(dst) == 1  # everything reported stopped


def _logs(server_dir: str) -> str:
    out = []
    rd = os.path.join(server_dir, "run")
    if os.path.isdir(rd):
        for name in sorted(os.listdir(rd)):
            if name.endswith(".log"):
                with open(os.path.join(rd, name), errors="replace") as f:
                    out.append(f"==== {name} ====\n" + f.read()[-4000:])
    return "\n".join(out)


def test_sample_config_prints(capsys):
    assert cli.main(["sample-config"]) == 0
    assert "[dispatcher1]" in capsys.readouterr().out


def test_sample_config_loads(tmp_path):
    """The emitted sample must round-trip through the real loader —
    ConfigParser has no inline-comment support, so a trailing `# ...`
    on a value line would crash every process at boot."""
    from goworld_tpu import config as config_mod

    ini = tmp_path / "goworld_tpu.ini"
    ini.write_text(config_mod.dumps_sample())
    cfg = config_mod.load(str(ini))
    assert cfg.gates[1].heartbeat_timeout == 60.0
    assert cfg.games[1].capacity == 16384


def test_watchdog_single_process_crash_and_deliberate_stop(server_dir):
    """Fast watchdog semantics on a 1-proc-per-role cluster: a healthy
    scan is a no-op; a SIGKILLed game (crash = dead process with its
    pidfile still present) is restarted; a gate crash respawns in
    place; a DELIBERATE `stop` (pidfiles unlinked) is never resurrected."""
    dst, gport = server_dir
    assert cli.cmd_start(dst) == 0, _logs(dst)
    try:
        assert cli.watch_once(dst) == []  # healthy: nothing to do

        # crash the game (SIGKILL leaves the pidfile behind)
        pid = cli._read_pid(dst, "game", 1)
        os.kill(pid, signal.SIGKILL)
        t0 = time.time()
        while time.time() - t0 < 10 and cli._alive(pid):
            time.sleep(0.05)
        actions = cli.watch_once(dst)
        assert any(a.startswith("game1: restarted") for a in actions), \
            actions
        assert cli.cmd_status(dst) == 0, _logs(dst)
        asyncio.run(_bot_session(gport))  # the restarted game serves

        # crash the gate: respawned in place
        gpid = cli._read_pid(dst, "gate", 1)
        os.kill(gpid, signal.SIGKILL)
        t0 = time.time()
        while time.time() - t0 < 10 and cli._alive(gpid):
            time.sleep(0.05)
        actions = cli.watch_once(dst)
        assert "gate1: restarted" in actions, actions
        assert cli.cmd_status(dst) == 0, _logs(dst)
    finally:
        assert cli.cmd_stop(dst) == 0
    # deliberate stop: watchdog must NOT resurrect anything
    assert cli.watch_once(dst) == []
    assert cli.cmd_status(dst) == 1


def test_deployment_counts_autocreate_sections(tmp_path):
    """[deployment] declares desired counts (reference read_config.go:
    40-118): counts beyond the numbered sections create defaults from
    *_common, and the count keys never clobber the parsed dicts."""
    from goworld_tpu import config as config_mod

    ini = tmp_path / "goworld.ini"
    ini.write_text(
        "[deployment]\n"
        "dispatchers = 2\n"
        "games = 3\n"
        "gates = 1\n"
        "[dispatcher1]\n"
        "port = 14100\n"
        "[game_common]\n"
        "capacity = 512\n"
        "behavior = btree\n"
        "[game1]\n"
        "capacity = 1024\n"
        "[gate1]\n"
        "port = 15100\n"
    )
    cfg = config_mod.load(str(ini))
    assert sorted(cfg.dispatchers) == [1, 2]
    assert sorted(cfg.games) == [1, 2, 3]
    assert cfg.desired_games == 3
    # explicit section keeps its override; auto-created ones get _common
    assert cfg.games[1].capacity == 1024
    assert cfg.games[2].capacity == 512
    assert cfg.games[2].behavior == "btree"
    assert cfg.gates[1].port == 15100


def test_deployment_counts_offset_ports_and_truncate(tmp_path):
    """Auto-created listeners get per-index port offsets (no EADDRINUSE
    at start) and sections beyond the declared count are dropped."""
    from goworld_tpu import config as config_mod

    ini = tmp_path / "goworld.ini"
    ini.write_text(
        "[deployment]\n"
        "dispatchers = 3\n"
        "games = 1\n"
        "gates = 2\n"
        "[dispatcher_common]\n"
        "port = 14100\n"
        "[dispatcher1]\n"
        "port = 14000\n"
        "[game1]\n"
        "[game2]\n"          # beyond the declared count: dropped
        "[gate_common]\n"
        "port = 15100\n"
        "kcp_port = 15200\n"
    )
    cfg = config_mod.load(str(ini))
    assert cfg.dispatchers[1].port == 14000          # explicit wins
    assert cfg.dispatchers[2].port == 14101          # common + offset
    assert cfg.dispatchers[3].port == 14102
    assert sorted(cfg.games) == [1]                  # truncated to count
    assert cfg.gates[1].port == 15100 and cfg.gates[1].kcp_port == 15200
    assert cfg.gates[2].port == 15101 and cfg.gates[2].kcp_port == 15201


def test_port_collisions_detected(tmp_path):
    """An explicit section inheriting a _common port must not silently
    collide with an auto-created sibling (EADDRINUSE at start)."""
    import pytest

    from goworld_tpu import config as config_mod

    ini = tmp_path / "goworld.ini"
    ini.write_text(
        "[deployment]\n"
        "dispatchers = 2\n"
        "[dispatcher_common]\n"
        "port = 14100\n"
        "[dispatcher2]\n"   # explicit but empty: inherits 14100 verbatim
        "[game1]\n"
        "[gate1]\n"
        "port = 15000\n"
    )
    with pytest.raises(ValueError, match="collides"):
        config_mod.load(str(ini))


@pytest.mark.slow
def test_cli_start_megaspace_demo(tmp_path):
    """The flagship path through production ops: `start` the megaspace
    demo (one space over a 4x2 8-device mesh, btree NPCs), log a real
    client in over the gate, `stop` — the same flow a reference operator
    runs, with the device mesh underneath."""
    import shutil as _shutil

    src = os.path.join(REPO, "examples", "megaspace_demo")
    dst = str(tmp_path / "megaspace_demo")
    _shutil.copytree(src, dst)
    gport = _free_port()
    ini = os.path.join(dst, "goworld_tpu.ini")
    with open(ini) as f:
        text = f.read()
    text = text.replace("port = 15400", f"port = {gport}")
    with open(ini, "w") as f:
        f.write(text)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    try:
        r = subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "start", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=240,
        )
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]

        async def login():
            from goworld_tpu.net.botclient import BotClient

            bot = BotClient("127.0.0.1", gport, strict=True)
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 20)
                bot.call_server("Login_Client", "opstest")
                for _ in range(150):
                    if bot.player is not None \
                            and bot.player.type_name == "Avatar":
                        break
                    await asyncio.sleep(0.1)
                assert bot.player.type_name == "Avatar"
            finally:
                recv.cancel()
                await bot.conn.close()

        asyncio.run(asyncio.wait_for(login(), 60))
    finally:
        subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "stop", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=120,
        )


@pytest.mark.slow
def test_cli_start_multihost_demo(tmp_path):
    """Production ops for a MULTI-CONTROLLER game: `start` spawns two
    SPMD controller processes for game1 (shared jax.distributed
    coordinator from the ini's mesh_processes = 2), a real client logs
    in through the gate, its Avatar lands on the SECOND controller's
    half of the world and still receives create/sync traffic
    (cross-controller visibility through the dispatcher wire), `status`
    shows both controller processes, `stop` tears everything down."""
    import shutil as _shutil

    src = os.path.join(REPO, "examples", "multihost_demo")
    dst = str(tmp_path / "multihost_demo")
    _shutil.copytree(src, dst)
    gport = _free_port()
    dport = _free_port()
    ini = os.path.join(dst, "goworld_tpu.ini")
    with open(ini) as f:
        text = f.read()
    text = text.replace("port = 15500", f"port = {gport}")
    text = text.replace("port = 14500", f"port = {dport}")
    with open(ini, "w") as f:
        f.write(text)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # 4 virtual devices PER controller process -> 8-device global mesh
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO
    try:
        r = subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "start", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "game1c0: started" in r.stdout, r.stdout
        assert "game1c1: started" in r.stdout, r.stdout

        st = subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "status", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=60,
        )
        assert st.returncode == 0, st.stdout
        assert "game1c0: running" in st.stdout
        assert "game1c1: running" in st.stdout

        async def session():
            from goworld_tpu.net.botclient import BotClient

            bot = BotClient("127.0.0.1", gport, strict=True)
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                await asyncio.wait_for(bot.player_ready.wait(), 30)
                bot.call_server("Login_Client", "mhops")
                for _ in range(200):
                    if bot.player is not None \
                            and bot.player.type_name == "Avatar" \
                            and bot.sync_count > 0 \
                            and any(not m.is_player
                                    for m in bot.entities.values()):
                        break
                    await asyncio.sleep(0.1)
                assert bot.player is not None
                assert bot.player.type_name == "Avatar"
                # the avatar sits at x=600: controller 1's half; its
                # visible monsters + syncs crossed the dispatcher wire
                assert any(not m.is_player for m in bot.entities.values())
                assert bot.sync_count > 0
                assert not bot.errors, bot.errors

                # live reload of the WHOLE controller group: SIGHUP to
                # the leader, freeze spreads through the exchange, both
                # ranks snapshot + exit, the CLI restarts them with
                # -restore — and the still-connected bot's syncs resume
                r2 = await asyncio.to_thread(
                    subprocess.run,
                    [sys.executable, "-m", "goworld_tpu", "reload", dst],
                    env=env, cwd=dst, capture_output=True, text=True,
                    timeout=300,
                )
                assert r2.returncode == 0, \
                    r2.stdout[-2000:] + r2.stderr[-2000:]
                assert "game1: reloaded" in r2.stdout, r2.stdout
                s0 = bot.sync_count
                t0 = time.time()
                while time.time() - t0 < 90 and bot.sync_count <= s0:
                    await asyncio.sleep(0.2)
                assert bot.sync_count > s0, \
                    "syncs never resumed after the multihost reload"
                assert not bot.errors, bot.errors
            finally:
                recv.cancel()
                await bot.conn.close()

        asyncio.run(asyncio.wait_for(session(), 500))
    finally:
        subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "stop", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=120,
        )


@pytest.mark.slow
def test_watchdog_recovers_killed_multihost_rank(tmp_path):
    """Supervised crash recovery (VERDICT r3 #4): SIGKILL one controller
    rank of a live 2-rank multihost group while a strict bot is
    connected. `watchdog --once` detects the dead rank, tears down the
    survivor (a partial group cannot be healed — the jax coordinator
    cannot re-admit a rank), restarts the whole group with -restore from
    the periodic checkpoint (checkpoint_interval in the demo ini), and
    the still-connected bot's syncs resume. The reference's model is
    reconnect-forever (DispatcherConnMgr.go:63-85) with total state loss
    on an unfrozen crash; this recovers the world too."""
    import shutil as _shutil

    src = os.path.join(REPO, "examples", "multihost_demo")
    dst = str(tmp_path / "multihost_demo")
    _shutil.copytree(src, dst)
    gport = _free_port()
    dport = _free_port()
    ini = os.path.join(dst, "goworld_tpu.ini")
    with open(ini) as f:
        text = f.read()
    text = text.replace("port = 15500", f"port = {gport}")
    text = text.replace("port = 14500", f"port = {dport}")
    with open(ini, "w") as f:
        f.write(text)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO
    try:
        r = subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "start", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]

        async def session():
            from goworld_tpu.net.botclient import BotClient

            bot = BotClient("127.0.0.1", gport, strict=True)
            await bot.connect()
            recv = asyncio.ensure_future(bot._recv_loop())
            try:
                # generous: the logic thread is blocked during the
                # first-tick compile on a loaded CI box
                await asyncio.wait_for(bot.player_ready.wait(), 90)
                bot.call_server("Login_Client", "crashtest")
                for _ in range(200):
                    if bot.player is not None \
                            and bot.player.type_name == "Avatar" \
                            and bot.sync_count > 0:
                        break
                    await asyncio.sleep(0.1)
                assert bot.player is not None
                assert bot.player.type_name == "Avatar"

                # wait for a periodic checkpoint NEWER than the login
                # (3 s cadence): killing before the avatar is captured
                # would restore a correctly-older world without it —
                # bounded loss, but not what this test asserts on
                t_login = time.time()
                ckpt = os.path.join(dst, "game1_checkpoint.dat")
                t0 = time.time()
                while time.time() - t0 < 90 and (
                    not os.path.exists(ckpt)
                    or os.path.getmtime(ckpt) < t_login + 1.0
                ):
                    await asyncio.sleep(0.5)
                assert os.path.exists(ckpt) \
                    and os.path.getmtime(ckpt) >= t_login + 1.0, \
                    "no post-login periodic checkpoint"

                # CRASH: SIGKILL the rank-1 controller (no freeze, no
                # goodbye)
                with open(os.path.join(dst, "run", "game1c1.pid")) as f:
                    pid1 = int(f.read().strip())
                os.kill(pid1, signal.SIGKILL)
                t0 = time.time()
                while time.time() - t0 < 10:
                    try:
                        os.kill(pid1, 0)
                        await asyncio.sleep(0.1)
                    except OSError:
                        break

                # supervised recovery: one watchdog scan heals the group
                wd = await asyncio.to_thread(
                    subprocess.run,
                    [sys.executable, "-m", "goworld_tpu", "watchdog",
                     dst, "--once"],
                    env=env, cwd=dst, capture_output=True, text=True,
                    timeout=300,
                )
                assert wd.returncode == 0, \
                    wd.stdout[-2000:] + wd.stderr[-2000:]
                assert "restarted from" in wd.stdout, wd.stdout
                assert "game1_checkpoint.dat" in wd.stdout \
                    or "game1_freezed.dat" in wd.stdout, wd.stdout

                st = await asyncio.to_thread(
                    subprocess.run,
                    [sys.executable, "-m", "goworld_tpu", "status", dst],
                    env=env, cwd=dst, capture_output=True, text=True,
                    timeout=60,
                )
                assert "game1c0: running" in st.stdout, st.stdout
                assert "game1c1: running" in st.stdout, st.stdout

                # the still-connected strict bot's traffic resumes
                s0 = bot.sync_count
                t0 = time.time()
                while time.time() - t0 < 90 and bot.sync_count <= s0:
                    await asyncio.sleep(0.2)
                assert bot.sync_count > s0, \
                    "syncs never resumed after crash recovery"
                assert not bot.errors, bot.errors
            finally:
                recv.cancel()
                await bot.conn.close()

        asyncio.run(asyncio.wait_for(session(), 560))
    finally:
        subprocess.run(
            [sys.executable, "-m", "goworld_tpu", "stop", dst],
            env=env, cwd=dst, capture_output=True, text=True, timeout=120,
        )


def test_cli_build(tmp_path):
    """`build` prebuilds the native C++ cores and byte-compiles the
    framework + server dir (the reference's `goworld build` role,
    cmd/goworld/build.go:9-38, adapted: no Go link step)."""
    sdir = tmp_path / "srv"
    sdir.mkdir()
    (sdir / "server.py").write_text("import goworld_tpu\n")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "goworld_tpu", "build", str(sdir)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "build ok" in r.stdout
    native = os.path.join(REPO, "goworld_tpu", "native")
    for so in ("_packet_codec.so", "_kcp_core_v2.so", "_snappy_core.so"):
        assert os.path.exists(os.path.join(native, so))
    assert (sdir / "__pycache__").exists()


def test_cli_reload_with_services(tmp_path):
    """Hot reload of a game WITH service entities (examples/test_game:
    OnlineService etc.): the -restore boot replays a snapshot that
    CONTAINS service entities, so their types must be registered before
    the restore (regression: restore ran during GameServer construction
    while service types registered only afterwards — the restart died
    with 'entity type not registered' and reload reported RESTORE
    FAILED)."""
    dst, gport = _copy_example("test_game", tmp_path, 14400, 15400)
    try:
        assert cli.cmd_start(dst) == 0, _logs(dst)
        assert cli.cmd_reload(dst) == 0, _logs(dst)
        assert cli.cmd_status(dst) == 0, _logs(dst)
    finally:
        cli.cmd_stop(dst)
