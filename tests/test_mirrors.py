"""Slot/client numpy mirrors (manager._mir_*): the sync fan-out's
vectorized decode is only correct if the mirrors track _slot_owner and
client bindings through every mutation path — spawn, despawn+release,
EnterSpace migration, client bind/rebind/unbind, megaspace tile hops."""

import numpy as np
import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity, GameClient
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.ops.aoi import GridSpec


class Npc(Entity):
    pass


class Arena(Space):
    pass


def _mk_world(n_spaces=1, megaspace=False, capacity=64, **kw):
    cfg = WorldConfig(
        capacity=capacity,
        grid=GridSpec(radius=10.0, extent_x=120.0, extent_z=100.0,
                      k=8, cell_cap=16, row_block=capacity),
        npc_speed=30.0, turn_prob=0.3,
        enter_cap=2048, leave_cap=2048, sync_cap=2048,
    )
    w = World(cfg, n_spaces=n_spaces, megaspace=megaspace, **kw)
    w.register_entity("Npc", Npc)
    w.register_space("Arena", Arena, megaspace=megaspace)
    w.create_nil_space()
    return w


def _assert_mirrors_match(w: World):
    for sh in range(w.n_spaces):
        expect_eid = np.zeros(w.cfg.capacity, "S16")
        expect_cid = np.zeros(w.cfg.capacity, "S16")
        expect_gate = np.full(w.cfg.capacity, -1, np.int32)
        for slot, eid in w._slot_owner[sh].items():
            expect_eid[slot] = eid.encode()
            e = w.entities.get(eid)
            if e is not None and e.client is not None:
                expect_cid[slot] = e.client.client_id.encode()
                expect_gate[slot] = e.client.gate_id
        assert (w._mir_eid[sh] == expect_eid).all(), f"shard {sh} eid"
        assert (w._mir_cid[sh] == expect_cid).all(), f"shard {sh} cid"
        assert (w._mir_gate[sh] == expect_gate).all(), f"shard {sh} gate"


def test_mirrors_track_churn_and_rebinds():
    rng = np.random.default_rng(3)
    w = _mk_world()
    arena = w.create_space("Arena")
    ents = []
    for i in range(24):
        e = w.create_entity(
            "Npc", space=arena,
            pos=(float(rng.uniform(0, 120)), 0.0,
                 float(rng.uniform(0, 100))),
            moving=True,
            client=(GameClient(1 + i % 3, f"CID{i:013d}", w)
                    if i % 3 == 0 else None),
        )
        ents.append(e)
    _assert_mirrors_match(w)
    for t in range(20):
        if t % 4 == 1 and ents:
            ents.pop(int(rng.integers(len(ents)))).destroy()
        if t % 4 == 2:
            ents.append(w.create_entity(
                "Npc", space=arena,
                pos=(float(rng.uniform(0, 120)), 0.0,
                     float(rng.uniform(0, 100))), moving=True,
            ))
        if t % 5 == 3 and ents:
            e = ents[int(rng.integers(len(ents)))]
            if e.client is None:
                e.set_client(GameClient(2, f"REB{t:013d}", w))
            else:
                e.set_client(None)
        w.tick()
        _assert_mirrors_match(w)


@pytest.mark.slow
def test_mirrors_track_megaspace_hops():
    from goworld_tpu.parallel.mesh import make_mesh

    w = _mk_world(n_spaces=8, megaspace=True, capacity=48,
                  halo_cap=32, migrate_cap=16, mesh=make_mesh(8))
    arena = w.create_space("Arena")
    rng = np.random.default_rng(5)
    for i in range(120):
        w.create_entity(
            "Npc", space=arena,
            pos=(float(rng.uniform(0, 800)), 0.0,
                 float(rng.uniform(0, 100))),
            moving=True,
            client=(GameClient(1, f"MEG{i:013d}", w)
                    if i % 11 == 0 else None),
        )
    for _ in range(12):
        w.tick()
        _assert_mirrors_match(w)


def test_mirror_sync_decode_matches_bruteforce():
    """The vectorized per-gate groupby must produce exactly the records
    the old per-record dict-lookup loop produced."""
    rng = np.random.default_rng(7)
    w = _mk_world()
    arena = w.create_space("Arena")
    for i in range(32):
        w.create_entity(
            "Npc", space=arena,
            pos=(float(rng.uniform(0, 60)), 0.0,
                 float(rng.uniform(0, 60))),
            moving=True,
            client=(GameClient(3 + i % 2, f"SYN{i:013d}", w)
                    if i % 2 == 0 else None),
        )
    got: list = []
    w.sync_sink = lambda g, c, e, v: got.append(
        (g, [bytes(x) for x in c], [bytes(x) for x in e],
         np.asarray(v).copy())
    )
    for _ in range(5):
        got.clear()
        w.tick()
        outs = w.last_outputs
        sn = min(int(outs.sync_n[0]), w.cfg.sync_cap)
        ws = np.asarray(outs.sync_w[0])[:sn]
        js = np.asarray(outs.sync_j[0])[:sn]
        vs = np.asarray(outs.sync_vals[0])[:sn]
        want: dict = {}
        for i, (wi, ji) in enumerate(zip(ws, js)):
            we = w._owner_entity(0, int(wi))
            je = w._owner_subject(0, int(ji))
            if we is None or we.client is None or je is None:
                continue
            want.setdefault(we.client.gate_id, []).append(
                (we.client.client_id.encode(), je.id.encode(),
                 tuple(vs[i]))
            )
        got_by_gate = {
            g: list(zip(c, e, (tuple(r) for r in v))) for g, c, e, v in got
        }
        assert set(got_by_gate) == set(want)
        for g in want:
            assert got_by_gate[g] == want[g], g


def test_mh_mutation_log_backpressure():
    """The multihost mutation log drains at most MH_LOG_BYTES_PER_TICK
    per tick; surplus packets stay queued IN ORDER (never dropped), and
    an oversized single packet still ships alone."""
    from goworld_tpu.net.game import GameServer

    gs = GameServer.__new__(GameServer)   # drain logic only, no network
    gs.game_id = 1
    gs._mh_backlog_ticks = 0
    gs.world = type("W", (), {"op_stats": {}})()
    gs._mh_pending = [(100 + i, bytes([i]) * 400_000) for i in range(5)]
    blob1 = gs._mh_drain_pending()
    # 2 x 400KB fits under 1MB; the 3rd would overflow
    assert len(blob1) == 2 * (6 + 400_000)
    assert len(gs._mh_pending) == 3
    assert gs._mh_pending[0][0] == 102   # order preserved
    blob2 = gs._mh_drain_pending()
    assert len(blob2) == 2 * (6 + 400_000)
    # an oversized single packet still ships (taken==0 bypasses the cap)
    gs._mh_pending = [(7, bytes(2 * GameServer.MH_LOG_BYTES_PER_TICK))]
    blob3 = gs._mh_drain_pending()
    assert len(blob3) == 6 + 2 * GameServer.MH_LOG_BYTES_PER_TICK
    assert not gs._mh_pending
