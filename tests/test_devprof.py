"""Device-plane observability suites (ISSUE 8).

Covers the three tentpole pieces end to end on CPU:

* the XLA cost auditor (`utils/devprof.py`): CostReport smoke for
  EVERY bench autotune candidate shape plus the single-space, vmapped
  and scenario tick forms, and the live World provider behind
  debug_http ``/costs``;
* the in-graph telemetry lanes (`ops/telemetry.py`): bucket-count
  parity against a host-side recompute over the SAME tick series
  (bit-exact, skin on/off, scenario on/off), zero host syncs asserted
  via ``jax.transfer_guard`` and one-trace-per-config asserted via the
  TRACE_COUNTS counter;
* the roofline audit + SLO math (`hist_quantile`,
  ``slo_from_histogram``, ``roofline_audit``).
"""

import importlib.util
import json
import os
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from goworld_tpu.core.step import tick_body
from goworld_tpu.ops import telemetry
from goworld_tpu.utils import devprof

pytestmark = pytest.mark.devprof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_devprof_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


BENCH = _load_bench()


# =======================================================================
# histogram quantiles + SLO verdicts (pure math)
# =======================================================================
def test_hist_quantile_bucket_uppers():
    edges = (1.0, 2.0, 4.0)
    assert devprof.hist_quantile(edges, [1, 1, 1, 0], 0.50) == 2.0
    assert devprof.hist_quantile(edges, [1, 1, 1, 0], 0.99) == 4.0
    assert devprof.hist_quantile(edges, [3, 0, 0, 0], 0.99) == 1.0
    # +Inf tail reports inf (conservative: true value unknown upward)
    assert devprof.hist_quantile(edges, [0, 0, 0, 2], 0.50) \
        == float("inf")
    assert np.isnan(devprof.hist_quantile(edges, [0, 0, 0, 0], 0.5))


def test_slo_from_histogram_pass_and_fail():
    edges = (1.0, 2.0, 16.0, 33.0)
    ok = devprof.slo_from_histogram(edges, [50, 49, 1, 0, 0], 16.0)
    # rank 99 of 100 falls in the <=2ms bucket; the 1 outlier at
    # <=16ms is the p100 tail
    assert ok["pass"] and ok["p99_ms"] == 2.0 and ok["samples"] == 100
    bad = devprof.slo_from_histogram(edges, [0, 0, 0, 5, 0], 16.0)
    assert not bad["pass"] and bad["p99_ms"] == 33.0
    # an empty histogram can never pass
    empty = devprof.slo_from_histogram(edges, [0, 0, 0, 0, 0], 16.0)
    assert not empty["pass"] and empty["samples"] == 0


def test_slo_overflow_and_empty_are_json_safe():
    """Samples in the +Inf bucket (a 1M CPU tick past the last edge)
    and empty histograms must stamp None, never the non-RFC
    Infinity/NaN tokens, into the BENCH artifacts."""
    edges = (1.0, 2.0)
    over = devprof.slo_from_histogram(edges, [0, 0, 4], 16.0)
    assert over["p99_ms"] is None and over["overflow"]
    assert not over["pass"] and over["samples"] == 4
    empty = devprof.slo_from_histogram(edges, [0, 0, 0], 16.0)
    assert empty["p50_ms"] is None and empty["overflow"]
    for blob in (json.dumps(over), json.dumps(empty)):
        assert "Infinity" not in blob and "NaN" not in blob


# =======================================================================
# CostReport: every autotune candidate shape + tick forms
# =======================================================================
N = 256


def _candidate_ids():
    return [
        ",".join(f"{k}={v}" for k, v in ov.items()) or "default"
        for _sel, ov in BENCH.AUTOTUNE_CANDIDATES
    ]


@pytest.mark.parametrize(
    "selectable,overrides", BENCH.AUTOTUNE_CANDIDATES,
    ids=_candidate_ids(),
)
def test_cost_report_every_autotune_candidate(selectable, overrides,
                                              monkeypatch):
    """cost_analysis + memory_analysis succeed for the FULL tick at
    every autotune candidate config (a candidate whose compiled
    artifact can't be audited would hide from the device plane)."""
    for var in BENCH.GRID_ENV.values():
        monkeypatch.delenv(var, raising=False)
    cfg, st, inputs = BENCH.build(N, 0.02, overrides)

    def tick(state):
        s2, out = tick_body(cfg, state, inputs, None)
        return s2.pos.sum() + out.sync_n

    rep = devprof.cost_report(
        tick, st, name=f"tick:{_key(overrides)}",
        config=devprof.grid_config_key(cfg.grid), n=N)
    assert rep.error is None, rep.error
    assert rep.flops and rep.flops > 0
    assert rep.bytes_accessed and rep.bytes_accessed > 0
    assert rep.peak_hbm_bytes and rep.peak_hbm_bytes > 0
    d = rep.as_dict()
    # the per-config key carries the resolved kernel stamps
    for stamp in ("sweep_impl", "sort_impl", "skin"):
        assert stamp in d["key"]
    assert d["platform"] == "cpu"


def _key(ov):
    return ",".join(f"{k}={v}" for k, v in ov.items()) or "default"


def test_cost_report_vmapped_and_scenario_ticks():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.manager import _make_local_tick
    from goworld_tpu.ops.aoi import GridSpec
    from goworld_tpu.parallel.mesh import create_multi_state
    from goworld_tpu.core.step import TickInputs
    from goworld_tpu.scenarios.spec import get_scenario

    # vmapped multi-space form (the production n_spaces > 1 local step)
    cfg = WorldConfig(capacity=64, grid=GridSpec(
        radius=10.0, extent_x=40.0, extent_z=40.0))
    step = _make_local_tick(cfg, 2)
    state = create_multi_state(cfg, 2, seed=0)
    inputs = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (2,) + x.shape),
        TickInputs.empty(cfg))
    rep = devprof.cost_report(step, state, inputs, None,
                              name="vmapped.tick", n=128)
    assert rep.error is None and rep.bytes_accessed > 0

    # scenario form (heterogeneous vmapped lax.switch behaviors)
    spec = get_scenario("hotspot")
    cfg2, st2, in2 = BENCH.build(64, 0.02, scenario=spec)
    policy = None
    if spec.needs_policy:
        from goworld_tpu.models.npc_policy import init_policy

        policy = init_policy(jax.random.PRNGKey(0))

    def tick(state):
        s2, out = tick_body(cfg2, state, in2, policy)
        return s2.pos.sum() + out.sync_n

    rep2 = devprof.cost_report(tick, st2, name="scenario.tick", n=64)
    assert rep2.error is None and rep2.flops > 0


def test_cost_report_accepts_precompiled_executable():
    @jax.jit
    def f(x):
        return (x * 2).sum()

    x = jnp.ones((32, 32))
    compiled = f.lower(x).compile()
    rep = devprof.cost_report(compiled, name="precompiled")
    assert rep.error is None and rep.bytes_accessed > 0


def test_cost_report_folds_failures_instead_of_raising():
    def broken(x):
        raise RuntimeError("boom")

    rep = devprof.cost_report(broken, jnp.ones(4), name="broken")
    assert rep.error is not None and "boom" in rep.error


def test_world_registers_costs_provider():
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.manager import World
    from goworld_tpu.ops.aoi import GridSpec

    devprof.reset()
    try:
        w = World(WorldConfig(capacity=32, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0)), n_spaces=1)
        snap = devprof.snapshot()
        assert "world.tick" in snap["providers"]
        assert snap["reports"] == {}  # lazy: nothing ran yet
        rep = w.cost_report()
        assert rep.error is None, rep.error
        assert rep.flops > 0 and rep.config["sweep_impl"]
    finally:
        devprof.reset()


# =======================================================================
# /costs endpoint
# =======================================================================
def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read())


def test_costs_endpoint_reports_providers_and_slo():
    from goworld_tpu.utils import debug_http

    devprof.reset()
    srv = debug_http.start(0, process_name="devproftest")
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        devprof.register_report(
            devprof.CostReport(name="tick_scan", flops=1e9,
                               bytes_accessed=2e9, n=1024))
        ran = []

        def provider():
            ran.append(1)
            return devprof.CostReport(name="lazy", flops=5.0)

        devprof.register_provider("lazy", provider)
        devprof.record_slo({"target_ms": 16.0, "p99_ms": 3.0,
                            "pass": True})

        code, body = _get_json(base + "/costs")
        assert code == 200
        assert body["reports"]["tick_scan"]["flops"] == 1e9
        assert body["providers"] == ["lazy"]
        assert not ran  # providers NEVER run on a plain scrape
        assert body["slo"]["pass"] is True

        code, body = _get_json(base + "/costs?analyze=1")
        assert ran == [1]
        assert body["reports"]["lazy"]["flops"] == 5.0
    finally:
        srv.shutdown()
        srv.server_close()
        devprof.reset()


def test_costs_live_slo_falls_back_to_tick_latency_histogram():
    from goworld_tpu.utils import metrics

    devprof.reset()
    try:
        h = metrics.histogram("tick_latency_ms")
        before = h.count
        for v in (1.0, 2.0, 3.0, 900.0):
            h.observe(v)
        devprof.set_slo_target(16.0)
        slo = devprof.snapshot()["slo"]
        assert slo is not None
        assert slo["source"] == "tick_latency_ms"
        assert slo["samples"] >= before + 4
        assert slo["target_ms"] == 16.0
    finally:
        devprof.reset()


def test_registry_histogram_snapshot_accessor():
    from goworld_tpu.utils import metrics

    reg = metrics.Registry()
    assert reg.histogram_snapshot("nope") is None
    reg.counter("a_total").inc()
    assert reg.histogram_snapshot("a_total") is None  # wrong kind
    h = reg.histogram("lat_ms", buckets=(1.0, 2.0))
    h.observe(1.5)
    snap = reg.histogram_snapshot("lat_ms")
    assert len(snap) == 1
    labels, s = snap[0]
    assert labels == {} and s["count"] == 1
    assert s["buckets"] == [(1.0, 0), (2.0, 1)]


def test_scrape_metrics_costs_and_slo_lines():
    """tools/scrape_metrics.py learns /costs: per-process SLO verdict
    lines next to the metric table (ISSUE 8 satellite; cli.py status
    goes through the same two helpers)."""
    import importlib.util as _ilu

    from goworld_tpu.utils import debug_http

    spec = _ilu.spec_from_file_location(
        "scrape_under_test",
        os.path.join(REPO, "tools", "scrape_metrics.py"))
    scraper = _ilu.module_from_spec(spec)
    spec.loader.exec_module(scraper)

    devprof.reset()
    srv = debug_http.start(0, process_name="scrapetest")
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        devprof.record_slo({"target_ms": 16.0, "p50_ms": 1.0,
                            "p90_ms": 2.0, "p99_ms": 3.0,
                            "samples": 10, "pass": True,
                            "source": "in-graph-histogram"})
        costs = scraper.scrape_costs([("game1", base + "/metrics")])
        assert "game1" in costs
        lines = scraper.slo_lines(costs)
        assert len(lines) == 1
        assert "game1" in lines[0] and "PASS" in lines[0] \
            and "p99=3.0" in lines[0]
        # unreachable targets are skipped silently (the metric scrape
        # already reports reachability)
        assert scraper.scrape_costs(
            [("dead", "http://127.0.0.1:9/metrics")]) == {}
    finally:
        srv.shutdown()
        srv.server_close()
        devprof.reset()


# =======================================================================
# in-graph telemetry lanes: parity + zero-sync + one-trace
# =======================================================================
def _telemetry_scan(cfg, st, inputs, policy, ticks, skin_on,
                    base_ms, delta_ms, half_skin):
    """One jitted scan returning BOTH the on-device accumulator and
    the raw per-tick signal series (device truth) — the parity oracle
    histograms the series host-side and must match bit-exactly."""

    @jax.jit
    def run(state):
        acc0 = telemetry.telemetry_init(skin_on)

        def body(carry, _):
            s, acc = carry
            s2, out = tick_body(cfg, s, inputs, policy)
            acc = telemetry.telemetry_update(acc, out, base_ms,
                                             delta_ms, half_skin)
            rebuilt = out.aoi_rebuilt
            if rebuilt is None:
                rebuilt = jnp.ones((), jnp.int32)
            slack = out.aoi_skin_slack
            if slack is None:
                slack = jnp.zeros((), jnp.float32)
            series = {
                "tick_ms": jnp.float32(base_ms)
                + rebuilt.astype(jnp.float32) * jnp.float32(delta_ms),
                "rebuilt": rebuilt.astype(jnp.float32),
                "sync_n": out.sync_n.astype(jnp.float32),
                "enter_n": out.enter_n.astype(jnp.float32),
                "leave_n": out.leave_n.astype(jnp.float32),
                "over_k_rows":
                    out.aoi_over_k_rows.astype(jnp.float32),
                "over_cap_cells":
                    out.aoi_over_cap_cells.astype(jnp.float32),
                "skin_slack": (slack / jnp.float32(half_skin)
                               if half_skin > 0 else slack),
            }
            return (s2, acc), series
        (_s2, acc), series = lax.scan(body, (state, acc0), None,
                                      length=ticks)
        return acc, series
    return run


@pytest.mark.parametrize("skin,scenario", [
    (0.0, None), (4.0, None), (0.0, "hotspot"), (4.0, "teleport"),
], ids=["skinless", "skin", "scenario", "skin+scenario"])
def test_telemetry_histogram_parity_vs_host_recompute(skin, scenario,
                                                      monkeypatch):
    for var in BENCH.GRID_ENV.values():
        monkeypatch.delenv(var, raising=False)
    from goworld_tpu.scenarios.spec import get_scenario

    spec = get_scenario(scenario) if scenario else None
    cfg, st, inputs = BENCH.build(
        128, 0.05, {"skin": skin},
        scenario=spec if spec is not None else None)
    policy = None
    if spec is not None and spec.needs_policy:
        from goworld_tpu.models.npc_policy import init_policy

        policy = init_policy(jax.random.PRNGKey(0))
    skin_on = cfg.grid.skin > 0 and st.aoi_cache is not None
    base_ms, delta_ms = 3.0, (2.5 if skin_on else 0.0)
    half_skin = cfg.grid.skin / 2.0 if skin_on else 0.0
    ticks = 12
    run = _telemetry_scan(cfg, st, inputs, policy, ticks, skin_on,
                          base_ms, delta_ms, half_skin)
    acc, series = run(st)
    drained = telemetry.telemetry_drain(acc, skin_on, half_skin)
    for lane, edges in telemetry.lane_edges(skin_on).items():
        host = telemetry.host_histogram(np.asarray(series[lane]),
                                        edges)
        assert drained[lane]["counts"] == [int(c) for c in host], \
            f"lane {lane}: device {drained[lane]['counts']} " \
            f"!= host {host.tolist()}"
        assert sum(drained[lane]["counts"]) == ticks
    # the distribution is over REAL per-tick variation: with a skin,
    # the rebuild lane must show both a rebuild and reuse ticks
    if skin_on and scenario is None:
        rb = drained["rebuilt"]["counts"]
        assert rb[1] >= 1 and rb[0] >= 1, rb
    if scenario == "teleport":
        # every teleport tick defeats the skin: rebuilds dominate
        assert drained["rebuilt"]["counts"][1] >= ticks - 1


def test_telemetry_zero_host_syncs_and_single_trace(monkeypatch):
    """The accumulator scan runs with host<->device transfers DISALLOWED
    (zero per-tick syncs — the drain is the one readback, outside the
    guard) and traces exactly once per config across repeat calls."""
    for var in BENCH.GRID_ENV.values():
        monkeypatch.delenv(var, raising=False)
    cfg, st, inputs = BENCH.build(64, 0.05, {"skin": 0.0})

    @jax.jit
    def run(state):
        acc0 = telemetry.telemetry_init(False)

        def body(carry, _):
            s, acc = carry
            s2, out = tick_body(cfg, s, inputs, None)
            acc = telemetry.telemetry_update(acc, out, 1.0, 0.0)
            return (s2, acc), 0
        (_s2, acc), _ = lax.scan(body, (state, acc0), None, length=4)
        return acc

    st_dev = jax.device_put(st)
    in_dev = jax.device_put(inputs)  # noqa: F841 (closed over above)
    traces0 = telemetry.TRACE_COUNTS.get("telemetry_update", 0)
    run(st_dev)  # trace + compile outside the guard
    with jax.transfer_guard("disallow"):
        acc = run(jax.tree.map(lambda x: x, st_dev))
    drained = telemetry.telemetry_drain(acc, False)  # the ONE drain
    assert sum(drained["tick_ms"]["counts"]) == 4
    # one trace per config: the second (guarded) call hit the cache
    assert telemetry.TRACE_COUNTS["telemetry_update"] == traces0 + 1


# =======================================================================
# roofline model + audit block
# =======================================================================
@pytest.mark.parametrize("grid_kw", [
    {"sort_impl": "argsort", "sweep_impl": "ranges", "skin": 0.0},
    {"sort_impl": "counting", "sweep_impl": "table", "skin": 0.0},
    {"sort_impl": "argsort", "sweep_impl": "fused", "skin": 0.0},
    {"sort_impl": "counting", "sweep_impl": "ranges", "skin": 4.0,
     "verlet_cap": 48},
], ids=["ranges", "table+counting", "fused", "verlet"])
def test_roofline_model_bytes_shapes(grid_kw):
    kw = dict(grid_kw, k=32, cell_cap=12, radius=50.0,
              extent_x=10000.0, extent_z=10000.0)
    model = devprof.roofline_model_bytes(131072, kw)
    for phase in ("cell_ids", "aoi_sort", "aoi_build", "aoi_gather",
                  "aoi_rank", "aoi", "move", "collect"):
        assert phase in model and model[phase] >= 0.0
    if grid_kw.get("skin", 0) > 0:
        assert {"aoi_reuse", "aoi_rebuild"} <= set(model)
        assert model["aoi_rebuild"] > model["aoi_reuse"]
    if grid_kw["sweep_impl"] == "fused":
        # the fusion deletes the window-gather + packed-key HBM terms
        split = devprof.roofline_model_bytes(
            131072, dict(kw, sweep_impl="ranges"))
        assert model["aoi"] < 0.5 * split["aoi"]
    if grid_kw["sort_impl"] == "counting":
        bitonic = devprof.roofline_model_bytes(
            131072, dict(kw, sort_impl="argsort"))
        assert model["aoi_sort"] < 0.2 * bitonic["aoi_sort"]


def test_roofline_audit_block_shape():
    kw = {"k": 32, "cell_cap": 12, "sort_impl": "argsort",
          "sweep_impl": "ranges", "skin": 0.0, "radius": 50.0,
          "extent_x": 3000.0, "extent_z": 3000.0}
    phase_ms = {"aoi": 10.0, "move": 1.0, "collect": 2.0}
    costs = {"aoi": devprof.CostReport(name="phase:aoi",
                                       bytes_accessed=5e6, flops=1e6),
             "move": {"bytes_accessed": 2e6},
             "collect": {"bytes_accessed": 3e6}}
    block = devprof.roofline_audit(phase_ms, costs, 4096, kw,
                                   platform="cpu")
    assert block["doc"] == "docs/ROOFLINE.md" and block["n"] == 4096
    aoi = block["phases"]["aoi"]
    assert aoi["measured_ms"] == 10.0
    assert aoi["xla_mb"] == 5.0
    assert "drift_pct" in aoi and "model_ms_v5e" in aoi
    assert block["phases"]["move"]["xla_mb"] == 2.0
    assert "total_drift_pct" in block

    # PARTIAL XLA coverage (a probe whose lower failed) must never
    # stamp a like-for-unlike total drift — it flags coverage instead
    partial = devprof.roofline_audit(
        phase_ms, {k: costs[k] for k in ("aoi", "move")}, 4096, kw,
        platform="cpu")
    assert "total_drift_pct" not in partial
    assert partial["xla_coverage_partial"] == ["aoi", "move"]
    # phases with no cost report still carry the model columns
    assert "model_mb" in partial["phases"]["collect"]
    assert "xla_mb" not in partial["phases"]["collect"]
