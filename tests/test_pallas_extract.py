"""Pallas stream-compaction kernel vs the XLA bounded_extract.

Runs in interpreter mode off-TPU (same kernel code path as hardware
modulo Mosaic lowering — real-chip profiling is round-3 work)."""

import numpy as np
import pytest

import jax.numpy as jnp

from goworld_tpu.ops.extract import bounded_extract
from goworld_tpu.ops.pallas_extract import bounded_extract_pallas


@pytest.mark.parametrize("m,density,cap,seed", [
    (5000, 0.02, 256, 0),     # sparse, no overflow
    (5000, 0.5, 256, 1),      # dense, cap overflow
    (1024, 0.0, 64, 2),       # empty
    (1024, 1.0, 64, 3),       # all set, heavy overflow
    (3000, 0.1, 4096, 4),     # cap larger than set bits
    (2048, 0.3, 300, 5),      # cap crosses a block boundary mid-window
])
def test_matches_xla_bounded_extract(m, density, cap, seed):
    rng = np.random.default_rng(seed)
    mask = jnp.asarray(rng.uniform(size=m) < density)
    f0, v0, c0 = bounded_extract(mask, cap)
    f1, v1, c1 = bounded_extract_pallas(mask, cap)
    assert int(c0) == int(c1)
    np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
    np.testing.assert_array_equal(
        np.asarray(f0)[np.asarray(v0)], np.asarray(f1)[np.asarray(v1)]
    )


def test_two_d_mask_raveled():
    rng = np.random.default_rng(9)
    mask = jnp.asarray(rng.uniform(size=(300, 16)) < 0.05)
    f0, v0, c0 = bounded_extract(mask, 128)
    f1, v1, c1 = bounded_extract_pallas(mask, 128)
    assert int(c0) == int(c1)
    np.testing.assert_array_equal(
        np.asarray(f0)[np.asarray(v0)], np.asarray(f1)[np.asarray(v1)]
    )


def test_vmapped_like_migrate_pack():
    """migrate.pack_emigrants vmaps bounded_extract over destinations;
    the kernel's carry must reset per batch element (its first-block
    detection is data-driven — program_id moves under vmap batching)."""
    import jax

    rng = np.random.default_rng(7)
    masks = jnp.asarray(rng.uniform(size=(4, 2048)) < 0.1)
    cap = 128
    ref = jax.vmap(lambda m: bounded_extract(m, cap))(masks)
    got = jax.vmap(lambda m: bounded_extract_pallas(m, cap))(masks)
    for b in range(4):
        assert int(ref[2][b]) == int(got[2][b])
        v = np.asarray(ref[1][b])
        np.testing.assert_array_equal(
            np.asarray(ref[0][b])[v], np.asarray(got[0][b])[v]
        )


def test_flag_routes_the_real_event_paths(monkeypatch):
    """GOWORLD_TPU_PALLAS_EXTRACT=1 must actually route bounded_extract
    AND the two-level rows variant through the kernel."""
    import goworld_tpu.ops.extract as ex
    import goworld_tpu.ops.pallas_extract as px

    calls = []
    orig = px.bounded_extract_pallas

    def spy(mask, cap):
        calls.append(mask.size)
        return orig(mask, cap)

    monkeypatch.setenv("GOWORLD_TPU_PALLAS_EXTRACT", "1")
    monkeypatch.setattr(px, "bounded_extract_pallas", spy)
    rng = np.random.default_rng(3)
    mask2d = jnp.asarray(rng.uniform(size=(500, 8)) < 0.05)
    f, v, c = ex.bounded_extract_rows(mask2d, 64)
    assert calls, "flag did not route through the pallas kernel"
    # equivalence against the XLA path
    monkeypatch.setenv("GOWORLD_TPU_PALLAS_EXTRACT", "0")
    f0, v0, c0 = ex.bounded_extract_rows(mask2d, 64)
    assert int(c) == int(c0)
    np.testing.assert_array_equal(
        np.asarray(f)[np.asarray(v)], np.asarray(f0)[np.asarray(v0)]
    )
