"""Unified telemetry tests: registry semantics, Prometheus exposition,
tick-timeline ring buffer + Chrome trace export, and the debug-http
``/metrics`` + ``/trace`` endpoints (ISSUE 1 tentpole)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from goworld_tpu.utils import debug_http, metrics


# =======================================================================
# counters / gauges / histograms
# =======================================================================
def test_counter_semantics():
    r = metrics.Registry()
    c = r.counter("reqs_total")
    c.inc()
    c.inc(2)
    assert c.value == 3
    # same name + labels returns the same child
    assert r.counter("reqs_total") is c
    with pytest.raises(ValueError):
        c.inc(-1)
    # a name registers one kind only
    with pytest.raises(ValueError):
        r.gauge("reqs_total")


def test_gauge_semantics():
    r = metrics.Registry()
    g = r.gauge("depth")
    g.set(7)
    assert g.value == 7
    g.inc()
    g.dec(3)
    assert g.value == 5


def test_histogram_buckets_and_exposition():
    r = metrics.Registry()
    h = r.histogram("lat_ms", buckets=(1, 10, 100))
    for v in (0.5, 5.0, 50.0, 5000.0, 10.0):  # 10.0 lands in le="10"
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(5065.5)
    text = r.expose_text()
    assert "# TYPE lat_ms histogram" in text
    assert 'lat_ms_bucket{le="1"} 1' in text
    assert 'lat_ms_bucket{le="10"} 3' in text  # cumulative, le inclusive
    assert 'lat_ms_bucket{le="100"} 4' in text
    assert 'lat_ms_bucket{le="+Inf"} 5' in text
    assert "lat_ms_count 5" in text


def test_labels_render_as_name_suffix():
    r = metrics.Registry()
    r.counter("route_total", msgtype="12").inc()
    r.counter("route_total", msgtype="30").inc(4)
    text = r.expose_text()
    assert 'route_total{msgtype="12"} 1' in text
    assert 'route_total{msgtype="30"} 4' in text
    # one TYPE line per family, not per child
    assert text.count("# TYPE route_total counter") == 1


def test_exposition_parses_back():
    r = metrics.Registry()
    r.counter("a_total").inc(2)
    r.gauge("b", role="gate").set(1.5)
    parsed = metrics.parse_prometheus_text(r.expose_text())
    assert parsed["a_total"] == 2
    assert parsed['b{role="gate"}'] == 1.5


# =======================================================================
# tick timeline
# =======================================================================
def test_timeline_ring_buffer_bounds():
    tl = metrics.TickTimeline(capacity=8)
    for _ in range(20):
        tl.begin_tick()
        with tl.span("a"):
            pass
        tl.end_tick()
    assert len(tl.records()) == 8


def test_timeline_span_is_noop_without_open_tick():
    tl = metrics.TickTimeline()
    with tl.span("orphan"):
        pass
    assert tl.records() == []
    assert tl.end_tick() is None


def test_timeline_chrome_trace_and_coverage():
    tl = metrics.TickTimeline(capacity=4)
    tl.begin_tick()
    with tl.span("phase1"):
        time.sleep(0.005)
    with tl.span("phase2", rows=3):
        time.sleep(0.005)
    tl.set_tick_args(device_step_ms=1.25)
    dur = tl.end_tick()
    assert dur is not None and dur >= 0.01
    # contiguous spans cover (nearly) the whole tick — the /trace
    # acceptance bar is >= 95% of tick wall time
    assert tl.coverage() >= 0.95
    trace = tl.chrome_trace("game1")
    json.dumps(trace)  # must be valid JSON
    events = trace["traceEvents"]
    by_name = {e["name"]: e for e in events}
    assert by_name["tick"]["args"]["device_step_ms"] == 1.25
    assert by_name["phase2"]["args"] == {"rows": 3}
    tick_ev, p1 = by_name["tick"], by_name["phase1"]
    assert tick_ev["ph"] == "X" and p1["ph"] == "X"
    # spans nest inside their tick on the same track
    assert tick_ev["ts"] <= p1["ts"]
    assert p1["ts"] + p1["dur"] <= tick_ev["ts"] + tick_ev["dur"] + 1.0


def test_timeline_overhead_under_one_percent_of_frame():
    """The recorder is always on: a full game tick (begin + 6 spans +
    end) must cost well under 1% of the 16 ms roofline frame."""
    tl = metrics.TickTimeline(capacity=16)
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        tl.begin_tick()
        for name in ("a", "b", "c", "d", "e", "f"):
            with tl.span(name):
                pass
        tl.end_tick()
    per_tick = (time.perf_counter() - t0) / n
    assert per_tick < 160e-6, f"{per_tick * 1e6:.1f}us per tick"


# =======================================================================
# World.tick integration (the live phases the bench only had offline)
# =======================================================================
def test_world_tick_records_phases_and_aoi_series():
    from goworld_tpu.core import WorldConfig
    from goworld_tpu.entity import World
    from goworld_tpu.ops.aoi import GridSpec

    w = World(WorldConfig(capacity=32, grid=GridSpec(
        radius=10.0, extent_x=40.0, extent_z=40.0)), n_spaces=1)
    w.create_nil_space()
    metrics.timeline.clear()
    w.tick()
    w.tick()
    recs = metrics.timeline.records()
    assert len(recs) == 2
    names = [s[0] for s in recs[-1][2]]
    assert names == ["flush_staging", "device_step", "fetch_outputs",
                     "decode_fanout"]
    assert "device_step_ms" in recs[-1][3]
    assert metrics.timeline.coverage() >= 0.95
    # AOI saturation series exist (0 on a healthy world) and are scrapeable
    text = metrics.REGISTRY.expose_text()
    assert "aoi_overflow_total" in text
    assert "aoi_demand_max" in text


# =======================================================================
# live game acceptance: serve loop + /metrics + /trace end to end
# =======================================================================
def test_running_game_exposes_tick_series_and_trace():
    """ISSUE 1 acceptance: curl /metrics on a RUNNING game returns the
    tick_latency_ms histogram buckets, aoi_overflow_total and
    backlog_ticks; /trace returns Chrome JSON whose spans cover >= 95%
    of a tick's wall time."""
    import threading

    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity.manager import World
    from goworld_tpu.net.game import GameServer
    from goworld_tpu.net.standalone import ClusterHarness
    from goworld_tpu.ops.aoi import GridSpec

    harness = ClusterHarness(n_dispatchers=1, n_gates=1,
                             desired_games=1)
    harness.start()
    world = World(
        WorldConfig(capacity=64, grid=GridSpec(
            radius=10.0, extent_x=40.0, extent_z=40.0)),
        n_spaces=1,
    )
    world.create_nil_space()
    gs = GameServer(1, world, list(harness.dispatcher_addrs),
                    tick_interval=0.02, gc_freeze_on_boot=False)
    gs.start_network()
    metrics.timeline.clear()
    t = threading.Thread(target=gs.serve_forever, daemon=True)
    t.start()
    srv = debug_http.start(0, process_name="game1")
    try:
        # tick_latency_ms is process-global: wait RELATIVE to its
        # current count so an earlier test's serve loop can't satisfy
        # the wait before THIS loop has recorded any tick
        count0 = gs._m_tick_hist.count
        deadline = time.monotonic() + 10
        while gs._m_tick_hist.count < count0 + 5 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert gs._m_tick_hist.count >= count0 + 5, \
            "serve loop never ticked"

        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            body = resp.read().decode()
        assert 'tick_latency_ms_bucket{le="+Inf"}' in body
        assert "tick_latency_ms_count" in body
        assert "aoi_overflow_total" in body
        assert "backlog_ticks" in body
        assert "input_queue_depth" in body

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace") as resp:
            trace = json.loads(resp.read().decode())
        span_names = {e["name"] for e in trace["traceEvents"]}
        assert {"tick", "drain_inputs", "device_step",
                "fan_out"} <= span_names
        # per-tick span coverage of the live loop
        assert metrics.timeline.coverage() >= 0.95
    finally:
        srv.shutdown()
        srv.server_close()
        gs.stop()
        t.join(timeout=5)
        harness.stop()


def test_config_rejects_game_http_rank_collision(tmp_path):
    """A multihost game binds http_port..+mesh_processes-1; a sibling
    landing inside that span would get silently mis-attributed by the
    scraper — the config loader must reject it."""
    from goworld_tpu import config as config_mod

    ini = tmp_path / "goworld_tpu.ini"
    ini.write_text(
        "[dispatcher1]\nport = 14000\n"
        "[game1]\nhttp_port = 16000\nmesh_processes = 2\n"
        "[game2]\nhttp_port = 16001\n"
        "[gate1]\nport = 15000\n"
    )
    with pytest.raises(ValueError, match="http_port"):
        config_mod.load(str(ini))


# =======================================================================
# /metrics + /trace endpoints
# =======================================================================
def test_debug_http_metrics_and_trace():
    metrics.counter("endpoint_probe_total").inc(3)
    tl = metrics.timeline
    tl.begin_tick()
    with tl.span("probe_phase"):
        pass
    tl.end_tick()

    srv = debug_http.start(0, process_name="game-test")
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            ctype = resp.headers.get("Content-Type", "")
            body = resp.read().decode()
        assert ctype.startswith("text/plain")
        assert "endpoint_probe_total 3" in body
        assert metrics.parse_prometheus_text(body)[
            "endpoint_probe_total"] == 3

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace") as resp:
            trace = json.loads(resp.read().decode())
        names = [e["name"] for e in trace["traceEvents"]]
        assert "probe_phase" in names
        meta = [e for e in trace["traceEvents"]
                if e["name"] == "process_name"]
        assert meta and meta[0]["args"]["name"] == "game-test"

        # discovery: 404 advertises the new endpoints
        req = urllib.request.Request(f"http://127.0.0.1:{port}/nope")
        try:
            urllib.request.urlopen(req)
        except urllib.error.HTTPError as e:
            listing = json.loads(e.read().decode())["endpoints"]
            assert "/metrics" in listing and "/trace" in listing
    finally:
        srv.shutdown()
        srv.server_close()
