"""Soak + reload-under-load (VERDICT #6): the reference's de-facto
elasticity test is 200 strict bots for 300 s with a live ``goworld
reload`` mid-run (.github/workflows/test_game.yml:34-46). Scaled for CI:
100 strict bots for ~70 s against the in-process cluster, a freeze ->
restore (hot reload) in the middle, strict mirror verification after.

Marked ``soak`` — the slowest test in the suite by design."""

import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net.botclient import BotClient, BotProfiler
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec

# reference CI scale is 200 bots / 300 s + 60 s after reload
# (.github/workflows/test_game.yml:34-46); CI-sized defaults here, full
# scale via env: SOAK_BOTS=200 SOAK_BEFORE=300 SOAK_AFTER=60
import os as _os

N_BOTS = int(_os.environ.get("SOAK_BOTS", 100))
SOAK_BEFORE_RELOAD = float(_os.environ.get("SOAK_BEFORE", 20.0))
SOAK_AFTER_RELOAD = float(_os.environ.get("SOAK_AFTER", 25.0))


class Account(Entity):
    """Boot entity: auto-login — immediately hands the client an Avatar
    (the reference bot sends a Login RPC; the auto path keeps 100 bots
    deterministic)."""

    def OnClientConnected(self):
        avatar = self.world.create_entity(
            "Avatar", space=self.world._arena,
            pos=(
                50.0 + (hash(self.id) % 300),
                0.0,
                50.0 + (hash(self.id[::-1]) % 300),
            ),
        )
        avatar.attrs["name"] = f"soul-{self.id[:6]}"
        self.give_client_to(avatar)
        self.destroy()


class Avatar(Entity):
    ATTRS = {"name": "allclients", "level": "client"}

    def OnClientConnected(self):
        self.attrs["level"] = 1


class Arena(Space):
    pass


def _make_world(for_restore: bool = False):
    cfg = WorldConfig(
        capacity=512,
        grid=GridSpec(radius=30.0, extent_x=400.0, extent_z=400.0,
                      k=32, cell_cap=64, row_block=512),
        input_cap=1024,
        enter_cap=16384, leave_cap=16384, sync_cap=32768,
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Account", Account)
    w.register_entity("Avatar", Avatar)
    w.register_space("Arena", Arena)
    w.create_nil_space()
    if not for_restore:  # the restore path rebuilds the arena from disk
        w._arena = w.create_space("Arena")
    return w


def _drive(gs, stop):
    while not stop.is_set() and gs.run_state == "running":
        gs.pump()
        gs.tick()
        time.sleep(0.01)
    if gs.run_state == "freezing":
        gs._do_freeze()


@pytest.mark.soak
def test_soak_100_bots_reload_under_load(tmp_path):
    _run_soak(N_BOTS, SOAK_BEFORE_RELOAD, SOAK_AFTER_RELOAD, tmp_path)


@pytest.mark.soak
@pytest.mark.soak_full
def test_soak_reference_scale(tmp_path):
    """The reference CI's exact elasticity profile — 200 strict bots,
    300 s soak, live reload, 60 s after
    (.github/workflows/test_game.yml:34-46). ~7 min wall: skipped unless
    RUN_SOAK_FULL=1 so the quick suite stays quick; run (and its result
    recorded in docs/ROUND*.md) once per round."""
    if _os.environ.get("RUN_SOAK_FULL") != "1":
        pytest.skip("reference-scale soak: set RUN_SOAK_FULL=1 to run")
    _run_soak(200, 300.0, 60.0, tmp_path)


def _run_soak(n_bots, before_s, after_s, tmp_path):
    harness = ClusterHarness(
        n_dispatchers=2, n_gates=1, desired_games=1,
        position_sync_interval_ms=50,
    )
    harness.start()
    stop = threading.Event()
    stop2 = threading.Event()
    t = t2 = None
    gs = gs2 = None
    try:
        w = _make_world()
        gs = GameServer(1, w, list(harness.dispatcher_addrs),
                        boot_entity="Account", freeze_dir=str(tmp_path))
        gs.start_network()
        t = threading.Thread(target=_drive, args=(gs, stop), daemon=True)
        t.start()
        assert gs.ready_event.wait(20), "deployment never became ready"

        host, port = harness.gate_addrs[0]
        # one shared per-second profiler across the swarm (reference
        # examples/test_client/profile.go:20-52)
        profiler = BotProfiler()
        bots = [
            BotClient(host, port, bot_id=i, strict=True, move_interval=0.2,
                      profiler=profiler)
            for i in range(n_bots)
        ]
        total = before_s + after_s + 20.0
        futures = [harness.submit(b.run(total)) for b in bots]
        rep_future = harness.submit(profiler.reporter())

        # phase 1: soak
        deadline = time.monotonic() + before_s
        while time.monotonic() < deadline:
            time.sleep(0.5)
        ready_bots = sum(1 for b in bots if b.player is not None)
        assert ready_bots >= n_bots * 0.9, (
            f"only {ready_bots}/{n_bots} bots got avatars before reload"
        )
        syncs_before = sum(b.sync_count for b in bots)
        assert syncs_before > 0, "no position syncs flowed before reload"

        # phase 2: live reload (freeze -> restore) with bots connected
        gs.request_freeze()
        fdl = time.monotonic() + 20
        while gs.run_state != "frozen" and time.monotonic() < fdl:
            time.sleep(0.05)
        assert gs.run_state == "frozen", "freeze never completed under load"
        stop.set()
        t.join(timeout=5)
        n_avatars_frozen = sum(
            1 for e in w.entities.values()
            if e.type_name == "Avatar" and not e.destroyed
        )

        w2 = _make_world(for_restore=True)
        gs2 = GameServer(1, w2, list(harness.dispatcher_addrs),
                         boot_entity="Account", freeze_dir=str(tmp_path),
                         restore=True)
        w2._arena = next(
            sp for sp in w2.spaces.values() if sp.type_name == "Arena"
        )
        gs2.start_network()
        t2 = threading.Thread(target=_drive, args=(gs2, stop2), daemon=True)
        t2.start()

        restored = [
            e for e in w2.entities.values()
            if e.type_name == "Avatar" and not e.destroyed
        ]
        assert len(restored) == n_avatars_frozen, (
            f"restore lost avatars: {len(restored)} vs {n_avatars_frozen}"
        )
        assert all(e.client is not None for e in restored), \
            "client bindings lost in restore"

        # phase 3: soak after reload — traffic must resume
        deadline = time.monotonic() + after_s
        while time.monotonic() < deadline:
            time.sleep(0.5)
        syncs_after = sum(b.sync_count for b in bots)
        assert syncs_after > syncs_before, (
            "no position syncs after reload: "
            f"{syncs_after} <= {syncs_before}"
        )

        # wind the bots down and verify strict mirrors
        for f in futures:
            f.result(timeout=60)
        rep_future.cancel()
        errors = [(b.bot_id, e) for b in bots for e in b.errors]
        assert not errors, f"strict mirror violations: {errors[:10]}"

        # the per-second profiler saw the workload: per-second reports
        # were printed and the cumulative table has the hot client ops
        summary = profiler.summary()
        assert summary.get("sync_batch", {}).get("count", 0) > 0
        assert summary.get("send_position", {}).get("count", 0) > 0
        assert summary.get("create_entity", {}).get("count", 0) >= n_bots
        assert len(profiler.lines) >= before_s * 0.5, (
            f"expected ~{before_s:.0f} per-second reports, "
            f"got {len(profiler.lines)}"
        )

        # mirror attr consistency against the live server state
        live = {e.id: e for e in w2.entities.values()
                if e.type_name == "Avatar" and not e.destroyed}
        checked = 0
        for b in bots:
            if b.player is None or b.player.eid not in live:
                continue
            srv = live[b.player.eid]
            assert b.player.attrs.get("name") == srv.attrs.get("name"), \
                f"bot {b.bot_id} name mirror diverged"
            assert b.player.attrs.get("level") == srv.attrs.get("level"), \
                f"bot {b.bot_id} level mirror diverged"
            checked += 1
        assert checked >= n_bots * 0.9, (
            f"only {checked}/{n_bots} mirrors verifiable after reload"
        )
    finally:
        stop.set()
        stop2.set()
        if t is not None:
            t.join(timeout=5)
        if t2 is not None:
            t2.join(timeout=5)
        if gs is not None:
            gs.stop()
        if gs2 is not None:
            gs2.stop()
        harness.stop()
