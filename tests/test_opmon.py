"""opmon / gwvar tests (reference ``engine/opmon/opmon_test.go`` spirit)."""

import time

from goworld_tpu.utils import opmon


def test_record_and_snapshot():
    m = opmon.Monitor()
    m.record("op_a", 0.010)
    m.record("op_a", 0.030)
    m.record("op_b", 0.001)
    snap = m.snapshot()
    assert snap["op_a"]["count"] == 2
    assert snap["op_a"]["avg_ms"] == 20.0
    assert snap["op_a"]["max_ms"] == 30.0
    assert snap["op_b"]["count"] == 1
    m.reset()
    assert m.snapshot() == {}


def test_context_manager_times():
    m = opmon.Monitor()
    with m.op("sleepy"):
        time.sleep(0.01)
    snap = m.snapshot()
    assert snap["sleepy"]["count"] == 1
    assert snap["sleepy"]["max_ms"] >= 8.0


def test_world_tick_records():
    opmon.monitor.reset()
    from goworld_tpu.core import WorldConfig
    from goworld_tpu.entity import World
    from goworld_tpu.ops.aoi import GridSpec

    w = World(WorldConfig(capacity=32, grid=GridSpec(
        radius=10.0, extent_x=40.0, extent_z=40.0)), n_spaces=1)
    w.create_nil_space()
    w.tick()
    assert opmon.monitor.snapshot()["world.tick"]["count"] == 1


def test_gwvar_expose():
    opmon.expose("flag", 7)
    assert opmon.vars()["flag"] == 7
