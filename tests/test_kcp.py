"""KCP reliable-UDP transport: integrity under loss, framing compat,
dead-link detection (reference client edge: GateService.go:129-161,
turbo tuning consts.go:99-106)."""

import asyncio
import random

import pytest

from goworld_tpu.net.kcp import (
    KcpCore, open_kcp_connection, start_kcp_server,
)
from goworld_tpu.net.packet import PacketConnection, new_packet


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


from contextlib import contextmanager


@contextmanager
def fake_clock(step_ms=10):
    """Patch the module clock; yields advance() stepping it forward."""
    import goworld_tpu.net.kcp as kcpmod

    t0 = kcpmod._now_ms()
    real = kcpmod._now_ms
    state = {"step": 0}

    def advance():
        state["step"] += 1
        kcpmod._now_ms = lambda: t0 + state["step"] * step_ms

    try:
        yield advance
    finally:
        kcpmod._now_ms = real


def test_core_loopback_lossless():
    """Two cores wired back to back deliver a byte stream in order."""
    a_out, b_out = [], []
    a = KcpCore(7, a_out.append)
    b = KcpCore(7, b_out.append)
    msgs = [bytes([i]) * (1 + 317 * i) for i in range(9)]  # spans MSS
    for m in msgs:
        a.send(m)
    got = bytearray()
    for _ in range(50):
        a.flush()
        for d in a_out:
            b.input(d)
        a_out.clear()
        b.flush()
        for d in b_out:
            a.input(d)
        b_out.clear()
        while (chunk := b.recv()) is not None:
            got += chunk
    assert bytes(got) == b"".join(msgs)


def test_core_retransmit_under_loss():
    """30% datagram loss both ways: the stream still arrives intact, in
    order (ARQ: una/ack + rto + fast retransmit)."""
    rng = random.Random(5)
    a_out, b_out = [], []
    a = KcpCore(9, lambda d: a_out.append(d) if rng.random() > 0.3 else None)
    b = KcpCore(9, lambda d: b_out.append(d) if rng.random() > 0.3 else None)
    payload = bytes(rng.getrandbits(8) for _ in range(40000))
    a.send(payload)
    got = bytearray()
    step = 0
    with fake_clock() as advance:
        while len(got) < len(payload) and step < 4000:
            step += 1
            advance()             # simulate time passing so RTOs fire
            a.flush()
            for d in a_out:
                b.input(d)
            a_out.clear()
            b.flush()
            for d in b_out:
                a.input(d)
            b_out.clear()
            while (chunk := b.recv()) is not None:
                got += chunk
    assert bytes(got) == payload, (
        f"got {len(got)}/{len(payload)} bytes after {step} steps"
    )


def test_asyncio_packet_connection_over_kcp_with_loss():
    """The real stack: PacketConnection framing over the asyncio KCP
    server/client adapters through a lossy localhost UDP path."""
    rng = random.Random(11)

    def loss(datagram: bytes) -> bool:
        return rng.random() < 0.15

    async def main():
        received = []
        done = asyncio.Event()

        async def on_client(reader, writer):
            conn = PacketConnection(reader, writer)
            for _ in range(40):
                msgtype, pkt = await conn.recv()
                received.append((msgtype, pkt.read_var_str()))
            # echo one packet back
            p = new_packet(901)
            p.append_var_str("pong")
            conn.send(p)
            await conn.drain()
            done.set()

        server = await start_kcp_server(
            on_client, "127.0.0.1", 0, loss_hook=loss
        )
        port = server.bound_port
        reader, writer = await open_kcp_connection(
            "127.0.0.1", port, loss_hook=loss
        )
        conn = PacketConnection(reader, writer)
        for i in range(40):
            p = new_packet(900)
            p.append_var_str(f"msg-{i:03d}-" + "x" * (i * 37 % 300))
            conn.send(p)
        await conn.drain()
        await asyncio.wait_for(done.wait(), 30)
        msgtype, pkt = await conn.recv()
        assert msgtype == 901 and pkt.read_var_str() == "pong"
        await conn.close()
        server.close()
        return received

    received = run(main())
    assert len(received) == 40
    assert [t for t, _ in received] == [900] * 40
    assert received[0][1].startswith("msg-000")
    assert received[39][1].startswith("msg-039")


def test_dead_link_detected():
    """A peer that never answers kills the link after the retransmit
    limit instead of retrying forever."""
    a = KcpCore(3, lambda d: None)   # all output dropped
    a.send(b"hello")
    with fake_clock(step_ms=50) as advance:
        for _ in range(20000):
            advance()
            a.flush()
            if a.dead:
                break
    assert a.dead


# =======================================================================
# native C++ core (native/kcp_core.cpp) — must interoperate with the
# Python core bit-for-bit (same wire protocol; kcp-go parity role)
# =======================================================================
def _native_available():
    from goworld_tpu.net.kcp import _load_native
    return _load_native() is not None


@pytest.mark.skipif(not _native_available(), reason="no native kcp core")
@pytest.mark.parametrize("a_native,b_native", [
    (True, True), (True, False), (False, True),
])
def test_native_core_interop_under_loss(a_native, b_native):
    from goworld_tpu.net.kcp import NativeKcpCore

    rng = random.Random(23)
    a_out, b_out = [], []

    def mk(native, sink, lossp):
        out = (lambda d: sink.append(d) if rng.random() > lossp else None)
        return NativeKcpCore(5, out) if native else KcpCore(5, out)

    a = mk(a_native, a_out, 0.25)
    b = mk(b_native, b_out, 0.25)
    payload = bytes(rng.getrandbits(8) for _ in range(30000))
    a.send(payload)
    b.send(payload[::-1])    # full-duplex
    got_b, got_a = bytearray(), bytearray()
    step = 0
    with fake_clock() as advance:
        while (len(got_b) < len(payload) or len(got_a) < len(payload)) \
                and step < 4000:
            step += 1
            advance()
            a.flush()
            for d in a_out:
                b.input(d)
            a_out.clear()
            b.flush()
            for d in b_out:
                a.input(d)
            b_out.clear()
            while (chunk := b.recv()) is not None:
                got_b += chunk
            while (chunk := a.recv()) is not None:
                got_a += chunk
    assert bytes(got_b) == payload
    assert bytes(got_a) == payload[::-1]


@pytest.mark.skipif(not _native_available(), reason="no native kcp core")
def test_native_core_drives_the_gate_stack():
    """The asyncio adapters pick the native core automatically; the full
    PacketConnection flow must still work."""
    from goworld_tpu.net.kcp import _Session, make_core, NativeKcpCore
    assert isinstance(make_core(1, lambda d: None), NativeKcpCore)

    async def main():
        got = []

        async def on_client(reader, writer):
            conn = PacketConnection(reader, writer)
            mt, p = await conn.recv()
            got.append((mt, p.read_var_str()))
            reply = new_packet(31)
            reply.append_var_str("native-pong")
            conn.send(reply)
            await conn.drain()

        server = await start_kcp_server(on_client, "127.0.0.1", 0)
        reader, writer = await open_kcp_connection(
            "127.0.0.1", server.bound_port
        )
        conn = PacketConnection(reader, writer)
        p = new_packet(30)
        p.append_var_str("native-ping" * 400)
        conn.send(p)
        await conn.drain()
        mt, reply = await conn.recv()
        assert mt == 31 and reply.read_var_str() == "native-pong"
        await conn.close()
        server.close()
        return got

    got = run(main())
    assert got == [(30, "native-ping" * 400)]


@pytest.mark.parametrize("use_native", [False, True])
def test_crafted_len_field_rejected(use_native):
    """A datagram whose len field is near 2^31 must be rejected, not
    drive a negative offset into an out-of-bounds read (native core) or
    a bogus slice (python core)."""
    if use_native and not _native_available():
        pytest.skip("no native kcp core")
    from goworld_tpu.net.kcp import NativeKcpCore
    import struct as _s

    cls = NativeKcpCore if use_native else KcpCore
    core = cls(5, lambda d: None)
    evil = _s.pack("<IBBHIII", 5, 81, 0, 64, 0, 0, 0) \
        + _s.pack("<I", 0x80000000) + b"xx"
    core.input(evil)                      # must not crash
    assert core.recv() is None
    # and a 0-len PUSH never wedges the recv drain behind it
    z = _s.pack("<IBBHIII", 5, 81, 0, 64, 0, 0, 0) + _s.pack("<I", 0)
    d = _s.pack("<IBBHIII", 5, 81, 0, 64, 0, 1, 0) \
        + _s.pack("<I", 4) + b"data"
    core.input(z + d)
    chunks = []
    while (c := core.recv()) is not None:
        chunks.append(c)
    assert b"".join(chunks) == b"data"


@pytest.mark.parametrize("use_native", [False, True])
def test_corrupted_datagrams_never_break_the_stream(use_native):
    """Fuzz: random corruption (bit flips, truncation, garbage, foreign
    conv ids) injected alongside real traffic must never crash the core
    or corrupt the delivered byte stream — only well-formed segments of
    the right conversation count."""
    if use_native and not _native_available():
        pytest.skip("no native kcp core")
    from goworld_tpu.net.kcp import NativeKcpCore

    rng = random.Random(77)
    cls = NativeKcpCore if use_native else KcpCore
    a_out, b_out = [], []
    a = cls(9, a_out.append)
    b = cls(9, b_out.append)
    payload = bytes(rng.getrandbits(8) for _ in range(20000))
    a.send(payload)
    got = bytearray()
    step = 0
    with fake_clock() as advance:
        while len(got) < len(payload) and step < 3000:
            step += 1
            advance()
            a.flush()
            for d in a_out:
                r = rng.random()
                if r < 0.1:
                    # corrupt the conv field -> foreign-conversation
                    # datagram, must be rejected cleanly (payload-level
                    # bit flips are out of scope: KCP has no checksum,
                    # same as kcp-go without its crypto layer)
                    d = bytearray(d)
                    d[rng.randrange(4)] ^= 1 << rng.randrange(8)
                    d = bytes(d)
                    b.input(d)
                    continue   # the real copy is lost (drop + corrupt)
                elif r < 0.15:
                    d = d[:rng.randrange(len(d))]      # truncate
                elif r < 0.2:
                    d = bytes(rng.getrandbits(8)
                              for _ in range(rng.randrange(1, 200)))
                b.input(d)
                if rng.random() < 0.05:
                    # replay/duplicate delivery
                    b.input(d)
            a_out.clear()
            b.flush()
            for d in b_out:
                a.input(d)
            b_out.clear()
            while (chunk := b.recv()) is not None:
                got += chunk
    # rejected datagrams behave as loss: ARQ recovers the exact stream
    assert bytes(got) == payload, (len(got), len(payload), step)


# =======================================================================
# u32 serial wrap, idle reaping, TIME_WAIT tombstones, per-IP mint caps
# =======================================================================
import struct as _s

from goworld_tpu.net.kcp import KcpServer


def test_core_u32_serial_wrap():
    """sn/una arithmetic must wrap at 2^32 exactly like the native/kcp-go
    cores: a stream whose serial numbers cross the boundary still arrives
    intact and in order (cores preset to 3 segments before wrap)."""
    a_out, b_out = [], []
    a = KcpCore(7, a_out.append)
    b = KcpCore(7, b_out.append)
    start = (1 << 32) - 3
    a.snd_nxt = a.snd_una = start
    b.rcv_nxt = start
    payload = bytes(range(256)) * 40          # ~10 KB -> ~8 segments
    a.send(payload)
    got = bytearray()
    for _ in range(50):
        a.flush()
        for d in a_out:
            b.input(d)
        a_out.clear()
        b.flush()
        for d in b_out:
            a.input(d)
        b_out.clear()
        while (c := b.recv()) is not None:
            got += c
    assert bytes(got) == payload
    assert a.snd_nxt < (1 << 32) and a.snd_nxt == b.rcv_nxt
    assert b.rcv_nxt < start                   # crossed the boundary
    assert not a.snd_buf                       # everything acked past wrap


class _FakeTransport:
    def __init__(self):
        self.sent = []

    def sendto(self, d, addr):
        self.sent.append((d, addr))

    def get_extra_info(self, name, default=None):
        return ("127.0.0.1", 12345)

    def close(self):
        pass


def _push(conv, sn=0, data=b"x"):
    return _s.pack("<IBBHIII", conv, 81, 0, 64, 0, sn, 0) \
        + _s.pack("<I", len(data)) + data


def test_server_reaps_vanished_but_probes_idle_sessions():
    """Two halves of the idle policy: a LIVE client with zero traffic in
    either direction must survive past idle_timeout (the server's WASK
    probe elicits a WINS that refreshes last_heard), while a peer that
    VANISHES silently (no FIN on UDP, no unacked outbound data to trip
    dead-link) is reaped — heartbeat or not (gate default heartbeat is
    0 = disabled)."""
    async def main():
        held = asyncio.Event()

        async def on_client(reader, writer):
            await held.wait()

        server = await start_kcp_server(
            on_client, "127.0.0.1", 0, idle_timeout=0.6
        )
        reader, writer = await open_kcp_connection(
            "127.0.0.1", server.bound_port
        )
        writer.write(b"hello")
        await writer.drain()
        await asyncio.sleep(0.25)
        assert len(server._sessions) == 1
        # no data flows either way, but the client stack is alive: the
        # probe/WINS exchange must keep the session past idle_timeout
        await asyncio.sleep(1.2)
        assert len(server._sessions) == 1, "live idle client was kicked"
        # now the client vanishes without a trace (UDP has no FIN and
        # closing the writer sends nothing): only the reaper can act
        writer.close()
        await asyncio.sleep(1.5)
        assert not server._sessions, "vanished client never reaped"
        held.set()
        server.close()

    run(main())


def test_time_wait_tombstone_blocks_resurrection():
    """After a server-initiated close, the peer's retransmitted PUSH
    segments still pass mint validation — the TIME_WAIT tombstone must
    drop them instead of resurrecting the connection (fresh ClientProxy +
    boot entity per kick)."""
    async def main():
        async def cb(reader, writer):
            pass

        server = KcpServer(cb, idle_timeout=0)
        server.connection_made(_FakeTransport())
        addr = ("10.0.0.1", 5555)
        server.datagram_received(_push(7), addr)
        assert (addr, 7) in server._sessions
        server._sessions[(addr, 7)].close()    # server kicks the client
        assert not server._sessions
        # the client keeps retransmitting: no resurrection in TIME_WAIT
        server.datagram_received(_push(7), addr)
        assert not server._sessions
        # once the tombstone expires, a genuine reconnect mints again
        server._tombstones[(addr, 7)] = 0.0
        server.datagram_received(_push(7), addr)
        assert (addr, 7) in server._sessions
        server.close()

    run(main())


def test_per_ip_mint_cap():
    """One source IP can hold at most max_sessions_per_ip live sessions;
    other IPs are unaffected, and closing a session frees its slot."""
    async def main():
        async def cb(reader, writer):
            pass

        server = KcpServer(cb, idle_timeout=0, max_sessions_per_ip=2)
        server.connection_made(_FakeTransport())
        for conv in (1, 2, 3):
            server.datagram_received(_push(conv), ("10.0.0.9", 1000 + conv))
        assert len(server._sessions) == 2      # third mint refused
        server.datagram_received(_push(9), ("10.0.0.10", 1))
        assert len(server._sessions) == 3      # different IP unaffected
        # freeing one slot lets the IP mint again (tombstone keys differ)
        first = next(k for k in server._sessions if k[0][0] == "10.0.0.9")
        server._sessions[first].close()
        server.datagram_received(_push(8), ("10.0.0.9", 2000))
        assert sum(1 for k in server._sessions if k[0][0] == "10.0.0.9") == 2
        server.close()

    run(main())


@pytest.mark.skipif(not _native_available(), reason="no native kcp core")
@pytest.mark.parametrize("a_native,b_native", [
    (True, True), (True, False), (False, True),
])
def test_native_core_u32_serial_wrap(a_native, b_native):
    """The C++ core's sn/una compares must use signed serial distance
    (sn_diff) exactly like the Python core — a stream crossing sn 2^32
    keeps flowing in every native/python pairing."""
    from goworld_tpu.net.kcp import NativeKcpCore

    start = (1 << 32) - 3
    a_out, b_out = [], []

    def mk(native, sink):
        core = (NativeKcpCore if native else KcpCore)(5, sink.append)
        if native:
            core._lib.kcp_test_set_serials(core._h, start, start, start)
        else:
            core.snd_nxt = core.snd_una = core.rcv_nxt = start
        return core

    a = mk(a_native, a_out)
    b = mk(b_native, b_out)
    payload = bytes(range(256)) * 40
    a.send(payload)
    got = bytearray()
    for _ in range(50):
        a.flush()
        for d in a_out:
            b.input(d)
        a_out.clear()
        b.flush()
        for d in b_out:
            a.input(d)
        b_out.clear()
        while (c := b.recv()) is not None:
            got += c
    assert bytes(got) == payload
    assert a.unsent() == 0        # everything admitted AND acked past wrap


@pytest.mark.parametrize("use_native", [False, True])
def test_probe_elicits_wins(use_native):
    """probe() queues a WASK whose peer answers with a WINS — the
    liveness-probe exchange the idle reaper relies on."""
    if use_native and not _native_available():
        pytest.skip("no native kcp core")
    from goworld_tpu.net.kcp import CMD_WASK, CMD_WINS, NativeKcpCore

    cls = NativeKcpCore if use_native else KcpCore
    a_out, b_out = [], []
    a = cls(5, a_out.append)
    b = cls(5, b_out.append)
    a.probe()
    a.flush()
    assert any(d[4] == CMD_WASK for d in a_out)
    for d in a_out:
        b.input(d)
    b.flush()
    assert any(d[4] == CMD_WINS for d in b_out), "peer never answered"


@pytest.mark.parametrize("a_native,b_native", [
    (False, False), (True, True), (True, False),
])
def test_delay_reorder_netem(a_native, b_native):
    """netem-style link: every datagram independently delayed 30-90
    virtual ms (so later sends routinely overtake earlier ones) plus 5%
    loss, both directions. Exercises the srtt/rttval estimator, RTO
    backoff, and fast-retransmit interplay at realistic RTTs instead of
    loopback-zero (VERDICT r2 weak #6); the stream must arrive intact
    and in order both ways, and the Python core's smoothed RTT must
    settle near the real ~60-180 ms round trip."""
    if (a_native or b_native) and not _native_available():
        pytest.skip("no native kcp core")
    from goworld_tpu.net.kcp import NativeKcpCore

    rng = random.Random(99)
    a_out, b_out = [], []
    a = (NativeKcpCore if a_native else KcpCore)(5, a_out.append)
    b = (NativeKcpCore if b_native else KcpCore)(5, b_out.append)
    payload = bytes(rng.getrandbits(8) for _ in range(60000))
    a.send(payload)
    b.send(payload[::-1])
    link_ab: list = []   # (deliver_step, datagram)
    link_ba: list = []
    got_b, got_a = bytearray(), bytearray()
    step = 0
    with fake_clock(step_ms=10) as advance:   # 1 step = 10 virtual ms
        while (len(got_b) < len(payload) or len(got_a) < len(payload)) \
                and step < 8000:
            step += 1
            advance()
            a.flush()
            for d in a_out:
                if rng.random() < 0.05:
                    continue                    # loss
                link_ab.append((step + rng.randint(3, 9), d))
            a_out.clear()
            b.flush()
            for d in b_out:
                if rng.random() < 0.05:
                    continue
                link_ba.append((step + rng.randint(3, 9), d))
            b_out.clear()
            # deliver everything due this step, in DELAY order — a
            # shorter-delayed later datagram overtakes an earlier one
            for link, dst in ((link_ab, b), (link_ba, a)):
                due = [x for x in link if x[0] <= step]
                link[:] = [x for x in link if x[0] > step]
                for _, d in sorted(due, key=lambda x: x[0]):
                    dst.input(d)
            while (c := b.recv()) is not None:
                got_b += c
            while (c := a.recv()) is not None:
                got_a += c
    assert bytes(got_b) == payload, (len(got_b), step)
    assert bytes(got_a) == payload[::-1], (len(got_a), step)
    # the estimator must have converged near the real RTT (one-way 30-90
    # => round trip ~60-180 ms); wildly off means RTO backoff ran the
    # show instead of measurement
    for core in (a, b):
        if isinstance(core, KcpCore):
            assert 20 <= core.rx_srtt <= 400, core.rx_srtt
