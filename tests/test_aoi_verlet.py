"""Verlet skin reuse (GridSpec.skin > 0): EXACT front-half skipping.

The acceptance bar is zero interest-set divergence vs a per-tick
rebuild — the skin is a cadence optimization, never an approximation.
These tests drive multi-tick random walks through the cached path and
assert bit-parity with the stateless sweep every tick, plus every
rebuild trigger: displacement past skin/2, alive-set changes
(spawn/despawn), watch-radius changes, the rebuild_every_max backstop,
and the candidate-cap overflow gauge.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from goworld_tpu.core.state import WorldConfig, create_state, despawn, \
    spawn
from goworld_tpu.core.step import TickInputs, make_tick
from goworld_tpu.ops.aoi import (
    GridSpec,
    grid_neighbors_flags,
    grid_neighbors_verlet,
    init_verlet_cache,
)

N = 500
EXTENT = 300.0


def _spec(skin, **kw):
    base = dict(radius=25.0, extent_x=EXTENT, extent_z=EXTENT, k=48,
                cell_cap=48, row_block=128, verlet_cap=96)
    base.update(kw)
    return GridSpec(**base, skin=skin)


def _world(seed=0):
    rng = np.random.default_rng(seed)
    pos = np.zeros((N, 3), np.float32)
    pos[:, 0] = rng.random(N) * EXTENT
    pos[:, 2] = rng.random(N) * EXTENT
    alive = rng.random(N) < 0.9
    fb = rng.integers(0, 4, N).astype(np.int32)
    return rng, pos, alive, fb


def _both(spec, spec0, pos, alive, fb, cache):
    out = grid_neighbors_verlet(
        spec, jnp.asarray(pos), jnp.asarray(alive), cache,
        flag_bits=jnp.asarray(fb), with_stats=True,
    )
    ref = grid_neighbors_flags(
        spec0, jnp.asarray(pos), jnp.asarray(alive),
        flag_bits=jnp.asarray(fb), with_stats=True,
    )
    return out, ref


def test_random_walk_zero_divergence_with_reuse():
    """30 small-step ticks: every tick's lists/counts/flags identical
    to the per-tick rebuild, while most ticks actually skip."""
    rng, pos, alive, fb = _world(1)
    spec, spec0 = _spec(6.0), _spec(0.0)
    cache = init_verlet_cache(spec, N)
    rebuilds = 0
    for t in range(30):
        out, ref = _both(spec, spec0, pos, alive, fb, cache)
        nbr, cnt, fl, stats, cache, reb, slack = out
        rebuilds += int(reb)
        assert np.array_equal(np.asarray(nbr), np.asarray(ref[0])), t
        assert np.array_equal(np.asarray(cnt), np.asarray(ref[1])), t
        assert np.array_equal(np.asarray(fl), np.asarray(ref[2])), t
        step = rng.normal(0, 0.35, (N, 2)).astype(np.float32)
        pos[:, 0] = np.clip(pos[:, 0] + step[:, 0], 0, EXTENT - 1e-3)
        pos[:, 2] = np.clip(pos[:, 2] + step[:, 1], 0, EXTENT - 1e-3)
        fb = rng.integers(0, 4, N).astype(np.int32)
    assert rebuilds >= 1                      # cold cache built once
    assert rebuilds < 15, f"reuse never kicked in ({rebuilds}/30)"


def test_teleport_forces_rebuild_and_stays_exact():
    rng, pos, alive, fb = _world(2)
    spec, spec0 = _spec(6.0), _spec(0.0)
    cache = init_verlet_cache(spec, N)
    (nbr, _c, _f, _s, cache, reb, _sl), _ = _both(
        spec, spec0, pos, alive, fb, cache)
    assert int(reb) == 1
    # one entity jumps across the world (>> skin/2)
    pos[7, 0] = (pos[7, 0] + EXTENT / 2) % EXTENT
    out, ref = _both(spec, spec0, pos, alive, fb, cache)
    nbr, cnt, fl, _s, cache, reb, slack = out
    assert int(reb) == 1 and float(slack) < 0
    assert np.array_equal(np.asarray(nbr), np.asarray(ref[0]))


def test_alive_change_forces_rebuild_and_stays_exact():
    rng, pos, alive, fb = _world(3)
    spec, spec0 = _spec(6.0), _spec(0.0)
    cache = init_verlet_cache(spec, N)
    (_n, _c, _f, _s, cache, _r, _sl), _ = _both(
        spec, spec0, pos, alive, fb, cache)
    dead = np.nonzero(alive)[0][3]
    born = np.nonzero(~alive)[0][0]
    alive = alive.copy()
    alive[dead] = False                       # despawn
    alive[born] = True                        # spawn into a free slot
    out, ref = _both(spec, spec0, pos, alive, fb, cache)
    nbr, cnt, _f, _s, cache, reb, _sl = out
    assert int(reb) == 1
    assert np.array_equal(np.asarray(nbr), np.asarray(ref[0]))
    assert np.array_equal(np.asarray(cnt), np.asarray(ref[1]))
    # the new entity is visible, the dead one is gone, everywhere
    assert not np.any(np.asarray(nbr) == dead)


def test_watch_radius_change_forces_rebuild():
    rng, pos, alive, fb = _world(4)
    spec, spec0 = _spec(6.0), _spec(0.0)
    wr = np.full(N, np.inf, np.float32)
    cache = init_verlet_cache(spec, N)
    out = grid_neighbors_verlet(
        spec, jnp.asarray(pos), jnp.asarray(alive), cache,
        watch_radius=jnp.asarray(wr), flag_bits=jnp.asarray(fb))
    cache = out[4]
    wr2 = wr.copy()
    watcher = np.nonzero(alive)[0][0]
    wr2[watcher] = 5.0                        # shrink one view distance
    out = grid_neighbors_verlet(
        spec, jnp.asarray(pos), jnp.asarray(alive), cache,
        watch_radius=jnp.asarray(wr2), flag_bits=jnp.asarray(fb))
    nbr, cnt, _f, _s, cache, reb, _sl = out
    assert int(reb) == 1
    ref = grid_neighbors_flags(
        spec0, jnp.asarray(pos), jnp.asarray(alive),
        watch_radius=jnp.asarray(wr2), flag_bits=jnp.asarray(fb))
    assert np.array_equal(np.asarray(nbr), np.asarray(ref[0]))


def test_rebuild_every_max_backstop():
    rng, pos, alive, fb = _world(5)
    spec = _spec(50.0, rebuild_every_max=4)   # huge skin: displacement
    cache = init_verlet_cache(spec, N)        # never triggers
    pattern = []
    for t in range(9):
        out = grid_neighbors_verlet(
            spec, jnp.asarray(pos), jnp.asarray(alive), cache,
            flag_bits=jnp.asarray(fb))
        cache = out[4]
        pattern.append(int(out[5]))
    assert pattern == [1, 0, 0, 0, 1, 0, 0, 0, 1]


def test_candidate_overflow_fires_over_k_gauge():
    """verlet_cap too small for the density: the stats must say so
    (the only regime where the skin may diverge is gauged, mirroring
    the k/cell_cap contract)."""
    rng = np.random.default_rng(6)
    m = 64
    pos = np.zeros((m, 3), np.float32)
    pos[:, 0] = 50.0 + rng.random(m) * 4.0    # one dense blob
    pos[:, 2] = 50.0 + rng.random(m) * 4.0
    alive = np.ones(m, bool)
    spec = GridSpec(radius=25.0, extent_x=100.0, extent_z=100.0,
                    k=8, cell_cap=64, row_block=64, skin=5.0,
                    verlet_cap=16)            # demand is ~63 per row
    cache = init_verlet_cache(spec, m)
    out = grid_neighbors_verlet(
        spec, jnp.asarray(pos), jnp.asarray(alive), cache,
        flag_bits=jnp.zeros(m, jnp.int32), with_stats=True)
    stats = out[3]
    assert int(stats[1]) > 0                  # over-cap rows reported


def test_tick_body_integration_bit_parity_and_gauges():
    """make_tick with skin vs without: identical neighbor state and
    event counts every tick (random_walk velocities don't read nbr, so
    the two configs' trajectories coincide), and the outputs carry the
    rebuild/slack gauges."""
    def run(skin):
        cfg = WorldConfig(
            capacity=256,
            grid=_spec(skin, row_block=256),
            npc_speed=5.0,
        )
        st = create_state(cfg, seed=9)
        rng = np.random.default_rng(8)
        for s in range(120):
            st = spawn(st, s, pos=(rng.random() * EXTENT, 0.0,
                                   rng.random() * EXTENT),
                       npc_moving=True)
        tick = make_tick(cfg)
        ins = TickInputs.empty(cfg)
        rebuilds, outs = 0, []
        for t in range(20):
            st, out = tick(st, ins, None)
            rebuilds += int(out.aoi_rebuilt)
            outs.append((
                np.asarray(st.nbr), np.asarray(st.nbr_cnt),
                int(out.enter_n), int(out.leave_n), int(out.sync_n),
            ))
            if t == 9:
                st = despawn(st, 3)           # mid-run alive change
        return rebuilds, outs

    reb0, a = run(0.0)
    reb1, b = run(5.0)
    assert reb0 == 20                         # skinless: every tick
    assert 2 <= reb1 < 20                     # cold + despawn, then reuse
    for t, (oa, ob) in enumerate(zip(a, b)):
        assert np.array_equal(oa[0], ob[0]), f"nbr diverged @ tick {t}"
        assert np.array_equal(oa[1], ob[1]), f"cnt diverged @ tick {t}"
        assert oa[2:] == ob[2:], f"event counts diverged @ tick {t}"


@pytest.mark.scenarios
def test_scenario_teleport_flips_rebuild_cond_on_exact_tick():
    """ISSUE 7 regression: under the teleport scenario kernel a jump
    (>> skin/2 by construction: uniform over the world) must flip the
    in-graph rebuild cond ON THAT TICK — predicted here tick-by-tick by
    mirroring the cache contract host-side (max Chebyshev displacement
    since the last rebuild vs skin/2), while the walk drift between
    jumps stays under skin/2 and correctly does NOT rebuild. Every tick
    also stays bit-identical to the skinless sweep."""
    from goworld_tpu.scenarios.spec import ScenarioSpec

    cap, live, ext, skin = 64, 48, 150.0, 8.0
    spec = ScenarioSpec(name="tp_exact_tick",
                        mix=(("teleport", 1.0),), teleport_prob=0.06)

    def mk(skin_v):
        return WorldConfig(
            capacity=cap,
            grid=GridSpec(radius=20.0, extent_x=ext, extent_z=ext,
                          k=16, cell_cap=48, row_block=cap,
                          verlet_cap=63, skin=skin_v),
            npc_speed=1.0,       # drift/tick = dt << skin/2
            scenario=spec,
        )

    cfg, cfg0 = mk(skin), mk(0.0)
    st = create_state(cfg, seed=21)
    st0 = create_state(cfg0, seed=21)
    rng = np.random.default_rng(21)
    for s in range(live):
        p = (rng.random() * ext, 0.0, rng.random() * ext)
        st = spawn(st, s, pos=p, npc_moving=True)
        st0 = spawn(st0, s, pos=p, npc_moving=True)
    tick, tick0 = make_tick(cfg), make_tick(cfg0)
    ins = TickInputs.empty(cfg)

    ref = None                    # pos snapshot at the last rebuild
    saw_jump_tick = saw_still_tick = 0
    for t in range(25):
        st, out = tick(st, ins, None)
        st0, _ = tick0(st0, ins, None)
        pos = np.asarray(st.pos)[:live, ::2]
        if ref is None:
            expect = 1            # cold cache: first tick rebuilds
        else:
            disp = np.max(np.abs(pos - ref))
            expect = int(disp > skin / 2.0)
        assert int(out.aoi_rebuilt) == expect, (
            f"tick {t}: rebuild={int(out.aoi_rebuilt)} but the "
            f"displacement bound says {expect}"
        )
        if expect:
            ref = pos
            if t > 0:
                saw_jump_tick += 1
        else:
            saw_still_tick += 1
        # the skin is exact through the churn (same rng stream -> the
        # two configs' populations coincide; teleports don't read nbr)
        assert np.array_equal(np.asarray(st.nbr), np.asarray(st0.nbr)), t
        assert np.array_equal(np.asarray(st.nbr_cnt),
                              np.asarray(st0.nbr_cnt)), t
    # the run must actually exercise both sides of the cond
    assert saw_jump_tick >= 3, "no teleport tick ever tripped the cond"
    assert saw_still_tick >= 3, "reuse never happened (skin too small?)"


def test_world_manager_exports_rebuild_gauges():
    """Single-space World with a skin: ticks run through the direct
    (un-vmapped) local step so the rebuild cond stays a real branch,
    and op_stats exports the cadence gauges."""
    from goworld_tpu.entity import Entity, Space, World

    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=32, row_block=64, skin=3.0),
    )
    w = World(cfg, n_spaces=1)
    w.register_entity("Mob", type("Mob", (Entity,), {}))
    w.register_space("Sp", type("Sp", (Space,), {}))
    w.create_nil_space()
    sp = w.create_space("Sp")
    for i in range(5):
        sp.create_entity("Mob", pos=(50 + i, 0, 50))
    for _ in range(3):
        w.tick()
    assert "aoi_rebuild_last" in w.op_stats
    assert "aoi_skin_slack" in w.op_stats
    assert w.op_stats["aoi_rebuild_last"] in (0, 1)
