"""From-scratch MongoDB stack: BSON + OP_MSG wire + minimongo server +
the storage/kvdb/gwmongo backends over them.

Closes the last open SURVEY component (the reference's MongoDB entity
storage, ``backend/mongodb/mongodb.go:27-136``, and kvdb engine,
``kvdb/backend/kvdb_mongodb/mongodb.go``): no driver or server exists
in this environment, so the public formats are implemented directly —
BSON per bsonspec.org (canonical vector tested), commands over OP_MSG
(opcode 2013) — and an in-process server speaks the same bytes, so a
real mongod is a drop-in.
"""

import time

import pytest

from goworld_tpu.ext.db import bson
from goworld_tpu.ext.db.minimongo import MiniMongo
from goworld_tpu.ext.db.mongowire import MongoClient, MongoError


@pytest.fixture()
def server():
    with MiniMongo() as srv:
        yield srv


# ---------------------------------------------------------------- BSON --

def test_bson_canonical_vector():
    # the spec's own example: {"hello": "world"}
    want = (b"\x16\x00\x00\x00\x02hello\x00\x06\x00\x00\x00world\x00"
            b"\x00")
    assert bson.encode({"hello": "world"}) == want
    assert bson.decode(want) == {"hello": "world"}


def test_bson_roundtrips():
    cases = [
        {},
        {"a": 1, "b": -1, "big": 1 << 40, "neg": -(1 << 40)},
        {"f": 1.5, "t": True, "x": False, "n": None},
        {"s": "héllo wörld"},
        {"nest": {"deep": [1, "two", {"three": [None, 4.0]}]}},
        {"bin": b"\x00\x01\xfe\xff"},
        {"mix": [{"hp": 10}, [1, 2], "s", None, True]},
    ]
    for c in cases:
        assert bson.decode(bson.encode(c)) == c
    # int32/int64 boundary
    for v in (-(1 << 31), (1 << 31) - 1, 1 << 31, -(1 << 31) - 1):
        assert bson.decode(bson.encode({"v": v})) == {"v": v}


def test_bson_rejects_bad_input():
    with pytest.raises(TypeError):
        bson.encode({"x": object()})
    with pytest.raises(ValueError):
        bson.encode({"a\x00b": 1})
    with pytest.raises(ValueError):
        bson.decode(b"\x08\x00\x00\x00\x7fzz\x00")  # unknown type tag


# ------------------------------------------------------- wire + server --

def test_wire_crud_and_range(server):
    c = MongoClient.from_addr(server.addr + "/gametest")
    assert c.ping()
    assert c.insert("ents", [{"_id": "e1", "data": {"hp": 10}}]) == 1
    c.upsert_id("ents", "e1", {"data": {"hp": 11}})   # replace
    c.upsert_id("ents", "e2", {"data": {"hp": 2}})    # insert-by-upsert
    assert c.find_id("ents", "e1")["data"] == {"hp": 11}
    got = c.find("ents", {"_id": {"$gte": "e1", "$lt": "e9"}},
                 sort={"_id": 1})
    assert [d["_id"] for d in got] == ["e1", "e2"]
    assert c.delete("ents", {"_id": "e2"}) == 1
    assert c.find_id("ents", "e2") is None
    # duplicate insert: mongod reports it as ok:1 + writeErrors — the
    # client must RAISE (a swallowed write error would let the
    # retry-forever save queue count a failed save as done)
    c.insert("ents", [{"_id": "dup"}])
    with pytest.raises(MongoError, match="write error"):
        c.insert("ents", [{"_id": "dup"}])
    # unknown command -> MongoError
    with pytest.raises(MongoError):
        c.command({"noSuchCommand": 1})
    c.close()


def test_multi_batch_cursor(server):
    """A real mongod caps an unlimited find's firstBatch at 101 docs;
    minimongo batches the same way, so the client's getMore loop is
    exercised: 250-doc scans must return everything."""
    from goworld_tpu.kvdb import open_kvdb_backend
    from goworld_tpu.storage import open_backend

    c = MongoClient.from_addr(server.addr)
    c.insert("big", [{"_id": f"d{i:04d}"} for i in range(250)])
    got = c.find("big", {})
    assert len(got) == 250
    assert sorted(d["_id"] for d in got) == [f"d{i:04d}"
                                             for i in range(250)]
    c.close()

    b = open_backend("mongodb", server.addr)
    for i in range(205):
        b.write("Npc", f"n{i:04d}", {"i": i})
    assert len(b.list_entity_ids("Npc")) == 205
    b.close()

    kb = open_kvdb_backend("mongodb", server.addr)
    for i in range(150):
        kb.put(f"rk{i:04d}", str(i))
    assert len(kb.get_range("rk", "rl")) == 150
    kb.close()


def test_wire_reconnects(server):
    c = MongoClient.from_addr(server.addr)
    c.insert("t", [{"_id": "a"}])
    c._sock.close()  # sever under the client
    assert c.find_id("t", "a") == {"_id": "a"}
    c.close()


# ---------------------------------------------------------- storage ----

def test_mongodb_storage_backend(server):
    from goworld_tpu.storage import open_backend

    b = open_backend("mongodb", server.addr + "/goworld")
    assert b.read("Avatar", "e1") is None
    assert not b.exists("Avatar", "e1")
    data = {"name": "hero", "hp": 42, "bag": {"gold": 7, "items": [1]}}
    b.write("Avatar", "e1", data)
    assert b.read("Avatar", "e1") == data
    assert b.exists("Avatar", "e1")
    b.write("Avatar", "e1", {"hp": 1})      # UpsertId replaces
    assert b.read("Avatar", "e1") == {"hp": 1}
    b.write("Avatar", "e2", {"name": "alt"})
    b.write("Account", "a1", {"pw": "x"})
    assert b.list_entity_ids("Avatar") == ["e1", "e2"]
    assert b.list_entity_ids("Account") == ["a1"]
    # the reference layout: collection per type, attrs under "data"
    assert server.colls[("goworld", "Avatar")]["e1"] == {
        "_id": "e1", "data": {"hp": 1}}
    b.close()


def test_async_storage_over_mongodb(server):
    from goworld_tpu.storage import Storage, open_backend

    posted = []
    st = Storage(open_backend("mongodb", server.addr), posted.append)
    results = []
    st.save("Avatar", "e9", {"hp": 1}, cb=lambda: results.append("saved"))
    st.load("Avatar", "e9", cb=lambda d: results.append(d))
    deadline = time.monotonic() + 10
    while len(posted) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    for cb in posted:
        cb()
    assert results == ["saved", {"hp": 1}]
    st.shutdown()


# ------------------------------------------------------------- kvdb ----

def test_mongodb_kvdb_backend(server):
    from goworld_tpu.kvdb import open_kvdb_backend

    b = open_kvdb_backend("mongodb", server.addr)
    assert b.get("k") is None
    b.put("k", "v")
    assert b.get("k") == "v"
    b.put("k", "v2")
    assert b.get("k") == "v2"
    for k, v in [("a1", "1"), ("a2", "2"), ("a3", "3"), ("b1", "4")]:
        b.put(k, v)
    assert b.get_range("a1", "a3") == [("a1", "1"), ("a2", "2")]
    assert b.get_range("a", "b") == [
        ("a1", "1"), ("a2", "2"), ("a3", "3")
    ]
    # the reference layout: _id = key, value under "_" in __kv__
    assert server.colls[("goworld", "__kv__")]["a1"] == {
        "_id": "a1", "_": "1"}
    b.close()


# ----------------------------------------------------------- gwmongo ---

def test_gwmongo_over_real_wire(server):
    from goworld_tpu.ext.db.gwmongo import GWMongo
    from goworld_tpu.utils.asyncwork import AsyncWorkers

    posted = []
    m = GWMongo.connect_mongodb(server.addr, AsyncWorkers(posted.append))
    results = {}
    did = m.insert_one("game", "players", {"name": "bo", "lv": 3},
                       cb=lambda r, e: results.setdefault("ins", (r, e)))
    m.find_id("game", "players", did,
              cb=lambda r, e: results.setdefault("find", (r, e)))
    deadline = time.monotonic() + 10
    while len(posted) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    for cb in posted:
        cb()
    assert results["ins"][1] is None
    doc, err = results["find"]
    assert err is None and doc["name"] == "bo" and doc["lv"] == 3
    # documents are NATIVE mongo docs (no msgpack envelope)
    assert server.colls[("goworld", "game.players")][did]["name"] == "bo"
    # the scan path (find_one/count ride store.keys) works over the wire
    posted.clear()
    m.find_one("game", "players", {"name": "bo"},
               cb=lambda r, e: results.setdefault("fo", (r, e)))
    deadline = time.monotonic() + 10
    while len(posted) < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    for cb in posted:
        cb()
    fdoc, ferr = results["fo"]
    assert ferr is None and fdoc["_id"] == did


def test_bson_decoder_never_crashes_on_garbage():
    """Adversarial robustness for the from-scratch BSON decoder:
    random bytes and mutated valid documents must raise a bounded,
    expected error — never hang, never allocate from an
    attacker-controlled length (MemoryError is a FAILURE here: it
    means a bit-flipped int32 drove a huge allocation), and a decode
    that SUCCEEDS must have stayed inside the declared document
    bounds."""
    import random
    import struct

    rng = random.Random(13)
    ok_errors = (ValueError, IndexError, OverflowError,
                 UnicodeDecodeError, struct.error)

    def probe(blob: bytes) -> None:
        try:
            _, end = bson.decode_with_end(blob)
        except ok_errors:
            return
        assert end <= len(blob), "decoder read past the input"

    for _ in range(500):
        probe(bytes(rng.randrange(256)
                    for _ in range(rng.randrange(4, 64))))
    valid = bson.encode({"a": [1, {"b": "cc"}], "d": 2.5, "e": b"xy"})
    for _ in range(400):
        m = bytearray(valid)
        for _ in range(rng.randrange(1, 3)):
            m[rng.randrange(len(m))] ^= 1 << rng.randrange(8)
        probe(bytes(m))
