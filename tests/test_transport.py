"""Gate transport parity (VERDICT #5): compression + TLS on the client
edge, mirroring the reference CI which runs with compression and
encryption ON (goworld_actions.ini; ClientProxy.go:38-53). The KCP
deviation is documented in net/transport.py."""

import asyncio
import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net.botclient import BotClient
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.packet import PacketConnection, new_packet
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.net import snappy as _snappy
from goworld_tpu.ops.aoi import GridSpec

# snappy is the DEFAULT codec for compress=True, so every compressed
# test needs the native core; skip (not error) where it can't build,
# like tests/test_snappy.py does
requires_snappy = pytest.mark.skipif(
    not _snappy.available(), reason="native snappy core failed to build")
_snappy_param = pytest.param("snappy", marks=requires_snappy)


# =======================================================================
# packet-level compression
# =======================================================================
@pytest.mark.parametrize("codec", [_snappy_param, "zlib"])
def test_compressed_packet_roundtrip(codec):
    async def main():
        got = []

        async def handle(reader, writer):
            conn = PacketConnection(reader, writer, compress=True,
                                    compress_codec=codec)
            mt, p = await conn.recv()
            got.append((mt, p.read_var_str(), p.read_data()))
            reply = new_packet(77)
            reply.append_var_str("pong")
            conn.send(reply)
            await conn.drain()
            await conn.close()  # 3.12 Server.wait_closed waits on this

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        conn = PacketConnection(reader, writer, compress=True,
                                compress_codec=codec)
        p = new_packet(42)
        p.append_var_str("hello" * 200)  # compressible payload
        p.append_data({"k": [1, 2, 3]})
        conn.send(p)
        await conn.drain()
        mt, reply = await conn.recv()
        assert mt == 77 and reply.read_var_str() == "pong"
        await conn.close()
        server.close()
        await server.wait_closed()
        assert got == [(42, "hello" * 200, {"k": [1, 2, 3]})]

    asyncio.run(main())


@requires_snappy
def test_compression_mismatch_detected():
    """An uncompressed sender against a compressed receiver must fail
    loudly (bad zlib header), not feed garbage into the packet codec."""
    async def main():
        errs = []

        async def handle(reader, writer):
            conn = PacketConnection(reader, writer, compress=True)
            try:
                await conn.recv()
            except ConnectionError as exc:
                errs.append(str(exc))
            finally:
                await conn.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        plain = PacketConnection(reader, writer)  # no compression
        p = new_packet(42)
        p.append_var_str("hello")
        plain.send(p)
        await plain.drain()
        for _ in range(100):
            if errs:
                break
            await asyncio.sleep(0.02)
        await plain.close()
        server.close()
        await server.wait_closed()
        assert errs and "compressed" in errs[0]

    asyncio.run(main())


class _CaptureWriter:
    def __init__(self):
        self.data = bytearray()

    def write(self, b):
        self.data += b

    async def drain(self):
        pass

    def close(self):
        pass


@requires_snappy
def test_stream_compression_beats_plain_on_hot_path():
    """Per-connection streaming compression must SHRINK a realistic
    client-edge stream (repeated small sync records); per-packet zlib
    would inflate it (fresh header per packet)."""
    import struct

    plain_w, comp_w = _CaptureWriter(), _CaptureWriter()
    plain = PacketConnection(None, plain_w)
    comp = PacketConnection(None, comp_w, compress=True)
    for i in range(200):
        for conn in (plain, comp):
            p = new_packet(3)  # sync-record-shaped payload
            p.append_bytes(b"E" * 16 + struct.pack("<4f", 1.0 * i, 0, 2.0,
                                                   0.5))
            conn.send(p)
    assert len(comp_w.data) < len(plain_w.data), (
        f"compression inflated the stream: {len(comp_w.data)} vs "
        f"{len(plain_w.data)} plain"
    )


@pytest.mark.parametrize("codec", [_snappy_param, "zlib"])
def test_decompression_bomb_rejected(codec):
    """A crafted high-ratio stream must be rejected by the output cap,
    not materialized (gate OOM)."""
    import struct

    async def main():
        if codec == "zlib":
            import zlib as _z

            comp = _z.compressobj(1)
            payload = comp.compress(b"\0" * (64 * 1024 * 1024))
            payload += comp.flush(_z.Z_SYNC_FLUSH)
            match = "too large"
        else:
            from goworld_tpu.net import snappy as _snappy

            payload = _snappy.StreamCompressor().compress(
                b"\0" * (64 * 1024 * 1024))
            match = "size bound"
        assert len(payload) < 32 * 1024 * 1024  # passes the wire check
        reader = asyncio.StreamReader()
        reader.feed_data(struct.pack("<I", len(payload)) + payload)
        reader.feed_eof()
        conn = PacketConnection(reader, _CaptureWriter(), compress=True,
                                compress_codec=codec)
        with pytest.raises(ConnectionError, match=match):
            await conn.recv()

    asyncio.run(main())


# =======================================================================
# full cluster over compressed + TLS transport
# =======================================================================
class Account(Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"

    def Login_Client(self, name):
        avatar = self.world.create_entity(
            "Avatar", space=self.world._test_space, pos=(50.0, 0.0, 50.0)
        )
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


class Avatar(Entity):
    ATTRS = {"name": "allclients", "level": "client"}

    def OnClientConnected(self):
        self.attrs["level"] = 1


class Arena(Space):
    pass


def _cluster(**harness_kwargs):
    """Shared 1-dispatcher/1-gate/1-game bring-up for the transport
    variants; yields (harness, world, game_server) and tears down."""
    harness = ClusterHarness(
        n_dispatchers=1, n_gates=1, desired_games=1,
        position_sync_interval_ms=20, **harness_kwargs,
    )
    harness.start()
    cfg = WorldConfig(
        capacity=128,
        grid=GridSpec(radius=50.0, extent_x=200.0, extent_z=200.0),
        input_cap=128,
    )
    world = World(cfg, n_spaces=1)
    world.register_entity("Account", Account)
    world.register_entity("Avatar", Avatar)
    world.register_space("Arena", Arena)
    world.create_nil_space()
    world._test_space = world.create_space("Arena")
    gs = GameServer(1, world, list(harness.dispatcher_addrs),
                    boot_entity="Account")
    gs.start_network()
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            gs.pump()
            gs.tick()
            time.sleep(0.01)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    assert gs.ready_event.wait(60), "deployment never became ready"
    yield harness, world, gs
    stop.set()
    t.join(timeout=5)
    gs.stop()
    harness.stop()


@pytest.fixture()
def secure_cluster(tmp_path):
    yield from _cluster(compress=True, tls_dir=str(tmp_path))


async def _login_and_walk(bot: BotClient):
    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 10)
        assert bot.player.type_name == "Account"
        bot.call_server("Login_Client", "alice")
        # wait for the avatar handoff
        for _ in range(200):
            if bot.player is not None and bot.player.type_name == "Avatar":
                break
            await asyncio.sleep(0.05)
        assert bot.player.type_name == "Avatar"
        # position syncs flow over the compressed+TLS link
        bot.send_position(60.0, 0.0, 60.0, 1.0)
        for _ in range(200):
            if bot.player.attrs.get("name") == "alice":
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("name") == "alice"
    finally:
        recv.cancel()
        await bot.conn.close()


@requires_snappy
def test_bot_over_compressed_tls(secure_cluster):
    harness, world, gs = secure_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True, compress=True, tls=True)
    harness.submit(_login_and_walk(bot)).result(timeout=40)
    assert not bot.errors, bot.errors
    avatars = [e for e in world.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert len(avatars) == 1 and avatars[0].client is not None


def test_plaintext_bot_rejected_by_tls_gate(secure_cluster):
    """A client skipping TLS can't talk to an encrypted gate."""
    harness, _, _ = secure_cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, compress=True, tls=False)

    async def attempt():
        await bot.connect()
        try:
            await asyncio.wait_for(bot._recv_loop(), 3)
        except (asyncio.TimeoutError, ConnectionError, EOFError):
            return False
        return bot.player is not None

    ok = harness.submit(attempt()).result(timeout=20)
    assert not ok, "plaintext client slipped through a TLS gate"


# =======================================================================
# KCP (reliable-UDP) client edge — reference GateService.go:129-161
# =======================================================================
@pytest.fixture()
def kcp_cluster():
    yield from _cluster(with_kcp=True)


def test_bot_over_kcp(kcp_cluster):
    """Full client flow (boot entity, RPC login, avatar handoff, strict
    attr mirror, position sync) over the reliable-UDP listener."""
    harness, world, gs = kcp_cluster
    host, port = harness.gate_kcp_addrs[0]
    bot = BotClient(host, port, strict=True, kcp=True)
    harness.submit(_login_and_walk(bot)).result(timeout=40)
    assert not bot.errors, bot.errors
    avatars = [e for e in world.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert len(avatars) == 1 and avatars[0].client is not None


def test_bot_swarm_over_kcp(kcp_cluster):
    """A strict bot swarm over the reliable-UDP edge (the reference CI
    drives test_client -kcp against its gates)."""
    from goworld_tpu.net.botclient import run_swarm

    harness, world, gs = kcp_cluster
    host, port = harness.gate_kcp_addrs[0]
    n = 8
    bots = harness.submit(
        run_swarm(host, port, n, 8.0, strict=True, kcp=True)
    ).result(timeout=90)
    errs = [e for b in bots for e in b.errors]
    assert not errs, errs[:5]
    # every bot's boot entity arrived over reliable UDP (this fixture's
    # Account stays in the nil space, so no AOI syncs are expected; the
    # 8 s window absorbs full-suite machine load)
    assert all(b.player is not None for b in bots)
    accounts = [e for e in world.entities.values()
                if e.type_name == "Account" and not e.destroyed]
    assert len(accounts) == n


@pytest.fixture()
def kcp_compressed_cluster():
    yield from _cluster(with_kcp=True, compress=True)


@requires_snappy
def test_bot_over_kcp_with_snappy(kcp_compressed_cluster):
    """Compression composes with the reliable-UDP edge: the gate's KCP
    sessions reuse the TCP client handler, so the snappy stream codec
    must run unchanged over (reader, writer) adapters backed by KCP."""
    harness, world, gs = kcp_compressed_cluster
    host, port = harness.gate_kcp_addrs[0]
    bot = BotClient(host, port, strict=True, kcp=True, compress=True)
    harness.submit(_login_and_walk(bot)).result(timeout=40)
    assert not bot.errors, bot.errors
    avatars = [e for e in world.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert len(avatars) == 1 and avatars[0].client is not None
