"""Quantized state planes (ISSUE 12, GridSpec.precision="q16").

The exactness story is BY CONSTRUCTION, so the tests assert it as
equalities, not tolerances: the lattice step is a power of two and the
cell edge a power-of-two multiple of it, so (1) snapping is idempotent,
(2) the int16-pair distance math equals the f32 math over snapped
positions BIT-FOR-BIT, (3) every sweep impl with precision on equals
the brute-force oracle over the SNAPPED world, and (4) the packed
fast paths (the 2-lane ranges sorted view, the 21-bit-triplet Verlet
cand cache) are bit-identical to the f32 paths over the same snapped
positions. Plus the two delta companions: the sync codec
(net/codec.py) and the snapshot chain (freeze.py — tested in
tests/test_freeze.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

from goworld_tpu.net.codec import DeltaSyncDecoder, DeltaSyncEncoder
from goworld_tpu.ops.aoi import (
    GridSpec,
    grid_neighbors_flags,
    grid_neighbors_verlet,
    init_verlet_cache,
    neighbors_oracle,
    pack_ids21,
    quantize_positions,
    quantize_xz_i32,
    unpack_ids21,
)

pytestmark = pytest.mark.precision

N = 500
EXTENT = 300.0
RADIUS = 25.0
SKIN = 7.5


def _world(seed=5):
    rng = np.random.default_rng(seed)
    pos = np.zeros((N, 3), np.float32)
    pos[:, 0] = rng.random(N) * EXTENT
    pos[:, 2] = rng.random(N) * EXTENT
    alive = rng.random(N) < 0.92
    fb = rng.integers(0, 4, N).astype(np.int32)
    pos2 = pos.copy()
    step = rng.normal(0.0, 1.0, (N, 2)).astype(np.float32)
    step = np.clip(step, -SKIN / 2 + 0.1, SKIN / 2 - 0.1)
    pos2[:, 0] = np.clip(pos[:, 0] + step[:, 0], 0, EXTENT - 1e-3)
    pos2[:, 2] = np.clip(pos[:, 2] + step[:, 1], 0, EXTENT - 1e-3)
    return pos, pos2, alive, fb


POS, POS2, ALIVE, FB = _world()


def _spec(sweep_impl, precision="q16", skin=0.0, **kw):
    return GridSpec(
        radius=RADIUS, extent_x=EXTENT, extent_z=EXTENT,
        k=64, cell_cap=64, row_block=256, sweep_impl=sweep_impl,
        skin=skin, verlet_cap=128, precision=precision, **kw,
    )


def _sets(nbr):
    nbr = np.asarray(nbr)
    return [set(r[r < N].tolist()) for r in nbr]


SPEC_Q = _spec("ranges")
SPOS = np.asarray(quantize_positions(SPEC_Q, jnp.asarray(POS)))
SPOS2 = np.asarray(quantize_positions(SPEC_Q, jnp.asarray(POS2)))
ORACLE_Q = neighbors_oracle(SPOS, ALIVE, RADIUS)
ORACLE_Q2 = neighbors_oracle(SPOS2, ALIVE, RADIUS)


# =======================================================================
# the lattice quantizer itself
# =======================================================================
def test_quant_step_is_power_of_two_and_covers_extent():
    sp = SPEC_Q
    import math

    m, _e = math.frexp(sp.quant_step)
    assert m == 0.5                      # exact power of two
    assert sp.quant_step * (1 << 15) >= EXTENT
    assert sp.quant_step <= RADIUS / 4.0
    # the cell edge is a power-of-two multiple of the step and still
    # covers the reach (the 3x3-window coverage invariant)
    assert sp.cell_size == sp.quant_step * (1 << sp.quant_cell_shift)
    assert sp.cell_size >= sp.radius + sp.skin
    assert sp.quant_bits == 15
    assert _spec("ranges", precision="off").quant_bits == 0


def test_snap_is_idempotent_and_exact():
    snapped = quantize_positions(SPEC_Q, jnp.asarray(POS))
    twice = quantize_positions(SPEC_Q, snapped)
    assert np.array_equal(np.asarray(snapped), np.asarray(twice))
    # y passes through untouched
    assert np.array_equal(np.asarray(snapped)[:, 1], POS[:, 1])
    # every snapped coordinate is an exact lattice multiple
    q = np.asarray(snapped)[:, 0] / SPEC_Q.quant_step
    assert np.array_equal(q, np.round(q))


def test_packed_xz_mirror_distance_equals_f32_over_snapped():
    """The heart of the construction: int16-pair Chebyshev distances
    times the step EQUAL the f32 distances over snapped positions,
    bitwise, for every pair in the world."""
    qxz = np.asarray(quantize_xz_i32(SPEC_Q, jnp.asarray(POS)))
    qx = (qxz >> 16).astype(np.int64)
    qz = (qxz & 0xFFFF).astype(np.int64)
    dint = np.maximum(np.abs(qx[:, None] - qx[None, :]),
                      np.abs(qz[:, None] - qz[None, :]))
    d_from_int = (dint.astype(np.float32)
                  * np.float32(SPEC_Q.quant_step))
    d_f32 = np.maximum(
        np.abs(SPOS[:, 0][:, None] - SPOS[:, 0][None, :]),
        np.abs(SPOS[:, 2][:, None] - SPOS[:, 2][None, :]),
    ).astype(np.float32)
    assert np.array_equal(d_from_int, d_f32)


def test_pack_ids21_roundtrip_lossless():
    rng = np.random.default_rng(0)
    for v in (1, 2, 3, 7, 48, 128):
        ids = rng.integers(0, (1 << 21) - 1, (5, v)).astype(np.int32)
        up = np.asarray(unpack_ids21(pack_ids21(jnp.asarray(ids), N)))
        assert np.array_equal(up[:, :v], ids), v
        assert np.all(up[:, v:] == N)    # pads carry the sentinel


# =======================================================================
# GridSpec validation (loud, construction-time — GridSpec style)
# =======================================================================
def test_precision_validation_messages():
    with pytest.raises(ValueError, match=r"off\|q16"):
        _spec("ranges", precision="fp8")
    with pytest.raises(ValueError, match=r"origin-free"):
        GridSpec(radius=RADIUS, origin_x=10.0, extent_x=EXTENT,
                 extent_z=EXTENT, precision="q16")
    # a lattice coarser than radius/4 (tiny radius over a huge extent)
    # is rejected with the named bound
    with pytest.raises(ValueError, match=r"radius/4"):
        GridSpec(radius=2.0, extent_x=1 << 18, extent_z=1 << 18,
                 precision="q16")
    # the off default constructs exactly as before
    GridSpec(radius=2.0, extent_x=1 << 18, extent_z=1 << 18)


# =======================================================================
# oracle exactness + cross-impl bit parity, precision ON
# =======================================================================
@pytest.mark.parametrize("sort_impl", ["argsort", "counting"])
@pytest.mark.parametrize("sweep_impl", ["table", "ranges", "cellrow",
                                        "shift"])
def test_q16_matrix_matches_snapped_oracle(sweep_impl, sort_impl):
    spec = _spec(sweep_impl, sort_impl=sort_impl)
    nbr, cnt, fl = grid_neighbors_flags(
        spec, jnp.asarray(POS), jnp.asarray(ALIVE),
        flag_bits=jnp.asarray(FB),
    )
    got = _sets(nbr)
    for i in range(N):
        want = ORACLE_Q[i] if ALIVE[i] else set()
        assert got[i] == want, (sweep_impl, sort_impl, i)


def test_q16_ranges_packed_bit_identical_to_table():
    """The packed 2-lane sorted view ("ranges" under q16) must produce
    the same raw arrays as the f32 table impl over the same snapped
    world — same candidates, same exact distances, same keys."""
    outs = {}
    for sweep in ("ranges", "table"):
        nbr, cnt, fl = grid_neighbors_flags(
            _spec(sweep), jnp.asarray(POS), jnp.asarray(ALIVE),
            flag_bits=jnp.asarray(FB),
        )
        outs[sweep] = (np.asarray(nbr), np.asarray(cnt),
                       np.asarray(fl))
    for a, b in zip(outs["ranges"], outs["table"]):
        assert np.array_equal(a, b)


def test_q16_equals_f32_sweep_over_snapped_positions():
    """precision=q16 on raw positions == precision=off on the SNAPPED
    positions, bit-for-bit (same grid geometry pinned via the same
    spec family) — the construction's central equality."""
    sp_q = _spec("ranges")
    nbr_q, cnt_q, fl_q = grid_neighbors_flags(
        sp_q, jnp.asarray(POS), jnp.asarray(ALIVE),
        flag_bits=jnp.asarray(FB),
    )
    # off-spec with the SAME cell geometry: radius grown to the
    # quantized cell edge would change reach; instead run the q16 spec
    # on pre-snapped input — the internal snap is idempotent, so this
    # isolates "who snaps" from "what is computed"
    nbr_s, cnt_s, fl_s = grid_neighbors_flags(
        sp_q, jnp.asarray(SPOS), jnp.asarray(ALIVE),
        flag_bits=jnp.asarray(FB),
    )
    assert np.array_equal(np.asarray(nbr_q), np.asarray(nbr_s))
    assert np.array_equal(np.asarray(cnt_q), np.asarray(cnt_s))
    assert np.array_equal(np.asarray(fl_q), np.asarray(fl_s))


@pytest.mark.parametrize("sort_impl", ["argsort", "counting"])
def test_q16_verlet_rebuild_and_reuse_exact(sort_impl):
    """The packed-cand Verlet path under q16: cold rebuild and a
    legal reuse tick both match the snapped oracle; the reuse tick
    really skipped the front half; gauges stay zero."""
    spec = _spec("ranges", skin=SKIN, sort_impl=sort_impl)
    cache = init_verlet_cache(spec, N)
    assert cache.cand.dtype == jnp.uint32      # 21-bit-packed plane
    nbr, cnt, fl, st, cache, reb, _sl = grid_neighbors_verlet(
        spec, jnp.asarray(POS), jnp.asarray(ALIVE), cache,
        flag_bits=jnp.asarray(FB), with_stats=True,
    )
    assert int(reb) == 1
    got = _sets(nbr)
    for i in range(N):
        want = ORACLE_Q[i] if ALIVE[i] else set()
        assert got[i] == want, ("rebuild", i)
    nbr2, cnt2, fl2, st2, cache, reb2, _sl = grid_neighbors_verlet(
        spec, jnp.asarray(POS2), jnp.asarray(ALIVE), cache,
        flag_bits=jnp.asarray(FB), with_stats=True,
    )
    assert int(reb2) == 0                      # under skin/2: reused
    got2 = _sets(nbr2)
    for i in range(N):
        want = ORACLE_Q2[i] if ALIVE[i] else set()
        assert got2[i] == want, ("reuse", i)
    assert int(st2[1]) == 0 and int(st2[3]) == 0  # both gauges zero


def test_q16_verlet_rebuild_triggers_still_fire():
    """The rebuild cond runs in the snapped domain — alive-set change
    and a past-skin/2 jump must still trip it on the exact tick."""
    spec = _spec("ranges", skin=SKIN)
    cache = init_verlet_cache(spec, N)
    out = grid_neighbors_verlet(spec, jnp.asarray(POS),
                                jnp.asarray(ALIVE), cache,
                                flag_bits=jnp.asarray(FB))
    cache = out[4]
    # alive flip
    alive2 = ALIVE.copy()
    alive2[int(np.flatnonzero(ALIVE)[0])] = False
    out = grid_neighbors_verlet(spec, jnp.asarray(POS),
                                jnp.asarray(alive2), cache,
                                flag_bits=jnp.asarray(FB))
    assert int(out[5]) == 1
    cache = out[4]
    # a teleport-sized jump
    pos3 = POS.copy()
    j = int(np.flatnonzero(alive2)[0])
    pos3[j, 0] = (pos3[j, 0] + EXTENT / 2) % EXTENT
    out = grid_neighbors_verlet(spec, jnp.asarray(pos3),
                                jnp.asarray(alive2), cache,
                                flag_bits=jnp.asarray(FB))
    assert int(out[5]) == 1


# =======================================================================
# whole-tick / World-level exactness (scenario oracle incl. mirrors)
# =======================================================================
@pytest.mark.scenarios
@pytest.mark.parametrize("name", ["flock", "teleport"])
def test_q16_world_passes_scenario_oracle(name):
    """run_scenario's full-contract check (interest == snapped-domain
    brute force, interested_by mirrors, client mirrors from drained
    create/destroy messages) with the precision plane ON — the skin's
    best case (flock) and its worst (teleport) both must hold, with
    the exactness precondition (both overflow gauges zero) intact."""
    from goworld_tpu.scenarios.runner import run_scenario

    rep = run_scenario(
        name, n=96, ticks=12, seed=3, oracle_every=3,
        client_frac=0.2, skin=4.0 if name == "flock" else 0.0,
        grid_kw={"precision": "q16"}, raise_on_mismatch=True,
    )
    assert rep.oracle_ticks_checked > 0
    assert not rep.mismatches


def test_q16_tick_deadbands_sub_step_motion():
    """An entity moving less than one lattice step per tick is CLEAN
    under q16 — no sync records (the delta-sync byte story's device
    half) — while a multi-step mover still syncs."""
    import jax

    from goworld_tpu.core.state import WorldConfig, create_state, spawn
    from goworld_tpu.core.step import TickInputs, make_tick

    grid = GridSpec(radius=30.0, extent_x=256.0, extent_z=256.0,
                    k=16, cell_cap=32, precision="q16")
    cfg = WorldConfig(capacity=64, grid=grid, dt=1.0,
                      adaptive_extract=True)
    st = create_state(cfg, seed=0)
    assert st.vel.dtype == jnp.bfloat16       # the narrow plane
    # two watchers with clients near two movers
    st = spawn(st, 0, pos=(100.0, 0.0, 100.0), has_client=True,
               client_gate=1)
    st = spawn(st, 1, pos=(105.0, 0.0, 100.0), npc_moving=True)
    st = spawn(st, 2, pos=(200.0, 0.0, 200.0), has_client=True,
               client_gate=1)
    st = spawn(st, 3, pos=(205.0, 0.0, 200.0), npc_moving=True)
    # slot 1 crawls at 1/8 lattice step per tick, slot 3 at 4 steps
    step = grid.quant_step
    vel = np.zeros((64, 3), np.float32)
    vel[1, 0] = step / 8.0
    vel[3, 0] = step * 4.0
    st = st.replace(vel=jnp.asarray(vel).astype(st.vel.dtype),
                    npc_moving=st.npc_moving.at[1].set(True)
                    .at[3].set(True))
    tick = make_tick(cfg)
    ins = TickInputs.empty(cfg)
    st, out = tick(st, ins, None)             # spawn-dirty tick
    st, out = jax.jit(tick)(st, ins, None)
    subs = set(np.asarray(out.sync_j)[:int(out.sync_n)].tolist())
    assert 3 in subs                          # the striding mover syncs
    assert 1 not in subs                      # sub-step jitter is clean


# =======================================================================
# the delta-sync codec (wire half)
# =======================================================================
STEP = 2.0 ** -5
_BASE_RNG = np.random.default_rng(11)
_BASE_VALS = (_BASE_RNG.random((16, 4)) * 900).astype(np.float32)


def _lattice_vals(rng, n, t=0):
    """Smooth motion: a fixed base drifting ~3 lattice steps/tick —
    the steady state the delta encoder exists for (a fresh random
    position every tick would be a teleport storm: all keyframes)."""
    vals = _BASE_VALS[:n] + np.float32(t) * np.float32(3 * STEP)
    vals = vals.astype(np.float32)
    vals[:, 0] = np.floor(vals[:, 0] / STEP) * STEP
    vals[:, 2] = np.floor(vals[:, 2] / STEP) * STEP
    return vals


def test_delta_sync_roundtrip_bit_exact_on_lattice():
    rng = np.random.default_rng(1)
    enc = DeltaSyncEncoder(STEP, keyframe_every=8)
    dec = DeltaSyncDecoder()
    cids = np.array([b"c%03d" % (i % 4) for i in range(12)], "S16")
    eids = np.array([b"e%03d" % i for i in range(12)], "S16")
    for t in range(20):
        vals = _lattice_vals(rng, 12, t)
        c2, e2, v2 = dec.decode_batch(
            enc.encode_batch(cids, eids, vals, t))
        assert np.array_equal(c2, cids)
        assert np.array_equal(e2, eids)
        # lattice lanes reconstruct EXACTLY; y/yaw within step/2
        assert np.array_equal(v2[:, 0], vals[:, 0]), t
        assert np.array_equal(v2[:, 2], vals[:, 2]), t
        assert np.max(np.abs(v2[:, 1] - vals[:, 1])) <= STEP / 2 + 1e-5
    # steady state is delta-dominated: wire bytes well under full
    assert enc.stats["wire_bytes"] < 0.55 * enc.stats["full_bytes"]
    assert enc.stats["deltas"] > enc.stats["keyframes"]


def test_delta_sync_keyframe_cadence_and_threshold():
    enc = DeltaSyncEncoder(STEP, keyframe_every=4)
    dec = DeltaSyncDecoder()
    cids = np.array([b"c"], "S16")
    eids = np.array([b"e"], "S16")
    kinds = []
    v = np.zeros((1, 4), np.float32)
    for t in range(9):
        before = enc.stats["keyframes"]
        dec.decode_batch(enc.encode_batch(cids, eids, v, t))
        kinds.append("K" if enc.stats["keyframes"] > before else "D")
    # keyframe at t=0 then every 4 ticks (cadence honored)
    assert kinds == ["K", "D", "D", "D", "K", "D", "D", "D", "K"]
    # an int16-overflow jump forces a keyframe regardless of cadence
    big = v.copy()
    big[0, 0] = 40000.0 * STEP
    before = enc.stats["keyframes"]
    _c, _e, v2 = dec.decode_batch(enc.encode_batch(cids, eids, big, 9))
    assert enc.stats["keyframes"] == before + 1
    assert v2[0, 0] == big[0, 0]


def test_delta_sync_decoder_is_pure_function_of_stream():
    """Two decoders fed the same byte stream agree bit-for-bit; a
    late-joining decoder drops unknown-handle deltas and self-heals
    at the pair's next keyframe."""
    rng = np.random.default_rng(2)
    enc = DeltaSyncEncoder(STEP, keyframe_every=3)
    d1, d2 = DeltaSyncDecoder(), DeltaSyncDecoder()
    cids = np.array([b"c%d" % (i % 2) for i in range(6)], "S16")
    eids = np.array([b"e%d" % i for i in range(6)], "S16")
    stream = [enc.encode_batch(cids, eids, _lattice_vals(rng, 6, t), t)
              for t in range(6)]
    for p in stream:
        o1 = d1.decode_batch(p)
        o2 = d2.decode_batch(p)
        for a, b in zip(o1, o2):
            assert np.array_equal(a, b)
    late = DeltaSyncDecoder()
    n_out = [len(late.decode_batch(p)[0]) for p in stream[4:]]
    assert late.stats["dropped_unknown"] > 0 or n_out[0] == 6
    # after one full cadence every pair has re-keyframed
    p = enc.encode_batch(cids, eids, _lattice_vals(rng, 6, 40), 40)
    assert len(late.decode_batch(p)[0]) == 6


def test_delta_sync_reset_rides_in_band():
    enc = DeltaSyncEncoder(STEP, keyframe_every=64, max_entries=4)
    dec = DeltaSyncDecoder()
    rng = np.random.default_rng(3)
    cids = np.array([b"c%02d" % i for i in range(8)], "S16")
    eids = np.array([b"e%02d" % i for i in range(8)], "S16")
    dec.decode_batch(enc.encode_batch(cids, eids,
                                      _lattice_vals(rng, 8), 0))
    # over max_entries: the next batch resets BOTH sides in-band
    dec.decode_batch(enc.encode_batch(cids, eids,
                                      _lattice_vals(rng, 8, 1), 1))
    assert enc.stats["resets"] == 1
    assert dec.stats["resets"] == 1
    assert dec.stats["dropped_unknown"] == 0   # all re-keyframed


# =======================================================================
# roofline model: the byte claim (acceptance criterion)
# =======================================================================
def test_model_precision_terms_hit_the_byte_target():
    from goworld_tpu.utils.devprof import (
        roofline_model_bytes,
        roofline_model_bytes_multichip,
    )

    def total(kw, n=1 << 20):
        m = roofline_model_bytes(n, kw)
        return sum(m[p] for p in ("aoi", "move", "collect"))

    head = dict(k=32, cell_cap=12, sort_impl="counting",
                sweep_impl="fused", skin=0.0)
    # the ROOFLINE headline config (fused + counting) at 1M: the
    # "~1.5 GB with margin" baseline models ~1.1 GB arithmetic and
    # must drop under 0.8 GB with precision on
    assert total(head) > 1.0e9
    assert total(dict(head, precision="q16")) < 0.8e9
    # the skin-on steady state (~1.5 GB arithmetic) nearly halves
    skin = dict(head, sweep_impl="ranges", skin=4.0)
    assert total(skin) > 1.5e9
    assert total(dict(skin, precision="q16")) < 0.55 * total(skin)
    # modeled ICI halo bytes drop proportionally under q16
    mk = dict(n_dev=8, halo_cap=4096, migrate_cap=256,
              mesh_shape=(4, 2))
    for impl in ("ppermute", "async"):
        mk["halo_impl"] = impl
        off = roofline_model_bytes_multichip(131072, head, mk)
        q = roofline_model_bytes_multichip(
            131072, dict(head, precision="q16"), mk)
        assert q["ici_halo"] < 0.8 * off["ici_halo"], impl
        # the audit stamps both projections
    from goworld_tpu.utils.devprof import roofline_audit_multichip

    audit = roofline_audit_multichip(None, None, 1 << 20, head,
                                     dict(mk, halo_impl="async"))
    byimpl = audit["ici_halo_mb_by_impl"]
    assert {"ppermute", "async", "ppermute_q16", "async_q16"} \
        <= set(byimpl)
    assert byimpl["async_q16"] < byimpl["async"]


def test_bench_precision_ab_smoke():
    """The bench A/B block lands with both measured marginals and the
    modeled 1M claim (the r12 schema's precision_ab contract)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_for_precision_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = bench.precision_ab(4096, ticks=2)
    assert "error" not in out, out
    for k in ("off_ms", "q16_ms", "model_off_gb_1m",
              "model_q16_gb_1m", "pos_scale_bits", "quant_step"):
        assert k in out, (k, out)
    # the resolved-config rows drop; the ROOFLINE headline config
    # (fused + counting) lands the acceptance target at 1M
    assert out["model_q16_gb_1m"] < out["model_off_gb_1m"]
    assert out["model_q16_gb_1m_headline"] < 0.8
    assert out["model_off_gb_1m_headline"] > 1.0
    assert out["pos_scale_bits"] == 15


def test_delta_sync_game_to_gate_wire(monkeypatch):
    """The game flush really ships MT_SYNC_POSITION_YAW_DELTA_ON_
    CLIENTS with a payload the gate-side decoder reconstructs to the
    exact staged records (x/z are lattice values under q16, so the
    roundtrip is bit-exact end to end)."""
    from goworld_tpu.core.state import WorldConfig
    from goworld_tpu.entity import World
    from goworld_tpu.net import proto
    from goworld_tpu.net.game import GameServer

    grid = GridSpec(radius=30.0, extent_x=120.0, extent_z=120.0,
                    precision="q16")
    w = World(WorldConfig(capacity=64, grid=grid, input_cap=64),
              n_spaces=1)
    w.create_nil_space()
    gs = GameServer(1, w, [], sync_delta=True, sync_keyframe_every=4)
    sent = []
    monkeypatch.setattr(gs, "_send",
                        lambda conn, p: sent.append(p))
    monkeypatch.setattr(gs.cluster, "select_by_gate_id",
                        lambda gid: None)
    step = grid.quant_step
    dec = DeltaSyncDecoder()
    for t in range(6):
        x = np.float32(np.floor((10.0 + t) / step) * step)
        z = np.float32(np.floor((20.0 + 2 * t) / step) * step)
        vals = np.array([[x, 1.5, z, 0.25]], np.float32)
        gs._sync_sink(3, [b"c1"], [b"e1"], vals)
        sent.clear()
        gs._flush_sync_out()
        assert len(sent) == 1
        p = sent[0]
        mt = int.from_bytes(bytes(p.buf[0:2]), "little")
        assert mt == proto.MT_SYNC_POSITION_YAW_DELTA_ON_CLIENTS
        gate_id = int.from_bytes(bytes(p.buf[2:4]), "little")
        assert gate_id == 3
        sender = int.from_bytes(bytes(p.buf[4:6]), "little")
        assert sender == 1       # per-game handle space on the wire
        cids, eids, v2 = dec.decode_batch(bytes(memoryview(p.buf)[6:]))
        assert cids[0] == b"c1" and eids[0] == b"e1"
        assert v2[0, 0] == x and v2[0, 2] == z
    enc = gs._sync_encoders[3]
    assert enc.stats["deltas"] > 0 and enc.stats["keyframes"] >= 2


def test_delta_sync_truncated_payload_raises_connection_error():
    """A truncated 1505 payload must surface as ConnectionError (the
    gate handler's drop-one-batch guard), never a raw struct.error
    into the dispatcher read loop."""
    rng = np.random.default_rng(5)
    enc = DeltaSyncEncoder(STEP, keyframe_every=4)
    cids = np.array([b"c"], "S16")
    eids = np.array([b"e"], "S16")
    p = enc.encode_batch(cids, eids, _lattice_vals(rng, 1), 0)
    for cut in (3, len(p) - 5, len(p) - 1):
        with pytest.raises(ConnectionError):
            DeltaSyncDecoder().decode_batch(p[:cut])


def test_snapshot_planes_handle_nonzero_origin():
    """Chain planes are origin-relative: a shifted world's positions
    must roundtrip near themselves, not clamp to the zero corner."""
    from goworld_tpu.freeze import _extract_planes, _inject_planes

    step = 2.0 ** -5
    origin = (-1000.0, -500.0)
    data = {"entities": [
        {"pos": [-900.0, 1.0, -250.0], "yaw": 0.5, "moving": True},
        {"pos": [-1000.0, 0.0, -500.0], "yaw": 0.0, "moving": False},
    ]}
    planes = _extract_planes(data, step, origin)
    out = _inject_planes(data, planes, step, origin)
    assert abs(out["entities"][0]["pos"][0] - (-900.0)) <= step
    assert abs(out["entities"][0]["pos"][2] - (-250.0)) <= step
    assert out["entities"][1]["pos"][0] == -1000.0


def test_malformed_v2_snapshot_is_corrupt_not_keyerror(tmp_path):
    """A v2 record whose msgpack parses but lacks required keys (or
    whose planes are the wrong length) must raise CorruptSnapshotError
    so the restore walk falls back — never a raw KeyError."""
    import msgpack

    from goworld_tpu import freeze

    p = tmp_path / "game1_ckpt_delta.dat"
    p.write_bytes(msgpack.packb(
        {"version": freeze.SNAPSHOT_PLANE_VERSION, "kind": "delta"},
        use_bin_type=True))
    with pytest.raises(freeze.CorruptSnapshotError):
        freeze.read_freeze_file(str(p))
    # a keyframe whose plane bytes don't match its entity count
    p2 = tmp_path / "game1_ckpt_key.dat"
    p2.write_bytes(msgpack.packb({
        "version": freeze.SNAPSHOT_PLANE_VERSION, "kind": "key",
        "quant": {"step": 0.5, "yaw_step": freeze.YAW_STEP},
        "planes": {nm: b"" for nm in
                   ("pos_xz", "pos_y", "yaw", "moving")},
        "plane_crcs": {nm: 0 for nm in
                       ("pos_xz", "pos_y", "yaw", "moving")},
        "host": {"version": 1, "entities": [
            {"id": "x", "attrs": {}}]},
    }, use_bin_type=True))
    with pytest.raises(freeze.CorruptSnapshotError):
        freeze.read_freeze_file(str(p2))


def test_delta_sync_decoder_bounded_under_handle_churn():
    """Decoder state is bounded even though wire handles are never
    reused: past max_entries the oldest-inserted baselines evict, and
    an evicted-but-live pair self-heals at its next keyframe."""
    enc = DeltaSyncEncoder(STEP, keyframe_every=2)
    dec = DeltaSyncDecoder(max_entries=8)
    for t in range(6):
        cids = np.array([b"c%02d_%d" % (i, t) for i in range(4)],
                        "S16")
        eids = np.array([b"e%02d_%d" % (i, t) for i in range(4)],
                        "S16")
        vals = np.zeros((4, 4), np.float32)
        dec.decode_batch(enc.encode_batch(cids, eids, vals, t))
    assert len(dec._base) <= 8
    assert dec.stats["evicted"] > 0
