"""End-to-end single-Space tick: spawn, move, AOI enter/leave, sync records.

Covers the minimal slice of the reference's game loop semantics
(GameService.go:77-190 + Entity.go AOI callbacks + CollectEntitySyncInfos)."""

import jax
import jax.numpy as jnp
import numpy as np

from goworld_tpu.core import (
    SpaceState, TickInputs, WorldConfig, create_state, make_tick,
)
from goworld_tpu.core.state import despawn, spawn
from goworld_tpu.models.npc_policy import init_policy
from goworld_tpu.ops.aoi import GridSpec


def small_cfg(**kw):
    base = dict(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=16, cell_cap=32, row_block=64),
        npc_speed=5.0,
    )
    base.update(kw)
    return WorldConfig(**base)


def test_spawn_enter_leave_cycle():
    cfg = small_cfg()
    tick = make_tick(cfg)
    st = create_state(cfg)
    # two entities in AOI range, one out of range
    st = spawn(st, 0, pos=(50.0, 0, 50.0), has_client=True, client_gate=1)
    st = spawn(st, 1, pos=(55.0, 0, 52.0))
    st = spawn(st, 2, pos=(90.0, 0, 90.0))
    st, out = tick(st, TickInputs.empty(cfg), None)
    enters = {(int(w), int(j)) for w, j in
              zip(np.asarray(out.enter_w)[: int(out.enter_n)],
                  np.asarray(out.enter_j)[: int(out.enter_n)])}
    assert (0, 1) in enters and (1, 0) in enters
    assert not any(2 in p for p in enters)
    assert int(out.leave_n) == 0
    assert int(out.alive_count) == 3

    # teleport entity 1 far away via client input -> leave events
    inp = TickInputs.empty(cfg)
    inp = inp.replace(
        pos_sync_idx=inp.pos_sync_idx.at[0].set(1),
        pos_sync_vals=inp.pos_sync_vals.at[0].set(
            jnp.array([5.0, 0.0, 5.0, 1.0])),
        pos_sync_n=jnp.asarray(1, jnp.int32),
    )
    st, out = tick(st, inp, None)
    leaves = {(int(w), int(j)) for w, j in
              zip(np.asarray(out.leave_w)[: int(out.leave_n)],
                  np.asarray(out.leave_j)[: int(out.leave_n)])}
    assert (0, 1) in leaves and (1, 0) in leaves


def test_sync_records_only_for_clients_watching_dirty():
    cfg = small_cfg()
    tick = make_tick(cfg)
    st = create_state(cfg)
    st = spawn(st, 0, pos=(50.0, 0, 50.0), has_client=True)
    st = spawn(st, 1, pos=(52.0, 0, 50.0), npc_moving=True)  # NPC walks
    st = spawn(st, 2, pos=(54.0, 0, 50.0))                   # static, no client
    st, out = tick(st, TickInputs.empty(cfg), None)  # neighbors established
    st, out = tick(st, TickInputs.empty(cfg), None)
    w = np.asarray(out.sync_w)[: int(out.sync_n)]
    j = np.asarray(out.sync_j)[: int(out.sync_n)]
    assert int(out.sync_n) >= 1
    assert set(w.tolist()) == {0}          # only the client-owner watches
    assert set(j.tolist()) == {1}          # only the mover is reported
    # record carries the mover's fresh position
    vals = np.asarray(out.sync_vals)[0]
    assert np.allclose(vals[:3], np.asarray(st.pos)[1], atol=1e-5)


def test_despawn_removes_from_aoi():
    cfg = small_cfg()
    tick = make_tick(cfg)
    st = create_state(cfg)
    st = spawn(st, 0, pos=(50.0, 0, 50.0))
    st = spawn(st, 1, pos=(52.0, 0, 50.0))
    st, out = tick(st, TickInputs.empty(cfg), None)
    st = despawn(st, 1)
    st, out = tick(st, TickInputs.empty(cfg), None)
    leaves = {(int(w), int(j)) for w, j in
              zip(np.asarray(out.leave_w)[: int(out.leave_n)],
                  np.asarray(out.leave_j)[: int(out.leave_n)])}
    assert (0, 1) in leaves
    assert int(out.alive_count) == 1


def test_attr_dirty_flushed():
    cfg = small_cfg()
    tick = make_tick(cfg)
    st = create_state(cfg)
    st = spawn(st, 0, pos=(10.0, 0, 10.0))
    st = st.replace(
        hot_attrs=st.hot_attrs.at[0, 3].set(99.0),
        attr_dirty=st.attr_dirty.at[0].set(np.uint32(1 << 3)),
    )
    st, out = tick(st, TickInputs.empty(cfg), None)
    assert int(out.attr_n) == 1
    assert int(np.asarray(out.attr_e)[0]) == 0
    assert int(np.asarray(out.attr_i)[0]) == 3
    assert float(np.asarray(out.attr_v)[0]) == 99.0
    assert int(st.attr_dirty[0]) == 0  # cleared after flush


def test_mlp_behavior_compiles_and_moves():
    cfg = small_cfg(behavior="mlp")
    tick = make_tick(cfg)
    st = create_state(cfg)
    for s in range(8):
        st = spawn(st, s, pos=(40.0 + s, 0, 40.0), npc_moving=True)
    policy = init_policy(jax.random.PRNGKey(0))
    p0 = np.asarray(st.pos[:8]).copy()
    for _ in range(20):
        st, out = tick(st, TickInputs.empty(cfg), policy)
    assert not np.allclose(np.asarray(st.pos[:8]), p0)


def test_random_walk_stays_in_bounds():
    cfg = small_cfg()
    tick = make_tick(cfg)
    st = create_state(cfg)
    for s in range(16):
        st = spawn(st, s, pos=(50.0, 0, 50.0), npc_moving=True)
    for _ in range(100):
        st, _ = tick(st, TickInputs.empty(cfg), None)
    pos = np.asarray(st.pos[:16])
    assert (pos[:, 0] >= 0).all() and (pos[:, 0] <= 100.0).all()
    assert (pos[:, 2] >= 0).all() and (pos[:, 2] <= 100.0).all()
