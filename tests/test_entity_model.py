"""Host-side programming model tests: attrs, registry, timers, World.

Mirrors the reference's unit tests for attr tree semantics
(``engine/entity/attr_test.go``), plus integration of the host model with
the device tick (enter/leave hooks, client messages, RPC, migration)."""

import numpy as np
import pytest

from goworld_tpu.core import WorldConfig
from goworld_tpu.entity import (
    Entity, GameClient, ListAttr, MapAttr, Space, World,
)
from goworld_tpu.entity.attrs import make_root
from goworld_tpu.ops.aoi import GridSpec


# ---------------------------------------------------------------------------
# attrs
# ---------------------------------------------------------------------------
class TestAttrs:
    def setup_method(self):
        self.deltas = []
        self.root = make_root(self.deltas.append)

    def test_set_and_journal(self):
        self.root["hp"] = 100
        self.root["name"] = "bob"
        assert self.root.get_int("hp") == 100
        ops = [(d.path, d.op, d.value) for d in self.deltas]
        assert ops == [(("hp",), "set", 100), (("name",), "set", "bob")]

    def test_nested_paths(self):
        bag = self.root.get_map("bag")
        bag["gold"] = 5
        items = bag.get_list("items")
        items.append("sword")
        paths = [d.path for d in self.deltas]
        assert ("bag", "gold") in paths
        assert ("bag", "items") in paths
        assert self.deltas[-1].op == "append"
        assert self.root.to_dict() == {
            "bag": {"gold": 5, "items": ["sword"]}
        }

    def test_reparent_rejected(self):
        m = MapAttr()
        self.root["a"] = m
        with pytest.raises(ValueError):
            self.root["b"] = m

    def test_type_canonicalization(self):
        self.root["f"] = 1.5
        self.root["i"] = np.int64(3) if hasattr(np, "int64") else 3
        assert isinstance(self.root["f"], float)
        self.root["d"] = {"x": 1}
        assert isinstance(self.root["d"], MapAttr)
        self.root["l"] = [1, 2]
        assert isinstance(self.root["l"], ListAttr)

    def test_list_ops(self):
        l = self.root.get_list("l")
        l.append(1)
        l.append(2)
        l.insert(0, 0)
        assert l.to_list() == [0, 1, 2]
        assert l.pop(0) == 0
        assert l.to_list() == [1, 2]
        l[1] = 9
        assert l.to_list() == [1, 9]

    def test_delete_and_filter(self):
        self.root["keep"] = 1
        self.root["drop"] = 2
        del self.root["drop"]
        assert "drop" not in self.root
        assert self.root.to_dict_with_filter(lambda k: k == "keep") == {
            "keep": 1
        }


# ---------------------------------------------------------------------------
# world fixtures
# ---------------------------------------------------------------------------
class Monster(Entity):
    ATTRS = {"hp": "allclients persistent hot:0", "secret": "persistent"}

    def __init__(self):
        super().__init__()
        self.seen: list[str] = []
        self.lost: list[str] = []

    def OnEnterAOI(self, other):
        self.seen.append(other.id)

    def OnLeaveAOI(self, other):
        self.lost.append(other.id)

    def Hit(self, dmg):
        self.attrs["hp"] = self.attrs.get_int("hp") - dmg


class Avatar(Entity):
    ATTRS = {"name": "client persistent", "level": "allclients"}

    def __init__(self):
        super().__init__()
        self.greeted = []

    def Greet_Client(self, text):
        self.greeted.append(text)

    def ServerOnly(self):
        self.greeted.append("server")


class MySpace(Space):
    def __init__(self):
        super().__init__()
        self.entered = []

    def OnEntityEnterSpace(self, entity):
        self.entered.append(entity.id)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def small_world(n_spaces=2, **kw):
    cfg = WorldConfig(
        capacity=64,
        grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                      k=8, cell_cap=32, row_block=64),
    )
    clock = FakeClock()
    w = World(cfg, n_spaces=n_spaces, clock=clock, **kw)
    w.clock = clock
    w.register_entity("Monster", Monster)
    w.register_entity("Avatar", Avatar)
    w.register_space("MySpace", MySpace)
    w.create_nil_space()
    return w


# ---------------------------------------------------------------------------
# world behavior
# ---------------------------------------------------------------------------
class TestWorld:
    def test_create_and_aoi_hooks(self):
        w = small_world()
        sp = w.create_space("MySpace")
        a = sp.create_entity("Monster", pos=(50, 0, 50))
        b = sp.create_entity("Monster", pos=(52, 0, 50))
        far = sp.create_entity("Monster", pos=(5, 0, 5))
        assert sp.entered == [a.id, b.id, far.id]
        assert sp.count_entities("Monster") == 3
        w.tick()
        assert b.id in a.interested_in and a.id in b.interested_in
        assert a.id in b.seen and b.id in a.seen
        assert far.seen == []
        assert np.allclose(a.position, (50, 0, 50))

    def test_client_messages_on_aoi(self):
        w = small_world()
        sp = w.create_space("MySpace")
        av = w.create_entity("Avatar", space=sp, pos=(50, 0, 50),
                             client=None)
        av.set_client(GameClient(1, "client-1", w))
        mon = sp.create_entity("Monster", pos=(51, 0, 50),
                               attrs={"hp": 30})
        w.tick()
        msgs = [m for (_, cid, m) in w.client_messages if cid == "client-1"]
        kinds = [m["type"] for m in msgs]
        assert "create_entity" in kinds
        ce = [m for m in msgs if m["type"] == "create_entity"
              and m["eid"] == mon.id]
        assert ce and ce[0]["attrs"] == {"hp": 30}  # AllClients view only
        # monster moves -> sync record for the watching client
        w.client_messages.clear()
        mon.set_position((52, 0, 50))
        w.tick()
        w.tick()
        syncs = [m for (_, cid, m) in w.client_messages
                 if m["type"] == "sync" and m["eid"] == mon.id]
        assert syncs, "client should receive sync for watched mover"

    def test_attr_sync_audiences(self):
        w = small_world()
        sp = w.create_space("MySpace")
        av = w.create_entity("Avatar", space=sp, pos=(50, 0, 50))
        av.set_client(GameClient(1, "c-av", w))
        mon = sp.create_entity("Monster", pos=(51, 0, 50),
                               attrs={"hp": 30, "secret": 1})
        w.tick()  # establish interest
        w.client_messages.clear()
        mon.attrs["hp"] = 25       # allclients -> watcher sees it
        mon.attrs["secret"] = 2    # persistent only -> nobody sees it
        w.tick()
        attr_msgs = [m for (_, cid, m) in w.client_messages
                     if m["type"] == "attrs"]
        assert len(attr_msgs) == 1
        assert attr_msgs[0]["eid"] == mon.id
        assert attr_msgs[0]["deltas"] == [
            {"path": ["hp"], "op": "set", "value": 25}
        ]

    def test_hot_attr_mirrors_to_device(self):
        w = small_world()
        sp = w.create_space("MySpace")
        mon = sp.create_entity("Monster", pos=(50, 0, 50),
                               attrs={"hp": 30})
        w.tick()
        assert float(w.state.hot_attrs[sp.shard, mon.slot, 0]) == 30.0
        mon.attrs["hp"] = 12
        w.tick()
        assert float(w.state.hot_attrs[sp.shard, mon.slot, 0]) == 12.0

    def test_rpc_permissions(self):
        w = small_world()
        av = w.create_entity("Avatar")
        av.set_client(GameClient(1, "c-1", w))
        w.call(av.id, "Greet_Client", "hi", from_client="c-1")
        w.call(av.id, "ServerOnly", from_client="c-1")  # denied
        w.call(av.id, "ServerOnly")  # server side ok
        w.tick()
        assert av.greeted == ["hi", "server"]

    def test_timers(self):
        w = small_world()
        mon = w.create_entity("Monster", attrs={"hp": 10})
        mon.add_callback(1.0, "Hit", 3)
        tid = mon.add_timer(2.0, "Hit", 1)
        w.clock.t = 1.1
        w.tick()
        assert mon.attrs.get_int("hp") == 7
        w.clock.t = 4.2
        w.tick()  # repeating timer fires once per tick call
        w.clock.t = 6.2
        w.tick()
        assert mon.attrs.get_int("hp") == 5
        mon.cancel_timer(tid)
        w.clock.t = 10.0
        w.tick()
        assert mon.attrs.get_int("hp") == 5

    def test_destroy_releases_slot_after_leave_events(self):
        w = small_world()
        sp = w.create_space("MySpace")
        a = sp.create_entity("Monster", pos=(50, 0, 50))
        b = sp.create_entity("Monster", pos=(51, 0, 50))
        w.tick()
        slot_b = b.slot
        b.destroy()
        assert b.destroyed
        w.tick()  # leave events fire here
        assert b.id in a.lost
        assert b.id not in w.entities
        assert slot_b in w._free[sp.shard]
        assert not bool(w.state.alive[sp.shard, slot_b])

    def test_enter_space_migration_local(self):
        w = small_world(n_spaces=2)
        sp1 = w.create_space("MySpace")
        sp2 = w.create_space("MySpace")
        a = sp1.create_entity("Monster", pos=(50, 0, 50),
                              attrs={"hp": 44})
        w.tick()
        a.enter_space(sp2.id, (10, 0, 10))
        w.tick()
        assert a.space is sp2
        assert a.id in sp2.members and a.id not in sp1.members
        assert bool(w.state.alive[sp2.shard, a.slot])
        assert float(w.state.hot_attrs[sp2.shard, a.slot, 0]) == 44.0
        w.tick()
        assert np.allclose(a.position, (10, 0, 10))

    def test_give_client_to(self):
        w = small_world()
        acct = w.create_entity("Avatar")
        acct.set_client(GameClient(2, "cli-9", w))
        av = w.create_entity("Avatar")
        acct.give_client_to(av)
        assert acct.client is None
        assert av.client is not None and av.client.client_id == "cli-9"
        assert av.client.gate_id == 2

    def test_moving_entity_position_tracks_device(self):
        w = small_world()
        sp = w.create_space("MySpace")
        m = sp.create_entity("Monster", pos=(50, 0, 50), moving=True)
        w.tick()
        w.tick()
        w.tick()
        assert not np.allclose(m.position, (50, 0, 50)), \
            "host position must track the integrated device row"

    def test_attr_set_during_migration_window_is_safe(self):
        """During enter_space's staged window the entity has no device
        row; staged writes must not hit the source slot (now possibly
        another entity's) nor a wrong shard."""
        w = small_world(n_spaces=2)
        sp1 = w.create_space("MySpace")
        sp2 = w.create_space("MySpace")
        a = sp1.create_entity("Monster", pos=(50, 0, 50),
                              attrs={"hp": 5})
        w.tick()
        a.enter_space(sp2.id, (10, 0, 10))
        assert a.slot is None  # no addressable row mid-window
        a.attrs["hp"] = 99     # journaled, not staged to a wrong row
        w.tick()
        assert a.space is sp2 and a.slot is not None
        w.tick()
        assert float(w.state.hot_attrs[sp2.shard, a.slot, 0]) == 99.0

    def test_space_destroy_evicts_members(self):
        w = small_world(n_spaces=2)
        sp = w.create_space("MySpace")
        m = sp.create_entity("Monster", pos=(50, 0, 50))
        w.tick()
        shard = sp.shard
        sp.destroy()
        w.tick()
        # member moved to nil space, its row despawned
        assert m.space is w.nil_space
        assert m.slot is None
        assert int(np.asarray(w.state.alive[shard]).sum()) == 0
        # shard is reusable without ghosts
        sp2 = w.create_space("MySpace")
        assert sp2.shard == shard
        fresh = sp2.create_entity("Monster", pos=(50, 0, 50))
        w.tick()
        w.tick()
        assert fresh.interested_in == set()

    def test_nil_space_is_host_only(self):
        w = small_world()
        e = w.create_entity("Monster")  # defaults into nil space
        assert e.space is w.nil_space
        assert e.slot is None
        w.tick()  # must not crash with host-only entities around


class TestWorldMesh:
    def test_mesh_migration_repoints_entity(self):
        import jax
        from goworld_tpu.parallel import make_mesh

        if jax.device_count() < 8:
            pytest.skip("needs 8 devices")
        cfg = WorldConfig(
            capacity=32,
            grid=GridSpec(radius=10.0, extent_x=100.0, extent_z=100.0,
                          k=8, cell_cap=32, row_block=32),
        )
        clock = FakeClock()
        w = World(cfg, n_spaces=8, mesh=make_mesh(8), clock=clock,
                  migrate_cap=4)
        w.register_entity("Monster", Monster)
        w.register_space("MySpace", MySpace)
        w.create_nil_space()
        spaces = [w.create_space("MySpace") for _ in range(8)]
        a = spaces[0].create_entity("Monster", pos=(50, 0, 50),
                                    attrs={"hp": 7})
        b = spaces[0].create_entity("Monster", pos=(52, 0, 50))
        w.tick()
        assert b.id in a.interested_in
        a.enter_space(spaces[5].id, (20, 0, 20))
        w.tick()
        assert a.space is spaces[5]
        assert a.slot is not None
        assert bool(w.state.alive[5, a.slot])
        assert float(w.state.hot_attrs[5, a.slot, 0]) == 7.0
        w.tick()  # leave events on the old shard fire now
        assert a.id not in b.interested_in
        assert np.allclose(a.position, (20, 0, 20))
        # old slot released
        assert 0 not in w._slot_owner[0] or \
            w._slot_owner[0].get(0) != a.id
