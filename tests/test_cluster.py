"""End-to-end cluster integration: dispatcher + gate + game + bot clients
over real localhost sockets.

Mirrors the reference's de-facto distributed test (``test_game.yml``: start
the cluster, drive it with ``test_client -N ... -strict``) at unit-test
scale: bots log in, get a boot Account, RPC to create an Avatar in a space,
random-walk, and strict-mode mirrors must stay consistent.
"""

import threading
import time

import pytest

from goworld_tpu.core.state import WorldConfig
from goworld_tpu.entity.entity import Entity
from goworld_tpu.entity.manager import World
from goworld_tpu.entity.space import Space
from goworld_tpu.net.botclient import BotClient
from goworld_tpu.net.game import GameServer
from goworld_tpu.net.standalone import ClusterHarness
from goworld_tpu.ops.aoi import GridSpec


class Account(Entity):
    ATTRS = {"status": "client"}

    def OnClientConnected(self):
        self.attrs["status"] = "online"

    def Login_Client(self, name):
        avatar = self.world.create_entity(
            "Avatar", space=self.world._test_space,
            pos=(50.0, 0.0, 50.0),
        )
        avatar.attrs["name"] = name
        self.give_client_to(avatar)
        self.destroy()


class Avatar(Entity):
    ATTRS = {"name": "allclients", "level": "client", "hp": "allclients"}

    def OnClientConnected(self):
        self.attrs["level"] = 1

    def Say_Client(self, text):
        self.call_all_clients("OnSay", self.id, text)


class Arena(Space):
    pass


@pytest.fixture()
def cluster():
    harness = ClusterHarness(
        n_dispatchers=2, n_gates=1, desired_games=1,
        position_sync_interval_ms=20,
    )
    harness.start()

    cfg = WorldConfig(
        capacity=256,
        grid=GridSpec(radius=50.0, extent_x=200.0, extent_z=200.0),
        input_cap=256,
    )
    world = World(cfg, n_spaces=1)
    world.register_entity("Account", Account)
    world.register_entity("Avatar", Avatar)
    world.register_space("Arena", Arena)
    world.create_nil_space()
    world._test_space = world.create_space("Arena")

    gs = GameServer(1, world, list(harness.dispatcher_addrs),
                    boot_entity="Account")
    gs.start_network()

    stop = threading.Event()

    def loop():
        while not stop.is_set():
            gs.pump()
            gs.tick()
            time.sleep(0.01)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    assert gs.ready_event.wait(20), "deployment never became ready"
    yield harness, world, gs
    stop.set()
    t.join(timeout=5)
    gs.stop()
    harness.stop()


def _run_bot(harness, bot: BotClient, duration: float):
    return harness.submit(bot.run(duration))


def test_login_creates_boot_entity_and_avatar(cluster):
    harness, world, gs = cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)

    done = harness.submit(_bot_login_script(bot))
    done.result(timeout=30)

    assert not bot.errors, bot.errors
    # bot saw its Account first, then the Avatar after Login
    assert bot.player is not None
    assert bot.player.type_name == "Avatar"
    assert bot.player.attrs.get("name") == "bob"
    # the server-side avatar exists and owns the client
    avatars = [e for e in world.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert len(avatars) == 1
    assert avatars[0].client is not None


async def _bot_login_script(bot: BotClient):
    import asyncio

    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 10)
        assert bot.player.type_name == "Account"
        # status attr set in OnClientConnected must reach the mirror
        for _ in range(100):
            if bot.player.attrs.get("status") == "online":
                break
            await asyncio.sleep(0.05)
        assert bot.player.attrs.get("status") == "online"
        bot.call_server("Login_Client", "bob")
        # wait for the Avatar handoff
        for _ in range(100):
            if bot.player is not None and bot.player.type_name == "Avatar":
                break
            await asyncio.sleep(0.05)
        assert bot.player is not None
        assert bot.player.type_name == "Avatar"
        for _ in range(100):
            if bot.player.attrs.get("name") == "bob":
                break
            await asyncio.sleep(0.05)
    finally:
        recv.cancel()
        await bot.conn.close()


def test_two_bots_see_each_other_and_sync(cluster):
    harness, world, gs = cluster
    host, port = harness.gate_addrs[0]
    b1 = BotClient(host, port, bot_id=1, strict=True)
    b2 = BotClient(host, port, bot_id=2, strict=True)

    f1 = harness.submit(_bot_play_script(b1, "alice"))
    f2 = harness.submit(_bot_play_script(b2, "bob"))
    f1.result(timeout=40)
    f2.result(timeout=40)

    assert not b1.errors, b1.errors
    assert not b2.errors, b2.errors
    # both avatars spawn at the same point -> each mirror contains the
    # other avatar (AOI enter -> create_entity on client)
    names1 = {e.attrs.get("name") for e in b1.entities.values()
              if e.type_name == "Avatar"}
    assert "bob" in names1, f"alice's mirror: {names1}"
    names2 = {e.attrs.get("name") for e in b2.entities.values()
              if e.type_name == "Avatar"}
    assert "alice" in names2
    # position syncs flowed (b2 moved -> b1 receives records)
    assert b1.sync_count > 0 or b2.sync_count > 0
    # RPC broadcast: alice Say -> both clients got OnSay
    assert any(m == "OnSay" for _, m, _ in b1.rpc_log)
    assert any(m == "OnSay" for _, m, _ in b2.rpc_log)


async def _bot_play_script(bot: BotClient, name: str):
    import asyncio

    await bot.connect()
    recv = asyncio.ensure_future(bot._recv_loop())
    try:
        await asyncio.wait_for(bot.player_ready.wait(), 10)
        bot.call_server("Login_Client", name)
        for _ in range(100):
            if bot.player is not None and bot.player.type_name == "Avatar":
                break
            await asyncio.sleep(0.05)
        assert bot.player is not None and bot.player.type_name == "Avatar"
        # move around for a while
        for i in range(20):
            x, y, z = bot.player.pos
            bot.send_position(x + 1.0, y, z + 1.0, 0.1)
            bot.player.pos = (x + 1.0, y, z + 1.0)
            await asyncio.sleep(0.05)
        if name == "alice":
            bot.call_server("Say_Client", "hello world")
        await asyncio.sleep(1.0)
    finally:
        recv.cancel()
        await bot.conn.close()


def test_client_disconnect_detaches_entity(cluster):
    harness, world, gs = cluster
    host, port = harness.gate_addrs[0]
    bot = BotClient(host, port, strict=True)
    harness.submit(_bot_login_script(bot)).result(timeout=30)
    # bot's connection is closed by the script; the gate notifies the
    # dispatcher which notifies the game
    deadline = time.time() + 10
    while time.time() < deadline:
        avatars = [e for e in world.entities.values()
                   if e.type_name == "Avatar" and not e.destroyed]
        if avatars and avatars[0].client is None:
            break
        time.sleep(0.1)
    avatars = [e for e in world.entities.values()
               if e.type_name == "Avatar" and not e.destroyed]
    assert avatars and avatars[0].client is None


def test_create_space_anywhere_and_kvreg_traverse(cluster):
    """CreateSpaceAnywhere rides the anywhere placement path (reference
    goworld.go) and kvreg.TraverseByPrefix walks the local mirror."""
    harness, world, gs = cluster
    world.register_space("Lobby", Space, use_aoi=False)
    n_before = sum(1 for s in world.spaces.values()
                   if s.type_name == "Lobby")
    gs.create_entity_anywhere("Lobby", None)
    deadline = time.time() + 10
    while time.time() < deadline:
        lobbies = [s for s in world.spaces.values()
                   if s.type_name == "Lobby"]
        if len(lobbies) > n_before:
            break
        time.sleep(0.05)
    assert len(lobbies) == n_before + 1, "space never placed anywhere"

    gs.kvreg_register("Zone/alpha", "1")
    gs.kvreg_register("Zone/beta", "2")
    gs.kvreg_register("Other/x", "9")
    deadline = time.time() + 10
    while time.time() < deadline and len(
        [k for k in gs.kvreg if k.startswith("Zone/")]
    ) < 2:
        time.sleep(0.05)
    seen = []
    gs.kvreg_traverse("Zone/", lambda k, v: seen.append((k, v)))
    assert seen == [("Zone/alpha", "1"), ("Zone/beta", "2")]


def test_nosync_bot_mirrors_without_sending(cluster):
    """-nosync parity: the bot logs in and mirrors entities but never
    sends a position sync upstream (reference test_client -nosync)."""
    import asyncio

    harness, world, gs = cluster
    host, port = harness.gate_addrs[0]
    from goworld_tpu.net.botclient import BotClient

    bot = BotClient(host, port, strict=True, nosync=True)

    sent = []
    orig = bot.send_position
    bot.send_position = lambda *a: sent.append(a) or orig(*a)

    async def script():
        await bot.connect()
        recv = asyncio.ensure_future(bot._recv_loop())
        move = asyncio.ensure_future(bot._move_loop())
        try:
            await asyncio.wait_for(bot.player_ready.wait(), 15)
            await asyncio.sleep(1.0)   # move loop runs; must stay silent
        finally:
            move.cancel()
            recv.cancel()
            await bot.conn.close()

    harness.submit(script()).result(timeout=40)
    assert bot.player is not None
    assert not sent, "nosync bot sent position syncs"
