"""Regression tests for the wire-hardening fixes (ADVICE.md, ISSUE 1
satellites): BSON int32 length validation, minimongo message-size caps
and empty-command guard, kvdb cluster-mode get_range dedup."""

import socket
import struct

import pytest

from goworld_tpu.ext.db import bson
from goworld_tpu.ext.db.minimongo import OP_MSG, MiniMongo


# =======================================================================
# bson: unvalidated int32 lengths
# =======================================================================
def _raw_doc(body: bytes) -> bytes:
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def test_bson_roundtrip_still_works():
    doc = {"a": 1, "s": "x", "b": b"\x00\x01", "n": None, "f": 1.5,
           "l": [1, 2], "d": {"k": "v"}, "t": True, "big": 1 << 40}
    assert bson.decode(bson.encode(doc)) == doc


def test_bson_negative_string_length_raises():
    # pre-fix, n = -1 walked the cursor BACKWARDS and the minimongo
    # handler thread looped forever on the same element
    body = b"\x02a\x00" + struct.pack("<i", -1) + b"\x00"
    with pytest.raises(ValueError):
        bson.decode(_raw_doc(body))


def test_bson_oversized_string_length_raises():
    body = b"\x02a\x00" + struct.pack("<i", 1 << 20) + b"x\x00"
    with pytest.raises(ValueError):
        bson.decode(_raw_doc(body))


def test_bson_negative_binary_length_raises():
    body = b"\x05a\x00" + struct.pack("<i", -5) + b"\x00"
    with pytest.raises(ValueError):
        bson.decode(_raw_doc(body))


def test_bson_document_length_out_of_range():
    with pytest.raises(ValueError):
        bson.decode(struct.pack("<i", 4) + b"\x00")        # total < 5
    with pytest.raises(ValueError):
        bson.decode(struct.pack("<i", 64) + b"\x00" * 16)  # total > buf
    with pytest.raises(ValueError):
        bson.decode(b"\x01\x02")                           # truncated


# =======================================================================
# minimongo: wire message caps + empty command
# =======================================================================
_HDR = struct.Struct("<iiii")


def _op_msg(cmd: dict, rid: int = 1) -> bytes:
    body = struct.pack("<I", 0) + b"\x00" + bson.encode(cmd)
    return _HDR.pack(16 + len(body), rid, 0, OP_MSG) + body


def _recv_exact(s: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = s.recv(n)
        if not b:
            return b"".join(chunks)
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _roundtrip(s: socket.socket, msg: bytes) -> dict:
    s.sendall(msg)
    hdr = _recv_exact(s, 16)
    length = _HDR.unpack(hdr)[0]
    body = _recv_exact(s, length - 16)
    return bson.decode(body, 5)  # skip flags u32 + section kind byte


def test_minimongo_rejects_undersized_length():
    with MiniMongo() as srv:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        s.sendall(_HDR.pack(8, 1, 0, OP_MSG))  # length < 16
        assert s.recv(1) == b""  # connection dropped
        s.close()


def test_minimongo_rejects_oversized_length():
    with MiniMongo() as srv:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        s.sendall(_HDR.pack(49 * 1024 * 1024, 1, 0, OP_MSG))
        assert s.recv(1) == b""
        s.close()


def test_minimongo_empty_command_answers_and_survives():
    with MiniMongo() as srv:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        reply = _roundtrip(s, _op_msg({}))
        assert reply["ok"] == 0.0
        assert reply["code"] == 59
        # the handler thread is still alive: the same connection serves
        reply = _roundtrip(s, _op_msg({"ping": 1, "$db": "goworld"}))
        assert reply["ok"] == 1.0
        s.close()


def test_minimongo_malformed_bson_drops_connection():
    with MiniMongo() as srv:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        # valid framing, negative string length inside the command doc
        body = struct.pack("<I", 0) + b"\x00" + _raw_doc(
            b"\x02a\x00" + struct.pack("<i", -1) + b"\x00"
        )
        s.sendall(_HDR.pack(16 + len(body), 1, 0, OP_MSG) + body)
        assert s.recv(1) == b""
        s.close()


# =======================================================================
# kvdb: cluster-mode get_range dedup across a live slot migration
# =======================================================================
def test_kvdb_cluster_get_range_dedupes_keys():
    from goworld_tpu.ext.db import resp
    from goworld_tpu.kvdb import RedisClusterKVDB

    store = {b"kv:a": b"1", b"kv:b": b"2"}

    class _FakeNode:
        def __init__(self, keys):
            self._keys = keys

        def scan_keys(self, pattern):
            return list(self._keys)

        def command(self, *args):
            assert args[0] == b"MGET"
            return [store.get(k) for k in args[1:]]

    kv = RedisClusterKVDB.__new__(RedisClusterKVDB)
    kv._resp = resp
    # mid-migration: BOTH nodes report key "a" from their SCAN sweep
    kv._clients = {"n1": _FakeNode([b"kv:a", b"kv:b"]),
                   "n2": _FakeNode([b"kv:a"])}
    kv._seed_addrs = ["n1", "n2"]
    kv._slot_map = ["n1" if s % 2 == 0 else "n2" for s in range(16384)]

    out = kv.get_range("a", "z")
    assert out == [("a", "1"), ("b", "2")]  # no duplicate "a"
